"""Replica-to-replica transport: length-prefixed frames over an
in-process loopback (CI gangs both roles in one process) or a TCP
socket (cross-pod, discovered via tpufw.cluster.discovery).

One frame = u32 big-endian length + payload bytes. Payloads are
opaque — page bundles and JSON control messages share the framing.
Stdlib only.
"""

from __future__ import annotations

import queue
import socket
import struct
import time
from typing import Optional, Tuple

#: Frames above this are refused on read — a corrupt length prefix
#: must not allocate unbounded memory (1 GiB covers any real arena's
#: worth of pages with room to spare).
MAX_FRAME = 1 << 30


class TransportError(ConnectionError):
    """Framing violation or closed peer."""


def pack_frame(payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME:
        raise TransportError(f"frame too large ({len(payload)} bytes)")
    return struct.pack(">I", len(payload)) + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise TransportError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(got)
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(pack_frame(payload))


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = struct.unpack(">I", _read_exact(sock, 4))
    if length > MAX_FRAME:
        raise TransportError(f"frame length {length} exceeds cap")
    return _read_exact(sock, length)


class LoopbackTransport:
    """In-process bidirectional frame pipe: ``a`` and ``b`` are the
    two ends, each with send/recv. CI runs a prefill and a decode
    replica in one process over this — same framing code path as TCP,
    no sockets."""

    class _End:
        def __init__(self, out_q: "queue.Queue", in_q: "queue.Queue"):
            self._out = out_q
            self._in = in_q

        def send(self, payload: bytes) -> None:
            # Round-trip through the framing so loopback exercises the
            # same encode/decode path a socket would.
            frame = pack_frame(payload)
            self._out.put(frame)

        def recv(self, timeout: Optional[float] = None) -> bytes:
            try:
                frame = self._in.get(timeout=timeout)
            except queue.Empty:
                raise TransportError("loopback recv timeout") from None
            (length,) = struct.unpack(">I", frame[:4])
            if length != len(frame) - 4:
                raise TransportError("loopback frame length mismatch")
            return frame[4:]

    def __init__(self):
        q_ab: "queue.Queue" = queue.Queue()
        q_ba: "queue.Queue" = queue.Queue()
        self.a = self._End(q_ab, q_ba)
        self.b = self._End(q_ba, q_ab)


class TcpTransport:
    """Client end of a framed TCP connection to a replica."""

    def __init__(self, host: str, port: int, timeout: float = 600.0):
        self.addr = (host, int(port))
        self._sock = socket.create_connection(self.addr, timeout=timeout)
        self._sock.settimeout(timeout)

    def send(self, payload: bytes) -> None:
        send_frame(self._sock, payload)

    def recv(self, timeout: Optional[float] = None) -> bytes:
        if timeout is not None:
            self._sock.settimeout(timeout)
        return recv_frame(self._sock)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def rpc(
    host: str, port: int, payload: bytes, timeout: float = 600.0
) -> Tuple[bytes, float]:
    """One framed request/response round trip on a fresh connection;
    returns ``(reply, rtt_s)``. The measured wall (connect + send +
    remote work + recv) is what request tracing calls the prefill /
    decode rpc stage — the remote subtracts its own engine wall from
    it to expose pure wire time."""
    t0 = time.perf_counter()
    with TcpTransport(host, port, timeout=timeout) as t:
        t.send(payload)
        reply = t.recv()
    return reply, time.perf_counter() - t0


def serve_frames(port: int = 0, host: str = "0.0.0.0"):
    """Minimal framed TCP listener. Returns (socket, bound_port); the
    caller runs :func:`accept_loop` on its own thread with the
    per-frame handler. Kept tiny and synchronous — replica RPCs are
    one-in-one-out."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, int(port)))
    srv.listen(16)
    return srv, srv.getsockname()[1]


def accept_loop(srv: socket.socket, handler) -> None:
    """Serve until the listening socket is closed. One thread per
    connection keeps a slow decode from blocking the next prefill."""
    import threading

    def _conn(conn: socket.socket) -> None:
        with conn:
            conn.settimeout(600.0)
            while True:
                try:
                    frame = recv_frame(conn)
                except (TransportError, OSError):
                    return
                try:
                    reply = handler(frame)
                except Exception as e:  # noqa: BLE001 — report to peer
                    import json

                    reply = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode()
                try:
                    send_frame(conn, reply)
                except (TransportError, OSError):
                    return

    while True:
        try:
            conn, _ = srv.accept()
        except OSError:
            return  # listener closed: shutdown
        threading.Thread(target=_conn, args=(conn,), daemon=True).start()
