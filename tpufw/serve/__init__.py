"""Disaggregated serving subsystem: prefill/decode replica roles, the
page-bundle wire format that migrates KV between them, and the
front-door router that load-balances sessions across replica pools.

The in-process engine (tpufw.workloads.serve) is one replica role
inside this package; ``TPUFW_SERVE_ROLE`` selects which role a
container runs (see tpufw.serve.roles / docs/WORKFLOWS.md).
"""

from tpufw.serve.bundle import (  # noqa: F401
    BundleError,
    decode_bundle,
    encode_bundle,
)
from tpufw.serve.roles import DecodeEngine, PrefillEngine  # noqa: F401
from tpufw.serve.router import RouterPolicy  # noqa: F401
from tpufw.serve.transport import (  # noqa: F401
    LoopbackTransport,
    TcpTransport,
)
