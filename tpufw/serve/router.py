"""Front-door router for disaggregated serving: one HTTP endpoint in
front of replicated prefill/decode pools.

The router holds no model state — it never imports jax. Per request it

1. stamps the request into a per-tenant weighted-fair queue (virtual
   finish times: a tenant with weight 2 drains twice as fast as a
   weight-1 tenant under contention, and an idle tenant's backlog
   never starves others),
2. runs admission control against the DECODE pools' page arenas — the
   scarce resource in disaggregated serving is decode residency, so a
   request whose page footprint fits no replica is rejected up front
   with 429 + Retry-After instead of queueing into a stall,
3. picks replicas: sticky session→decode-replica affinity (a session's
   later turns land where its prefix pages already live), least-loaded
   otherwise, and forwards prompt → prefill → page bundle → decode.

Replica load signals are the ones the replicas already export —
pages_in_use / pages_total and slots_active / slots_total from the
arena, plus whatever goodput/MFU/HBM-headroom gauges ride in the
signals dict (``ReplicaState.score`` folds them in when present).
Snapshots refresh from every decode response and from explicit signal
probes, so the policy always ranks against recent truth without a
polling thread.

``RouterPolicy`` and ``WeightedFairQueue`` are pure (no sockets, no
clocks) — tests/test_router.py drives them directly.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpufw.obs import events as obs_events
from tpufw.obs import reqtrace
from tpufw.obs import slo as obs_slo
from tpufw.obs import trace as obs_trace
from tpufw.obs.registry import Registry as ObsRegistry
from tpufw.serve import transport
from tpufw.serve.bundle import (
    MAGIC,
    chunk_digests,
    drop_session,
    load_session,
    peek_trace,
)
from tpufw.workloads.env import env_float, env_int, env_str

DEFAULT_ROUTER_PORT = 8478

# http: serves

#: Signal-dict keys copied verbatim into a ReplicaState snapshot.
_SIGNAL_KEYS = (
    "pages_total", "pages_in_use", "slots_total", "slots_active",
    "migrations", "goodput_ratio", "mfu", "hbm_headroom_bytes",
    "spec_k", "spec_passes",
    "prefill_chunk_pages", "prefill_inflight", "prefill_chunks",
    "piggyback_waterline",
    # KV fabric: drain state, prefix-cache hit counters, spill-tier
    # occupancy, and the advertised trie digests the affinity hash
    # steers on (the one non-numeric signal — fleet's numeric-only
    # series collection skips it by type).
    "draining", "sessions_drained", "sessions_resumed",
    "prefix_hits", "prefix_misses",
    "spill_ram_pages", "spill_dir_pages",
    "spill_pages_total", "spill_restored_total",
    "prefix_digests",
)


@dataclass
class ReplicaState:
    """Point-in-time load snapshot of one replica, as the policy sees
    it. Page/slot occupancy is the primary signal; the optional
    goodput/MFU fields (PR 9's exports) break ties when present."""

    name: str
    role: str
    pages_total: int = 0
    pages_in_use: int = 0
    slots_total: int = 0
    slots_active: int = 0
    migrations: int = 0
    goodput_ratio: Optional[float] = None
    mfu: Optional[float] = None
    hbm_headroom_bytes: Optional[float] = None
    # Speculative decode replicas advertise their draft depth and pass
    # count; health() surfaces both so an operator can see which pool
    # is speculating (and that its verify passes are advancing).
    spec_k: int = 0
    spec_passes: int = 0
    # Chunked-prefill replicas advertise their chunk size and in-
    # flight chunked admissions; piggyback-capable decode replicas
    # additionally advertise their spare-capacity waterline. The
    # policy steers between the dedicated-prefill and piggyback paths
    # on these (score() and piggyback_fits()).
    prefill_chunk_pages: int = 0
    prefill_inflight: int = 0
    prefill_chunks: int = 0
    piggyback_waterline: float = 0.0
    # KV fabric: a draining replica (SIGTERM / scale-in) refuses new
    # work and is leaving rotation; prefix_digests is its advertised
    # resident-or-spilled trie coverage (cumulative chunk digests,
    # tpufw.serve.bundle.chunk_digests) the affinity hash steers on.
    draining: int = 0
    sessions_drained: int = 0
    sessions_resumed: int = 0
    prefix_hits: int = 0
    prefix_misses: int = 0
    spill_ram_pages: int = 0
    spill_dir_pages: int = 0
    spill_pages_total: int = 0
    spill_restored_total: int = 0
    prefix_digests: Tuple[str, ...] = ()
    healthy: bool = True
    last_seen: float = 0.0

    @property
    def free_pages(self) -> int:
        return max(0, self.pages_total - self.pages_in_use)

    @property
    def load(self) -> float:
        return self.pages_in_use / max(1, self.pages_total)

    def score(self) -> float:
        """Lower is better. Page occupancy dominates; a replica
        burning slots on wasted work (low goodput) or out of HBM
        headroom ranks behind an equally-occupied healthy one."""
        s = self.load + 0.1 * (self.slots_active / max(1, self.slots_total))
        # Prefill-chunk occupancy: each in-flight chunked prefill is a
        # whole prompt's worth of pending compute that page occupancy
        # does not yet show (chunked admission grabs pages lazily).
        s += 0.02 * self.prefill_inflight
        if self.goodput_ratio is not None:
            s += 0.05 * (1.0 - min(1.0, max(0.0, self.goodput_ratio)))
        if self.hbm_headroom_bytes is not None and self.hbm_headroom_bytes <= 0:
            s += 1.0
        return s

    def update(self, signals: Dict[str, Any], now: float = 0.0) -> None:
        # wire: consumes role-signals via signals
        role = signals.get("role")
        if role is not None and role != self.role:
            # A replica answering with the wrong role means this
            # address points at the wrong pool (mis-wired discovery
            # or a swapped port): routing to it would splice bundles
            # into the wrong arena. Take it out of rotation instead
            # of folding its numbers into the policy.
            self.healthy = False
            self.last_seen = now
            return
        for k in _SIGNAL_KEYS:
            # tpulint: disable=TPU015 — goodput_ratio / mfu /
            # hbm_headroom_bytes are ROADMAP item 4's forward
            # contract: no replica exports them yet, but the policy
            # folds them in the moment one does (score() above).
            v = signals.get(k)
            if v is not None:
                setattr(self, k, v)
        self.healthy = True
        self.last_seen = now


class WeightedFairQueue:
    """Virtual-time weighted fair queueing over tenants.

    ``push`` stamps an item with a virtual finish time
    ``max(global_vt, tenant_last_finish) + cost / weight``; ``pop``
    returns the earliest finish and advances global virtual time to
    it. Equal-cost streams from tenants with weights 2:1 therefore
    drain 2:1 under contention, and a tenant that went idle re-enters
    at the current virtual time instead of burning its saved-up
    backlog ahead of everyone."""

    def __init__(
        self,
        weights: Optional[Dict[str, float]] = None,
        default_weight: float = 1.0,
    ):
        self._weights = dict(weights or {})
        self._default = float(default_weight)
        self._vt = 0.0
        self._finish: Dict[str, float] = {}
        self._heap: List[Tuple[float, int, str, Any]] = []
        self._seq = 0
        # Per-tenant queued count. Entries persist at 0 after a tenant
        # drains so its gauge series keeps reporting 0 instead of
        # vanishing (absent-series vs zero, same rationale as the
        # pre-initialized counters in tpufw.obs.registry).
        self._depth: Dict[str, int] = {}

    def weight(self, tenant: str) -> float:
        return max(1e-9, float(self._weights.get(tenant, self._default)))

    def push(self, tenant: str, cost: float, item: Any) -> float:
        start = max(self._vt, self._finish.get(tenant, 0.0))
        fin = start + float(cost) / self.weight(tenant)
        self._finish[tenant] = fin
        heapq.heappush(self._heap, (fin, self._seq, tenant, item))
        self._seq += 1
        self._depth[tenant] = self._depth.get(tenant, 0) + 1
        return fin

    def pop(self) -> Any:
        fin, _, tenant, item = heapq.heappop(self._heap)
        self._vt = max(self._vt, fin)
        self._depth[tenant] = max(0, self._depth.get(tenant, 1) - 1)
        return item

    def depths(self) -> Dict[str, int]:
        """Per-tenant queued counts (drained tenants stay at 0)."""
        return dict(self._depth)

    def __len__(self) -> int:
        return len(self._heap)


class RouterPolicy:
    """Pure routing decisions: WFQ ordering, replica choice, and
    admission. Holds the session→decode-replica affinity map but no
    I/O — the server layer feeds it snapshots and forwards bytes."""

    def __init__(
        self,
        *,
        tenant_weights: Optional[Dict[str, float]] = None,
        saturation: float = 0.95,
        retry_after_s: int = 5,
        affinity_k: int = 0,
    ):
        self.queue = WeightedFairQueue(tenant_weights)
        self.saturation = float(saturation)
        self.retry_after_s = int(retry_after_s)
        #: Prefix-affinity depth: hash the first k page-aligned chunks
        #: of each prompt (tpufw.serve.bundle.chunk_digests) and steer
        #: to the replica already advertising them. 0 = occupancy only.
        self.affinity_k = max(0, int(affinity_k))
        #: Picks won by a nonzero digest match (the server mirrors the
        #: delta into tpufw_router_prefix_affinity_hits_total).
        self.affinity_hits = 0
        self._affinity: Dict[str, str] = {}

    # ---- replica choice -------------------------------------------

    @staticmethod
    def affinity_depth(
        r: ReplicaState, digests: Sequence[str]
    ) -> int:
        """Deepest chunk index (1-based) of ``digests`` this replica
        advertises. Digests are cumulative (digest i covers chunks
        0..i), so the deepest match is exactly the prefix the replica
        can serve from its trie or spill tier without recompute."""
        if not digests or not r.prefix_digests:
            return 0
        have = set(r.prefix_digests)
        depth = 0
        for i, d in enumerate(digests):
            if d in have:
                depth = i + 1
        return depth

    def pick_prefill(
        self,
        replicas: Sequence[ReplicaState],
        digests: Sequence[str] = (),
    ) -> Optional[str]:
        ok = [r for r in replicas if r.healthy and not r.draining]
        if not ok:
            return None
        best = min(
            ok,
            key=lambda r: (
                -self.affinity_depth(r, digests), r.score(), r.name
            ),
        )
        if self.affinity_depth(best, digests) > 0:
            self.affinity_hits += 1
        return best.name

    def decode_fits(self, r: ReplicaState, n_pages: int) -> bool:
        """Can this decode replica take a bundle of ``n_pages`` now —
        a free slot, the pages themselves, and room under the
        saturation waterline (the headroom that keeps in-flight rows'
        decode growth from hitting a full arena)."""
        if not r.healthy or r.draining:
            return False
        if r.slots_active >= max(1, r.slots_total):
            return False
        if n_pages > r.free_pages:
            return False
        return (r.pages_in_use + n_pages) <= self.saturation * max(
            1, r.pages_total
        )

    def pick_decode(
        self,
        session: str,
        replicas: Sequence[ReplicaState],
        n_pages: int,
        digests: Sequence[str] = (),
    ) -> Tuple[Optional[str], str]:
        """(replica_name, "") or (None, reject_reason). A session
        sticks to its previous decode replica while that replica can
        still take it — its earlier turns' pages (and any prefix
        reuse downstream) live there — and is re-homed, not failed,
        when the replica is gone or full. Session stickiness beats
        prefix affinity (the session's OWN pages out-rank a shared
        prefix); among the rest, the deepest digest match wins and
        occupancy score breaks ties."""
        by_name = {r.name: r for r in replicas}
        if session:
            pinned = self._affinity.get(session)
            if pinned is not None:
                r = by_name.get(pinned)
                if r is not None and self.decode_fits(r, n_pages):
                    return pinned, ""
        fits = [r for r in replicas if self.decode_fits(r, n_pages)]
        if not fits:
            return None, "saturated"
        best = min(
            fits,
            key=lambda r: (
                -self.affinity_depth(r, digests), r.score(), r.name
            ),
        )
        if self.affinity_depth(best, digests) > 0:
            self.affinity_hits += 1
        name = best.name
        if session:
            self._affinity[session] = name
        return name, ""

    def piggyback_fits(self, r: ReplicaState, n_pages: int) -> bool:
        """Can this decode replica take a RAW prompt of ``n_pages``
        (prompt + budget) chunk-by-chunk right now — chunked prefill
        enabled, a free slot, and spare pages still clearing its
        advertised waterline AFTER this row's full need. Mirrors the
        replica's own ``submit_raw`` admission test (minus the
        in-flight piggyback deficits only the replica can see — it
        re-checks and refuses, and the router falls back)."""
        if not r.healthy or r.draining or r.role != "decode":
            return False
        if not (r.prefill_chunk_pages and r.piggyback_waterline > 0):
            return False
        if r.slots_active >= max(1, r.slots_total):
            return False
        return (
            r.free_pages - n_pages
            >= r.piggyback_waterline * max(1, r.pages_total)
        )

    def pick_piggyback(
        self,
        replicas: Sequence[ReplicaState],
        n_pages: int,
        max_chunks: Optional[int] = None,
        digests: Sequence[str] = (),
    ) -> Optional[str]:
        """Least-loaded decode replica with piggyback headroom, or
        None when no replica clears its waterline.

        ``max_chunks`` bounds how much prefill work piggybacking may
        divert: with a healthy dedicated prefill pool the router only
        piggybacks prompts a decode replica can absorb in that many
        spare-capacity chunk passes (long prompts would turn the
        decode replica into a worse prefill replica and starve its
        decode slots). With NO dedicated path (``None``) any size
        that clears the waterline goes — fungibility is then the only
        way to serve at all."""
        fits = [
            r for r in replicas
            if self.piggyback_fits(r, n_pages)
            and (
                max_chunks is None
                or n_pages <= r.prefill_chunk_pages * max_chunks
            )
        ]
        if not fits:
            return None
        best = min(
            fits,
            key=lambda r: (
                -self.affinity_depth(r, digests), r.score(), r.name
            ),
        )
        if self.affinity_depth(best, digests) > 0:
            self.affinity_hits += 1
        return best.name

    def pin_session(self, session: str, name: str) -> None:
        """Record decode affinity for a replica chosen outside
        ``pick_decode`` (the piggyback path)."""
        if session:
            self._affinity[session] = name

    def forget_session(self, session: str) -> None:
        self._affinity.pop(session, None)


class _Metrics:
    """Router metrics on the shared ``tpufw.obs`` registry — same
    wrapper shape as the serving endpoint's (short names at call
    sites, prefix applied here, counters pre-initialized to 0 so
    increase() alerts see a real zero series)."""

    PREFIX = "tpufw_router_"

    def __init__(self, registry: Optional[ObsRegistry] = None):
        self.registry = registry if registry is not None else ObsRegistry()
        self.register(
            "requests_total",
            "rejects_total",
            "proxy_errors_total",
            "request_seconds_total",
            "piggyback_total",
            "deferred_total",
            "tokens_total",
            "prefix_affinity_hits_total",
            "session_rehomes_total",
            "replica_changes_total",
        )

    def inc(self, name: str, v: float = 1.0, **labels) -> None:
        self.registry.counter(self.PREFIX + name).inc(v, **labels)

    def register(self, *names: str) -> None:
        for name in names:
            self.registry.counter(self.PREFIX + name)

    def set_gauge(self, name: str, v: float, **labels) -> None:
        self.registry.gauge(self.PREFIX + name).set(float(v), **labels)

    def render(self, gauges: Dict[str, float]) -> str:
        for name, v in gauges.items():
            self.registry.gauge(self.PREFIX + name).set(float(v))
        return self.registry.render()


# ---------------------------------------------------- replica clients

class LocalReplica:
    """In-process replica client wrapping an engine directly — CI
    gangs one prefill + one decode + the router in a single process
    through these (scripts/router_smoke.py)."""

    def __init__(self, name: str, engine):
        self.name = name
        self._engine = engine

    def signals(self) -> Dict[str, Any]:
        return self._engine.signals()

    def prefill(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> bytes:
        return self._engine.prefill(
            prompt, max_new, trace=trace, session=session
        )

    def decode(self, bundle: bytes) -> Dict[str, Any]:
        slot = self._engine.submit(bundle)
        out = self._engine.collect_ex(slot)
        return {**out, **self._engine.signals()}

    def decode_raw(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> Dict[str, Any]:
        slot = self._engine.submit_raw(
            prompt, max_new, trace=trace, session=session
        )
        out = self._engine.collect_ex(slot)
        return {**out, **self._engine.signals()}

    def drain(self) -> Dict[str, Any]:
        """Session-safe scale-in, same contract as TcpReplica.drain —
        the executor drains through the client so LocalReplica and
        TcpReplica gangs scale in identically."""
        fn = getattr(self._engine, "drain", None)
        if callable(fn):
            return fn()
        return {"draining": True, "exported": [], "dropped": 0}


class TcpReplica:
    """Framed-TCP replica client (one connection per call — replica
    RPCs are one-in-one-out and rare relative to their cost)."""

    def __init__(self, name: str, host: str, port: int, role: str):
        self.name = name
        self.role = role
        self._addr = (host, int(port))
        #: Round-trip wall of the most recent _call — request tracing
        #: subtracts the replica's self-reported engine wall from it
        #: to expose pure serialization + wire time.
        self.last_rtt_s = 0.0

    def _call(self, payload: bytes) -> bytes:
        reply, self.last_rtt_s = transport.rpc(*self._addr, payload)
        return reply

    def signals(self) -> Dict[str, Any]:
        # wire: produces control-frame
        reply = self._call(json.dumps({"signals": True}).encode())
        return json.loads(reply.decode("utf-8"))

    def drain(self) -> Dict[str, Any]:
        """Ask the replica to export its live sessions to the spill
        store and refuse new work — the programmatic scale-in hook
        (manifest 13's preStop runs exactly this against localhost)."""
        # wire: produces control-frame
        reply = self._call(json.dumps({"drain": True}).encode())
        return json.loads(reply.decode("utf-8"))

    def prefill(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> bytes:
        # wire: produces control-frame via req
        req = {"prompt": list(prompt), "max_new": int(max_new)}
        if trace:
            req["trace"] = str(trace)
        if session:
            req["session"] = str(session)
        reply = self._call(json.dumps(req).encode())
        if reply[:4] != MAGIC:
            err = json.loads(reply.decode("utf-8"))
            raise RuntimeError(f"prefill {self.name}: {err.get('error')}")
        return reply

    def decode(self, bundle: bytes) -> Dict[str, Any]:
        out = json.loads(self._call(bundle).decode("utf-8"))
        if "error" in out:
            raise RuntimeError(f"decode {self.name}: {out['error']}")
        return out

    def decode_raw(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> Dict[str, Any]:
        # wire: produces control-frame via req
        req = {"prompt": list(prompt), "max_new": int(max_new)}
        if trace:
            req["trace"] = str(trace)
        if session:
            req["session"] = str(session)
        out = json.loads(
            self._call(json.dumps(req).encode()).decode("utf-8")
        )
        if "error" in out:
            raise RuntimeError(f"decode {self.name}: {out['error']}")
        return out


# ------------------------------------------------------- HTTP server

class RouterServer:
    """The front door: POST /generate, GET /healthz, GET /metrics.

    Dispatch order is the WFQ's; ``max_inflight`` requests proxy
    concurrently and completions pump the queue. Decode snapshots
    refresh from every decode response, so saturation decisions track
    the arenas without a polling loop."""

    def __init__(
        self,
        prefill: Sequence[Any],
        decode: Sequence[Any],
        *,
        policy: Optional[RouterPolicy] = None,
        port: int = 0,
        page: int = 16,
        max_inflight: int = 4,
        events=None,
        registry: Optional[ObsRegistry] = None,
        tracer=None,
        slo=None,
        spill_dir: str = "",
    ):
        self._prefill = list(prefill)
        self._decode = list(decode)
        self.policy = policy if policy is not None else RouterPolicy()
        self.page = max(1, int(page))
        self.max_inflight = max(1, int(max_inflight))
        #: Shared session store (TPUFW_KV_SPILL_DIR): when a decode
        #: replica drains mid-request, its exported session bundles
        #: land here and the router re-homes the request to a
        #: surviving replica instead of failing it.
        self.spill_dir = str(spill_dir or "")
        self._metrics = _Metrics(registry)
        self._events = events if events is not None else obs_events.NULL
        self._tracer = tracer if tracer is not None else obs_trace.NULL
        # SLO accounting always rides the request path (the judging is
        # a few clock reads); the tpufw_slo_* series land in the same
        # registry /metrics renders.
        self.slo = (
            slo
            if slo is not None
            else obs_slo.SloTracker.from_env(
                self._metrics.registry, self._events
            )
        )
        self._lock = threading.Lock()
        self._inflight = 0  # resource: counter inflight-credit
        self._last_reprobe = time.monotonic()
        self._states: Dict[str, ReplicaState] = {}
        for client in self._prefill:
            self._states[client.name] = ReplicaState(client.name, "prefill")
        for client in self._decode:
            self._states[client.name] = ReplicaState(client.name, "decode")
        self._refresh_all()

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet access log
                pass

            def _reply(self, code: int, obj: dict, headers=()):
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, server.health())
                elif self.path == "/metrics":
                    text = server.render_metrics().encode("utf-8")
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path not in ("/generate", "/replicas"):
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n).decode("utf-8"))
                except (ValueError, UnicodeDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                if self.path == "/replicas":
                    code, obj = server.replicas_api(req)
                    self._reply(code, obj)
                    return
                code, obj, headers = server.generate(
                    req,
                    trace_header=self.headers.get(reqtrace.HEADER, ""),
                )
                self._reply(code, obj, headers)

        self.httpd = ThreadingHTTPServer(("0.0.0.0", int(port)), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    # ---- state ----------------------------------------------------

    def _refresh_all(self) -> None:
        for client in self._prefill + self._decode:
            try:
                sig = client.signals()
            except Exception:  # noqa: BLE001 — probe failure = unhealthy
                self._states[client.name].healthy = False
                continue
            self._states[client.name].update(sig, now=time.monotonic())

    #: Seconds between opportunistic re-probes of unhealthy replicas.
    REPROBE_INTERVAL_S = 2.0

    def _reprobe_unhealthy(self, force: bool = False) -> None:
        """Second chance for replicas a failed call took out of
        rotation: a live ``signals()`` probe puts them back. Without
        this, one transient error removes a replica forever. Runs at
        most once per interval unless forced (no pickable replica
        left, so a probe is cheaper than a spurious 429/503)."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_reprobe < self.REPROBE_INTERVAL_S:
                return
            self._last_reprobe = now
            down = [
                c for c in self._prefill + self._decode
                if not self._states[c.name].healthy
            ]
        for client in down:
            try:
                sig = client.signals()
            except Exception:  # noqa: BLE001 — still down
                continue
            with self._lock:
                self._states[client.name].update(sig, now=time.monotonic())

    def _snapshot(self, role: str) -> List[ReplicaState]:
        with self._lock:
            return [
                ReplicaState(**vars(r))
                for r in self._states.values()
                if r.role == role
            ]

    # ---- elastic membership ---------------------------------------

    def add_replica(self, client, role: str) -> dict:
        """Register a replica client into a pool at runtime — the
        scale-out half of the closed loop (tpufw.load.GangExecutor
        and the POST /replicas surface both land here). The probe
        runs outside the lock; a replica that cannot answer signals
        still registers, just unhealthy (the reprobe path gives it
        its second chance, same as a startup straggler)."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        sig = None
        try:
            sig = client.signals()
        except Exception:  # noqa: BLE001 — probe failure = unhealthy
            pass
        with self._lock:
            if client.name in self._states:
                raise ValueError(
                    f"replica name {client.name!r} already registered"
                )
            pool = self._prefill if role == "prefill" else self._decode
            pool.append(client)
            state = ReplicaState(client.name, role)
            self._states[client.name] = state
            if sig is None:
                state.healthy = False
            else:
                state.update(sig, now=time.monotonic())
        self._metrics.inc("replica_changes_total", role=role, op="add")
        return {"name": client.name, "role": role,
                "healthy": sig is not None}

    def remove_replica(self, name: str, *, drain: bool = True) -> dict:
        """Deregister a replica — session-safe scale-in. The drain
        call (exports live sessions to the spill store, PR 19) runs
        BEFORE the membership change and outside the lock, so
        in-flight requests on other threads still see the replica
        while it exports; the last replica of a role is refused, the
        door stays open."""
        with self._lock:
            state = self._states.get(name)
            if state is None:
                raise KeyError(f"no replica named {name!r}")
            role = state.role
            pool = self._prefill if role == "prefill" else self._decode
            if sum(1 for s in self._states.values()
                   if s.role == role) <= 1:
                raise ValueError(
                    f"refusing to remove last {role} replica {name!r}"
                )
            client = next(c for c in pool if c.name == name)
            # Draining replicas stop winning _pick while the export
            # runs; membership is surgically removed after.
            state.draining = 1
        drained: dict = {}
        if drain:
            fn = getattr(client, "drain", None)
            if callable(fn):
                try:
                    drained = fn()
                except Exception as e:  # noqa: BLE001
                    drained = {"error": f"{type(e).__name__}: {e}"}
        with self._lock:
            pool = self._prefill if role == "prefill" else self._decode
            if client in pool:
                pool.remove(client)
            self._states.pop(name, None)
        self._metrics.inc(
            "replica_changes_total", role=role, op="remove"
        )
        return {"name": name, "role": role, "drained": drained}

    def replicas_api(self, req: dict) -> Tuple[int, dict]:
        """POST /replicas — the out-of-process executor surface.
        ``{"op": "add", "name", "host", "port", "role"}`` joins a
        framed-TCP replica; ``{"op": "remove", "name"}`` drains and
        deregisters. Returns (code, body) like generate()."""
        op = req.get("op")
        if op == "add":
            missing = [
                k for k in ("name", "host", "port", "role")
                if not req.get(k)
            ]
            if missing:
                return 400, {"error": f"missing fields {missing}"}
            try:
                client = TcpReplica(
                    str(req["name"]), str(req["host"]),
                    int(req["port"]), str(req["role"]),
                )
                return 200, self.add_replica(client, str(req["role"]))
            except (ValueError, TypeError) as e:
                return 400, {"error": str(e)}
        if op == "remove":
            if not req.get("name"):
                return 400, {"error": "missing fields ['name']"}
            try:
                return 200, self.remove_replica(
                    str(req["name"]),
                    drain=bool(req.get("drain", True)),
                )
            except (KeyError, ValueError) as e:
                return 400, {"error": str(e)}
        return 400, {"error": f"unknown op {op!r}"}

    def n_pages_for(self, prompt_len: int, max_new: int) -> int:
        need = max(1, prompt_len + max_new - 1)
        return -(-need // self.page)

    def health(self) -> dict:
        """Per-replica detail, not a bare status — a JobSet probe (or
        a human with curl) can tell WHICH replica is out of rotation,
        how stale its last signals are, and how the policy currently
        ranks it."""
        now = time.monotonic()
        with self._lock:
            replicas = {
                name: {
                    "name": name,
                    "role": r.role,
                    "healthy": r.healthy,
                    # None = never successfully probed since startup.
                    "last_probe_age_s": (
                        round(now - r.last_seen, 3)
                        if r.last_seen else None
                    ),
                    "score": round(r.score(), 4),
                    "pages_in_use": r.pages_in_use,
                    "pages_total": r.pages_total,
                    "slots_active": r.slots_active,
                    "slots_total": r.slots_total,
                    **(
                        {"spec_k": r.spec_k,
                         "spec_passes": r.spec_passes}
                        if r.spec_k else {}
                    ),
                    **(
                        {"prefill_chunk_pages": r.prefill_chunk_pages,
                         "prefill_inflight": r.prefill_inflight,
                         "prefill_chunks": r.prefill_chunks}
                        if r.prefill_chunk_pages else {}
                    ),
                    **(
                        {"piggyback_waterline": r.piggyback_waterline}
                        if r.piggyback_waterline else {}
                    ),
                    **({"draining": True} if r.draining else {}),
                }
                for name, r in self._states.items()
            }
            return {
                "ok": all(r["healthy"] for r in replicas.values())
                or bool(
                    # Degraded-but-serving: healthy coverage of both
                    # roles keeps the door open.
                    any(
                        r["healthy"] and r["role"] == "prefill"
                        for r in replicas.values()
                    )
                    and any(
                        r["healthy"] and r["role"] == "decode"
                        for r in replicas.values()
                    )
                ),
                "queue_depth": len(self.policy.queue),
                "inflight": self._inflight,
                "replicas": replicas,
            }

    def render_metrics(self) -> str:
        with self._lock:
            depth = len(self.policy.queue)
            depths = self.policy.queue.depths()
            decode_free = sum(
                r.free_pages
                for r in self._states.values()
                if r.role == "decode" and r.healthy
            )
        # Per-tenant WFQ depth rides as labeled children next to the
        # unlabeled total — queue pressure visible per tenant before
        # it becomes TTFT (drained tenants keep a 0 series).
        for tenant, n in depths.items():
            self._metrics.set_gauge("queue_depth", n, tenant=tenant)
        return self._metrics.render(
            {
                "queue_depth": depth,
                "inflight": self._inflight,
                "decode_pages_free": decode_free,
            }
        )

    # ---- WFQ dispatch ---------------------------------------------

    def _pump_locked(self) -> None:
        while self._inflight < self.max_inflight and len(self.policy.queue):
            ev = self.policy.queue.pop()
            if getattr(ev, "abandoned", False):
                # The waiter timed out and left; granting its slot
                # would leak it (nobody would _release). Skip.
                continue
            self._inflight += 1
            ev.set()

    def _admit(self, tenant: str, cost: float, timeout: float) -> bool:
        # resource: acquires inflight-credit
        ev = threading.Event()
        ev.abandoned = False
        with self._lock:
            self.policy.queue.push(tenant, cost, ev)
            self._pump_locked()
            deferred = not ev.is_set()
        if deferred:
            # Admission was not immediate: the request sat behind the
            # inflight cap. The counter is the alert-friendly
            # companion of the queue-depth gauge (a scrape can miss a
            # transient queue; it cannot miss a counter increment).
            self._metrics.inc("deferred_total", tenant=tenant)
        if ev.wait(timeout):
            return True
        with self._lock:
            if ev.is_set():
                # A pump granted the slot between the wait timing out
                # and us taking the lock — the slot is ours after all.
                return True
            ev.abandoned = True
        return False

    def _release(self) -> None:
        # resource: releases inflight-credit
        with self._lock:
            self._inflight -= 1
            self._pump_locked()

    # ---- the proxy path -------------------------------------------

    def _pick(
        self, session: str, n_pages: int, digests: Sequence[str] = ()
    ) -> Tuple[Optional[str], Optional[str], str]:
        """(decode_name, prefill_name, reject_reason) under the lock."""
        with self._lock:
            h0 = self.policy.affinity_hits
            name, reason = self.policy.pick_decode(
                session,
                [r for r in self._states.values() if r.role == "decode"],
                n_pages,
                digests,
            )
            pname = self.policy.pick_prefill(
                [r for r in self._states.values() if r.role == "prefill"],
                digests,
            )
            dh = self.policy.affinity_hits - h0
        if dh:
            self._metrics.inc("prefix_affinity_hits_total", dh)
        return name, pname, reason

    def _rehome(
        self, session: str, exclude: set, n_pages: int, ctx
    ) -> Tuple[Optional[Dict[str, Any]], str]:
        """Resume a drained session on a surviving decode replica.

        The draining replica exported the session's slot (prompt +
        every emitted token + its KV pages) to the shared spill
        directory before refusing further work; the router reads that
        bundle back and re-dispatches it through the NORMAL decode
        path — the survivor splices the pages and continues sampling
        from the exact KV state, so the resumed token stream cannot
        diverge. Returns (decode_reply, replica) or (None, "")."""
        # wire: consumes session-bundle via spill-store
        if not (self.spill_dir and session):
            return None, ""
        data = load_session(self.spill_dir, session)
        if data is None:
            return None, ""
        with self._lock:
            fits = [
                r for r in self._states.values()
                if r.role == "decode" and r.name not in exclude
                and self.policy.decode_fits(r, n_pages)
            ]
            target = (
                min(fits, key=lambda r: (r.score(), r.name)).name
                if fits else ""
            )
        if not target:
            return None, ""
        dclient = next(c for c in self._decode if c.name == target)
        try:
            out = dclient.decode(data)
        except Exception:  # noqa: BLE001 — proxy boundary
            self._metrics.inc("proxy_errors_total")
            with self._lock:
                self._states[target].healthy = False
            return None, ""
        with self._lock:
            self._states[target].update(out, now=time.monotonic())
            self.policy.pin_session(session, target)
        drop_session(self.spill_dir, session)
        self._metrics.inc("session_rehomes_total")
        self._events.emit(
            "router_rehome", session=session, replica=target,
            pages=n_pages, trace=ctx.trace_id,
        )
        return out, target

    def _piggyback(
        self,
        pig: str,
        prompt: List[int],
        max_new: int,
        ctx,
        tenant: str,
        session: str,
        queue_s: float,
        admit_s: float,
        n_pages: int,
        trace_hdr: tuple,
        t0: float,
    ) -> Tuple[int, dict, tuple]:
        """Forward a RAW prompt to decode replica ``pig`` (one RPC
        does prefill-by-chunks + decode in place). TTFT decomposes
        additively from the replica's self-reported chunk timings:
        ``first_flush_s = prefill_queue_s + prefill_s`` by
        construction, so

            ttft = queue_wait + admit + prefill_queue_chunks
                 + prefill_compute
        """
        # wire: consumes decode-reply via out
        # wire: produces router-response
        dclient = next(c for c in self._decode if c.name == pig)
        tp0 = time.perf_counter()
        resumed = False
        err = ""
        try:
            out = dclient.decode_raw(
                prompt, max_new, trace=ctx.wire(), session=session or None,
            )
        except Exception as e:  # noqa: BLE001 — proxy boundary
            self._metrics.inc("proxy_errors_total")
            with self._lock:
                self._states[pig].healthy = False
            out, err = None, f"{type(e).__name__}: {e}"
        if out is not None and out.get("drained"):
            with self._lock:
                self._states[pig].update(out, now=time.monotonic())
            # The drained reply names the session the replica actually
            # exported — prefer it for the spill-store lookup (the
            # replica's id is authoritative for its own bundle).
            session = str(out.get("session") or "") or session
            out, err = None, "decode replica draining"
        if out is None:
            # Same recovery as the splice path: the drained replica
            # exported this session's slot before exiting; a survivor
            # resumes it from the shared spill store.
            out, rname = self._rehome(session, {pig}, n_pages, ctx)
            if out is None:
                self.policy.forget_session(session)
                return 502, {"error": err}, trace_hdr
            pig, resumed = rname, True
        rpc_s = time.perf_counter() - tp0
        reqtrace.stage(
            self._tracer, ctx, "req_piggyback_rpc", rpc_s, replica=pig,
        )
        with self._lock:
            self._states[pig].update(out, now=time.monotonic())
            self.policy.pin_session(session, pig)
        pq_s = float(out.get("prefill_queue_s", 0.0))
        pf_s = float(out.get("prefill_s", 0.0))
        stages = {
            "queue_wait": round(queue_s, 6),
            "admit": round(admit_s, 6),
            "prefill_queue_chunks": round(pq_s, 6),
            "prefill_compute": round(pf_s, 6),
            # No migration happened: no splice, and the first token
            # is host-visible the moment the final chunk samples it.
            "splice": 0.0,
            "first_decode": round(float(out.get("first_flush_s", 0.0)), 6),
        }
        ttft = queue_s + admit_s + pq_s + pf_s
        latency = time.monotonic() - t0
        tokens = out.get("tokens") or []
        tok_s = (
            (latency - ttft) / (len(tokens) - 1)
            if len(tokens) > 1 else None
        )
        self.slo.observe(tenant, ttft, tok_s=tok_s, trace=ctx.trace_id)
        self._metrics.inc("requests_total")
        self._metrics.inc("piggyback_total")
        self._metrics.inc("request_seconds_total", latency)
        self._metrics.inc("tokens_total", len(tokens))
        self._events.emit(
            "router_request", tenant=tenant, replica=pig,
            latency_s=round(latency, 6),
            prefill_replica=pig, pages=n_pages, piggyback=True,
            prefill_chunks=int(out.get("prefill_chunks", 0)),
            trace=ctx.trace_id, ttft_s=round(ttft, 6),
            n_tokens=len(tokens), stages=stages,
        )
        return (
            200,
            {
                "tokens": tokens,
                "replica": pig,
                "prefill_replica": pig,
                "piggyback": bool(out.get("piggyback", True)),
                "migration_pages": 0,
                "trace": ctx.trace_id,
                "ttft_s": round(ttft, 6),
                "stages": stages,
                "resumed": resumed,
            },
            trace_hdr,
        )

    def generate(
        self, req: dict, trace_header: str = ""
    ) -> Tuple[int, dict, tuple]:
        """One request through WFQ → admission → prefill → migrate →
        decode. Returns (status, body, extra_headers).

        The request joins (or mints) a trace context from the
        X-TPUFW-Trace header and carries it through both hops; the
        router-observed TTFT is decomposed additively — each stage is
        a local duration, so no cross-process clock agreement is
        needed:

            ttft = queue_wait + admit + prefill_rtt + splice
            prefill_rtt = prefill_queue + prefill_admit
                        + prefill_compute + page_export + wire

        where ``wire`` is defined as the rpc wall minus the engine's
        self-reported wall (serialization + transport, by
        construction)."""
        # wire: consumes router-request via req
        # wire: consumes decode-reply via out
        # wire: consumes trace-meta via tmeta, engine_stages
        # wire: produces router-response
        t0 = time.monotonic()
        prompt = req.get("prompt")
        if not (
            isinstance(prompt, list)
            and prompt
            and all(isinstance(t, int) for t in prompt)
        ):
            return 400, {"error": "prompt must be a non-empty [int]"}, ()
        max_new = int(req.get("max_new", 16))
        tenant = str(req.get("tenant", "") or "default")
        session = str(req.get("session", "") or "")
        ctx = reqtrace.parse(trace_header or req.get("trace"))
        if ctx is None:
            ctx = reqtrace.mint(tenant)
        elif not ctx.tenant:
            ctx = reqtrace.TraceContext(
                ctx.trace_id, ctx.span_id, tenant, parent=ctx.parent
            )
        trace_hdr = ((reqtrace.HEADER, ctx.wire()),)
        n_pages = self.n_pages_for(len(prompt), max_new)
        # Prefix-affinity digests: jax-free, same page-granular
        # chunking as the replicas' radix tries, computed once per
        # request and matched against every pick's advertised set.
        digs = (
            chunk_digests(prompt, self.page, self.policy.affinity_k)
            if self.policy.affinity_k else ()
        )
        cost = len(prompt) + max_new
        tq0 = time.perf_counter()
        if not self._admit(tenant, cost, timeout=600.0):
            return 503, {"error": "queue wait timed out"}, trace_hdr
        try:
            # Everything after a granted credit runs under the
            # release-guaranteeing try: a raise in even the trace
            # plumbing would otherwise strand the inflight slot and
            # shrink the router's effective cap forever (TPU019).
            queue_s = time.perf_counter() - tq0
            reqtrace.stage(
                self._tracer, ctx, "req_queue_wait", queue_s,
                role="router",
            )
            ta0 = time.perf_counter()
            self._reprobe_unhealthy()
            name, pname, reason = self._pick(session, n_pages, digs)
            if name is None or pname is None:
                # Everything pickable may just be marked unhealthy
                # from a transient failure — force a probe and retry
                # once before turning traffic away.
                self._reprobe_unhealthy(force=True)
                name, pname, reason = self._pick(session, n_pages, digs)
            admit_s = time.perf_counter() - ta0
            if name is None:
                # Tenant-labeled so rejected load attributes per
                # tenant in the capacity curves — a 429 is offered
                # load the SLO did not serve.
                self._metrics.inc("rejects_total", tenant=tenant)
                self._events.emit(
                    "router_reject", tenant=tenant, reason=reason,
                    trace=ctx.trace_id,
                )
                return (
                    429,
                    {"error": f"decode pools {reason}; retry later"},
                    (("Retry-After", str(self.policy.retry_after_s)),)
                    + trace_hdr,
                )
            # Prefill/decode fungibility: when no prefill replica is
            # healthy, or the best one is already busy chunking other
            # prompts (load skew), steer the raw prompt straight at a
            # decode replica with spare chunk capacity — it prefills
            # chunk-by-chunk inside its own decode passes, skipping
            # the migration hop entirely.
            pig = None
            with self._lock:
                h0 = self.policy.affinity_hits
                pstate = self._states.get(pname) if pname else None
                if pname is None or (
                    pstate is not None and pstate.prefill_inflight > 0
                ):
                    pig = self.policy.pick_piggyback(
                        [
                            r for r in self._states.values()
                            if r.role == "decode"
                        ],
                        n_pages,
                        max_chunks=None if pname is None else 1,
                        digests=digs,
                    )
                dh = self.policy.affinity_hits - h0
            if dh:
                self._metrics.inc("prefix_affinity_hits_total", dh)
            if pig is not None:
                return self._piggyback(
                    pig, prompt, max_new, ctx, tenant, session,
                    queue_s, admit_s, n_pages, trace_hdr, t0,
                )
            if pname is None:
                self._metrics.inc("rejects_total", tenant=tenant)
                self._events.emit(
                    "router_reject", tenant=tenant, reason="no_prefill",
                    trace=ctx.trace_id,
                )
                return (
                    503, {"error": "no healthy prefill replica"},
                    trace_hdr,
                )
            reqtrace.stage(
                self._tracer, ctx, "req_admit", admit_s,
                replica=name, prefill_replica=pname,
            )
            pclient = next(c for c in self._prefill if c.name == pname)
            dclient = next(c for c in self._decode if c.name == name)
            # Mark the replica whose call actually raised — blaming
            # the decode replica for a prefill failure takes a healthy
            # replica out of rotation while the broken one keeps
            # receiving traffic.
            tp0 = time.perf_counter()
            # Router-observed prefill occupancy: prefill replies are
            # raw bundles (no signals piggyback like decode replies),
            # so a healthy replica's advertised prefill_inflight is
            # the startup-probe snapshot forever. The router counts
            # its own outstanding RPCs instead — that is exactly the
            # "busy chunking other prompts" signal the piggyback
            # steering and score() need, and it is live.
            with self._lock:
                self._states[pname].prefill_inflight += 1
            try:
                bundle = pclient.prefill(
                    prompt, max_new, trace=ctx.wire(),
                    session=session or None,
                )
            except Exception as e:  # noqa: BLE001 — proxy boundary
                self._metrics.inc("proxy_errors_total")
                with self._lock:
                    self._states[pname].healthy = False
                return 502, {"error": f"{type(e).__name__}: {e}"}, trace_hdr
            finally:
                with self._lock:
                    pst = self._states.get(pname)
                    if pst is not None:
                        pst.prefill_inflight = max(
                            0, pst.prefill_inflight - 1
                        )
            prefill_rtt = time.perf_counter() - tp0
            reqtrace.stage(
                self._tracer, ctx, "req_prefill_rpc", prefill_rtt,
                replica=pname,
            )
            stages: Dict[str, float] = {
                "queue_wait": round(queue_s, 6),
                "admit": round(admit_s, 6),
            }
            tmeta = peek_trace(bundle)
            engine_stages = (tmeta or {}).get("stages") or {}
            if engine_stages:
                for src, dst in (
                    ("queue", "prefill_queue"),
                    ("admit", "prefill_admit"),
                    ("compute", "prefill_compute"),
                    ("export", "page_export"),
                ):
                    stages[dst] = round(float(engine_stages.get(src, 0.0)), 6)
                if "queue_chunks" in engine_stages:
                    # Chunked prefill engine: time spent BETWEEN
                    # chunks (lock re-acquires + arena stalls) is its
                    # own TTFT term, so prefill_queue keeps meaning
                    # the FIRST lock wait. Additivity holds — the
                    # engine's wall_s is the literal five-stage sum.
                    stages["prefill_queue_chunks"] = round(
                        float(engine_stages["queue_chunks"]), 6
                    )
                wire_s = max(
                    0.0, prefill_rtt - float((tmeta or {}).get("wall_s", 0.0))
                )
            else:
                # Pre-trace prefill peer: no decomposition, the whole
                # rtt is one stage and wire is indistinguishable.
                stages["prefill_compute"] = round(prefill_rtt, 6)
                wire_s = 0.0
            stages["wire"] = round(wire_s, 6)
            reqtrace.stage(self._tracer, ctx, "req_wire", wire_s)
            td0 = time.perf_counter()
            resumed = False
            err = ""
            try:
                out = dclient.decode(bundle)
            except Exception as e:  # noqa: BLE001 — proxy boundary
                self._metrics.inc("proxy_errors_total")
                with self._lock:
                    self._states[name].healthy = False
                out, err = None, f"{type(e).__name__}: {e}"
            if out is not None and out.get("drained"):
                # The replica drained (SIGTERM / scale-in) while this
                # request was decoding: its reply carries partial
                # tokens and its exported session sits in the spill
                # store. Fold its final signals in, then re-home —
                # under the session id the reply names (authoritative
                # for the replica's own export).
                with self._lock:
                    self._states[name].update(out, now=time.monotonic())
                session = str(out.get("session") or "") or session
                out, err = None, "decode replica draining"
            if out is None:
                out, rname = self._rehome(session, {name}, n_pages, ctx)
                if out is None:
                    self.policy.forget_session(session)
                    return 502, {"error": err}, trace_hdr
                name, resumed = rname, True
            decode_rtt = time.perf_counter() - td0
            reqtrace.stage(
                self._tracer, ctx, "req_decode_rpc", decode_rtt,
                replica=name,
            )
            with self._lock:
                self._states[name].update(out, now=time.monotonic())
            splice_s = float(out.get("splice_s", 0.0))
            stages["splice"] = round(splice_s, 6)
            stages["first_decode"] = round(
                float(out.get("first_flush_s", 0.0)), 6
            )
            # First token usable on the decode side = the splice
            # landing; decode chunks after that are steady-state.
            ttft = queue_s + admit_s + prefill_rtt + splice_s
            latency = time.monotonic() - t0
            tokens = out["tokens"]
            tok_s = (
                (latency - ttft) / (len(tokens) - 1)
                if len(tokens) > 1 else None
            )
            self.slo.observe(
                tenant, ttft, tok_s=tok_s, trace=ctx.trace_id
            )
            self._metrics.inc("requests_total")
            self._metrics.inc("request_seconds_total", latency)
            self._metrics.inc("tokens_total", len(tokens))
            self._events.emit(
                "router_request", tenant=tenant, replica=name,
                latency_s=round(latency, 6),
                prefill_replica=pname, pages=n_pages,
                trace=ctx.trace_id, ttft_s=round(ttft, 6),
                n_tokens=len(tokens), stages=stages,
            )
            return (
                200,
                {
                    "tokens": tokens,
                    "replica": name,
                    "prefill_replica": pname,
                    "migration_pages": n_pages,
                    "trace": ctx.trace_id,
                    "ttft_s": round(ttft, 6),
                    "stages": stages,
                    "resumed": resumed,
                },
                trace_hdr,
            )
        finally:
            self._release()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


# --------------------------------------------------- role entrypoint

def _parse_weights(spec: str) -> Dict[str, float]:
    """"tenant:weight,tenant:weight" → dict; malformed entries are
    skipped (a bad knob must not take the front door down)."""
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, w = part.rpartition(":")
        try:
            out[name.strip()] = float(w)
        except ValueError:
            continue
    return out


def _parse_addrs(spec: str) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


def main_router() -> int:
    """Container entrypoint for TPUFW_SERVE_ROLE=router. Replica
    addresses come from the discovery contract (explicit env lists or
    JobSet DNS — tpufw.cluster.discovery)."""
    import os

    from tpufw.cluster.discovery import discover_replicas

    prefill_addrs, decode_addrs = discover_replicas()
    prefill = [
        TcpReplica(f"prefill-{i}", h, p, "prefill")
        for i, (h, p) in enumerate(prefill_addrs)
    ]
    decode = [
        TcpReplica(f"decode-{i}", h, p, "decode")
        for i, (h, p) in enumerate(decode_addrs)
    ]
    policy = RouterPolicy(
        tenant_weights=_parse_weights(
            env_str("router_tenant_weights", "")
        ),
        saturation=env_float("router_saturation", 0.95),
        retry_after_s=env_int("router_retry_after_s", 5),
        affinity_k=env_int("router_prefix_affinity", 0),
    )
    events = obs_events.NULL
    tracer = obs_trace.NULL
    tdir = env_str("telemetry_dir", "")
    if tdir:
        os.makedirs(tdir, exist_ok=True)
        events = obs_events.EventLog(
            os.path.join(tdir, "events-router.jsonl")
        )
        tracer = obs_trace.Tracer(
            os.path.join(tdir, "trace-router.json"),
            process_name="router", max_events=200_000,
        )
    server = RouterServer(
        prefill,
        decode,
        policy=policy,
        port=env_int("router_port", DEFAULT_ROUTER_PORT),
        page=env_int("serve_page", 16),
        max_inflight=env_int("router_inflight", 4),
        events=events,
        tracer=tracer,
        spill_dir=env_str("kv_spill_dir", ""),
    )
    # Fleet observatory attach point: the collector scrapes this
    # router's own exposition in-process plus every replica's framed-
    # TCP signals probe. collector_from_env is None (no thread, no
    # files) unless TPUFW_FLEET_SCRAPE_S is set — the disabled path
    # is byte-identical to a build without the observatory.
    from tpufw.obs import fleet as obs_fleet

    fleet_targets = [
        obs_fleet.Target("router", "router", server.render_metrics)
    ] + [
        obs_fleet.Target(c.name, c.role, c.signals)
        for c in prefill + decode
    ]
    collector = obs_fleet.collector_from_env(
        fleet_targets, health_fn=server.health, default_dir=tdir or "."
    )
    print(json.dumps(
        {
            "serving_role": "router",
            "port": server.port,
            "prefill": len(prefill),
            "decode": len(decode),
            "fleet": collector is not None,
        }
    ), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.close()
        if collector is not None:
            collector.stop()
        tracer.close()
        events.close()
    return 0
