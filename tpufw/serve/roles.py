"""Prefill / decode replica roles for disaggregated serving.

The split follows the workload physics (ROADMAP item 1 / Podracer's
decomposed-slice template): prefill is compute-bound and bursty,
decode is memory-bound and steady, so each gets its own mesh and its
own page arena. The handoff is PR 6's page arena made literal —

- :class:`PrefillEngine` runs admission (prefix-cache attach +
  ``_suffix_prefill_jit`` or cold ``prefill_row``) on its replica,
  scatters the row into its arena, then EXPORTS the slot's pages
  (int8 codes + page-structured scales raw) as a page bundle and
  releases the slot. Its prefix trie persists across requests, so
  shared prompts still prefill once per replica.
- :class:`DecodeEngine` imports bundles by allocating pages from its
  own arena and splicing them into its ``PagedSlotPool`` table. The
  cache shapes never change, so ``decode_steps`` stays the single
  jitted program it always was — migrations cost zero retraces, and
  greedy decode is bit-equal to a never-migrated run (the page table
  hides the physical ids).

RNG discipline mirrors the slot scheduler exactly: prefill stream
``fold_in(key(seed_base), job_index)``, chunk stream
``fold_in(key(seed_base + 1), chunk_index)`` — so a migrated request
draws the same sample stream the single-process path would.

``main_role`` is the container entrypoint behind
``TPUFW_SERVE_ROLE`` (deploy/manifests/13-serve-disagg-v5e8-jobset
.yaml): a framed-TCP server per engine, the router's HTTP front end
for the router role.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from tpufw.obs import events as obs_events
from tpufw.obs import reqtrace
from tpufw.obs import trace as obs_trace
from tpufw.serve import transport
from tpufw.serve.bundle import (
    BundleError,
    advertised_digests,
    attach_spill,
    decode_bundle,
    encode_bundle,
)
from tpufw.workloads.env import env_float, env_int, env_opt_str, env_str

DEFAULT_PEER_PORT = 8477


def _paged_models(model, page: int, kv_quant: str, arena_pages: int):
    """(pool_model, row_model) pair for a paged pool at the base
    model's full sequence budget — same construction the slot
    scheduler's ``_pool_model`` uses."""
    from tpufw.models import model_for_config

    cfg = model.cfg
    cache_len = int(cfg.max_seq_len)
    if page <= 0 or cache_len % page:
        raise ValueError(
            f"page={page} must be > 0 and divide max_seq_len={cache_len}"
        )
    pool_cfg = dataclasses.replace(
        cfg, kv_page=page, kv_pages=arena_pages, kv_quant=kv_quant
    )
    row_cfg = dataclasses.replace(cfg, kv_page=0, kv_quant="")
    return model_for_config(pool_cfg), model_for_config(row_cfg)


class _ChunkTicket:
    """One in-flight chunked prefill's place in the turn queue.
    Identity-compared on purpose (no ``__eq__``): two prompts with
    equal remaining work are still distinct tickets."""

    __slots__ = ("remaining", "seq", "blocked")

    def __init__(self, remaining: int, seq: int):
        self.remaining = remaining
        self.seq = seq
        #: set while this prefill is arena-stalled, so peers that CAN
        #: make progress aren't held behind it.
        self.blocked = False


def _fabric_signals(sig: Dict[str, Any], pool, spill) -> None:
    """KV-fabric occupancy/outcome numbers shared by both roles'
    ``signals()``: trie hit counters (the bench's hit-rate source) and
    spill-tier tier sizes + lifetime totals (the fleet deriver's spill
    occupancy series). Numeric-only on purpose — these ride into
    ``tpufw.obs.fleet``'s per-signal time series."""
    if pool.prefix is not None:
        sig["prefix_hits"] = pool.prefix_hits
        sig["prefix_misses"] = pool.prefix_misses
    if spill is not None:
        st = spill.stats()
        sig["spill_ram_pages"] = st["ram_pages"]
        sig["spill_dir_pages"] = st["dir_pages"]
        sig["spill_pages_total"] = st["spilled_pages_total"]
        sig["spill_restored_total"] = st["restored_total"]


class PrefillEngine:
    """One prefill replica: admission + prefix cache + page export.

    Slots are transient here — a slot lives exactly from insert to
    export+release — so the arena is sized for in-flight admissions
    plus whatever the prefix trie holds, not for decode residency."""

    def __init__(
        self,
        model,
        params,
        *,
        sampling,
        page: int,
        kv_quant: str = "",
        n_slots: int = 2,
        arena_pages: Optional[int] = None,
        eos_id: Optional[int] = None,
        seed_base: int = 0,
        prefix_cache: bool = True,
        prefill_chunk_pages: int = 0,
        spill=None,
        affinity_k: int = 0,
        events=None,
        tracer=None,
    ):
        from tpufw.infer.pages import PagedSlotPool

        cache_len = int(model.cfg.max_seq_len)
        per_row = cache_len // page
        pages = arena_pages or n_slots * per_row + 1
        pool_model, row_model = _paged_models(model, page, kv_quant, pages)
        self.pool = PagedSlotPool.create_paged(
            pool_model, row_model, params, n_slots,
            sampling=sampling, eos_id=eos_id,
            prefix_cache=prefix_cache,
        )
        self.page = page
        self.n_slots = n_slots
        self._eos = eos_id
        self._seed_base = seed_base
        self._job_index = 0
        self._events = events if events is not None else obs_events.NULL
        self._tracer = tracer if tracer is not None else obs_trace.NULL
        # KV fabric: host-RAM spill tier behind the trie (evicted
        # pages keep their KV; restore skips the chunk's re-prefill)
        # and the digest set the router's affinity steering reads.
        self._spill = spill
        self._affinity_k = max(0, int(affinity_k))
        self._digest_cache: Dict[str, Any] = {}
        if spill is not None:
            attach_spill(self.pool, spill, events=self._events)
        self._lock = threading.Lock()
        # Chunked mode: the engine lock is RELEASED between chunks, so
        # concurrent admissions interleave at chunk granularity instead
        # of serializing whole prompts (the lock wait that used to be
        # the "queue" stage collapses to one chunk's latency). The
        # condition variable wakes stalled chunk loops when a finalize
        # or an abandon returns pages.
        self.prefill_chunk_pages = max(0, int(prefill_chunk_pages))
        self._cv = threading.Condition(self._lock)
        #: pages promised to in-flight chunked admissions; admission
        #: blocks (rather than deadlocks) while the sum would pass the
        #: arena, so every admitted prefill can always finish.
        self._reserved = 0  # resource: counter reserved-pages
        #: Chunk-turn tickets, scheduled SRPT (shortest remaining
        #: prompt first, admission order on ties): equal-length
        #: prompts drain in strict FIFO — identical completion order
        #: to monolithic prefill — while a short prompt preempts a
        #: long one at the next chunk boundary instead of eating its
        #: whole remaining prefill as queue time. A bare lock gives
        #: neither property: the thread that just ran a chunk
        #: re-acquires before any waiter wakes.
        self._rr: List[_ChunkTicket] = []
        #: True while a chunk_step is in flight with the mutex
        #: RELEASED around its device call — exactly one chunk may
        #: compute at a time or the arena leaves would fork.
        self._chunk_busy = False
        self.prefill_inflight = 0  # resource: counter prefill-inflight
        self.prefill_chunks = 0
        self.prefill_resumes = 0
        self.migrations = 0
        self.migration_bytes = 0

    def signals(self) -> Dict[str, Any]:
        # wire: produces role-signals
        a = self.pool.allocator
        sig = {
            "role": "prefill",
            "pages_total": a.capacity,
            "pages_in_use": a.in_use,
            "migrations": self.migrations,
        }
        if self.prefill_chunk_pages:
            sig["prefill_chunk_pages"] = self.prefill_chunk_pages
            sig["prefill_inflight"] = self.prefill_inflight
            sig["prefill_chunks"] = self.prefill_chunks
        _fabric_signals(sig, self.pool, self._spill)
        if self._affinity_k:
            # wire: produces role-signals via prefix_digests
            sig["prefix_digests"] = advertised_digests(
                self.pool, self._spill, self._affinity_k,
                self._digest_cache,
            )
        return sig

    def prefill(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> bytes:
        """Admit one request, export its slot as a page bundle, free
        the slot. Returns the serialized bundle (the first sampled
        token rides inside it as the ``token`` cursor). Raises
        ValueError when the row can never fit this arena.

        ``trace`` is an optional request-trace context (wire string or
        TraceContext); stage timings — queue (engine lock wait), admit
        (page grant + trie attach), compute, export — always ride in
        the bundle header, so the router can decompose its observed
        round trip even for untraced traffic."""
        # wire: produces trace-meta via tmeta, stages
        from tpufw.infer import slots as slots_mod

        import jax

        if self.prefill_chunk_pages:
            return self._prefill_chunked(
                prompt, max_new, trace, session=session
            )
        ctx = reqtrace.parse(trace)
        ctx = ctx.child() if ctx is not None else None
        prompt = list(prompt)
        need = len(prompt) + max_new - 1
        if self.pool.n_pages_for(need) > self.pool.allocator.capacity:
            raise ValueError(
                f"prompt+budget needs {self.pool.n_pages_for(need)} "
                f"pages; arena capacity is {self.pool.allocator.capacity}"
            )
        t_req = time.perf_counter()
        with self._lock:
            t_lock = time.perf_counter()
            queue_s = t_lock - t_req
            job_index = self._job_index
            self._job_index += 1
            rng = jax.random.fold_in(
                jax.random.key(self._seed_base), job_index
            )
            t0 = time.monotonic()
            grant = self.pool.acquire_pages(prompt, need)
            if grant is None:
                raise RuntimeError(
                    "prefill arena exhausted — in-flight admissions "
                    "plus trie-held pages left no room"
                )
            ids, shared_n = grant
            inserted = False
            slot = 0  # transient occupancy: insert -> export -> release
            try:
                t_admit = time.perf_counter()
                admit_s = t_admit - t_lock
                if shared_n:
                    cache, _f, first, _d, seen = (
                        self.pool.prefill_shared(
                            prompt, ids[:shared_n], rng
                        )
                    )
                else:
                    cache, _f, first, _d, seen = (
                        # tpulint: disable=TPU003 — exclusive if/else
                        # arms: exactly ONE of prefill_shared/
                        # prefill_row consumes this request's rng.
                        slots_mod.prefill_row(
                            self.pool.row_model, self.pool.params,
                            prompt, rng, sampling=self.pool.sampling,
                            eos_id=self._eos, pad_to=len(prompt),
                        )
                    )
                self.pool.insert_paged(
                    slot, cache, first, len(prompt), max_new - 1,
                    ids, shared_n, row_seen=seen,
                )
                inserted = True
                self.pool.register_prefix(prompt, ids)
                t_compute = time.perf_counter()
                compute_s = t_compute - t_admit
                state = self.pool.export_slot(slot)
            except BaseException:
                # The grant must not outlive a failed prefill/export
                # (TPU019): pre-insert the pages are still owned by
                # this frame, post-insert the transient slot owns
                # them — release whichever holder is live.
                if inserted:
                    self.pool.release_slot(slot)
                else:
                    self.pool.release_pages(ids)
                raise
            self.pool.release_slot(slot)
            export_s = time.perf_counter() - t_compute
            # Stage timings seal into the header BEFORE encode: the
            # encode+framing remainder shows up as the router-side
            # "wire" stage (rpc wall minus wall_s), by construction.
            stages = {
                "queue": round(queue_s, 6),
                "admit": round(admit_s, 6),
                "compute": round(compute_s, 6),
                "export": round(export_s, 6),
            }
            tmeta: Dict[str, Any] = {
                "stages": stages,
                "wall_s": round(
                    queue_s + admit_s + compute_s + export_s, 6
                ),
            }
            if ctx is not None:
                tmeta.update(ctx.meta())
            state["trace"] = tmeta
            # Ride the prompt ids in the header: a spec-enabled decode
            # replica mines its n-gram proposals from them. Optional,
            # so old decoders splice the bundle unchanged.
            state["prompt"] = [int(t) for t in prompt]
            if session:
                # Sticky session id stamped at prefill: the decode
                # side carries it through drain bundles so the router
                # can re-home the session by name.
                state["session"] = str(session)
            data = encode_bundle(state)
            self.migrations += 1
            self.migration_bytes += len(data)
            reqtrace.stage(
                self._tracer, ctx, "req_queue_wait", queue_s,
                role="prefill",
            )
            reqtrace.stage(
                self._tracer, ctx, "req_admit", admit_s,
                role="prefill", shared_pages=shared_n,
            )
            reqtrace.stage(
                self._tracer, ctx, "req_prefill_compute", compute_s,
                prompt_tokens=len(prompt),
            )
            reqtrace.stage(
                self._tracer, ctx, "req_page_export", export_s,
                pages=state["n_pages"],
            )
            fields = dict(
                pages=state["n_pages"], bytes=len(data),
                wall_s=round(time.monotonic() - t0, 6),
                direction="export", shared_pages=shared_n,
            )
            if ctx is not None:
                fields["trace"] = ctx.trace_id
            self._events.emit("serve_migration", **fields)
            return data

    def _turn(self) -> Optional[_ChunkTicket]:
        """The ticket whose chunk runs next: fewest pages left, then
        admission order. Arena-stalled tickets are skipped so a prompt
        whose next chunk fits isn't held behind one whose doesn't."""
        live = [t for t in self._rr if not t.blocked]
        if not live:
            return None
        return min(live, key=lambda t: (t.remaining, t.seq))

    @contextlib.contextmanager
    def _unlocked(self):
        """Release the engine mutex around a chunk's device call so
        admissions/abandons (host-only bookkeeping) never wait behind
        compute; ``_chunk_busy`` keeps the compute itself exclusive."""
        self._cv.release()
        try:
            yield
        finally:
            self._cv.acquire()

    def _prefill_chunked(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> bytes:
        """Chunked admission: advance the prompt one page-aligned
        chunk per SRPT turn, with the engine mutex released both
        between chunks AND during each chunk's device call — so
        admission is immediate (host-only bookkeeping), concurrent
        prompts interleave at chunk granularity, and a short prompt
        preempts a long one at the next chunk boundary instead of
        head-of-line blocking behind it. The exported bundle carries prompt-only
        pages (``n_pages`` covers the prompt, not the decode budget —
        the decode replica allocates the tail from ``cache_index +
        remaining``), so the admission bound here is the prompt's page
        need alone: long prompts that used to 400 on prompt+budget now
        queue and drain chunk by chunk.

        Stage accounting stays additive: ``queue`` is the FIRST lock
        wait only, every later wait (lock re-acquires, arena stalls)
        lands in ``queue_chunks``, and ``wall_s`` is the literal sum —
        so the router's TTFT decomposition gains a
        ``prefill_queue_chunks`` term without losing additivity."""
        # wire: produces trace-meta via tmeta, stages
        import jax

        ctx = reqtrace.parse(trace)
        ctx = ctx.child() if ctx is not None else None
        prompt = list(prompt)
        n_prompt_pages = self.pool.n_pages_for(len(prompt))
        if n_prompt_pages > self.pool.allocator.capacity:
            raise ValueError(
                f"prompt needs {n_prompt_pages} pages; arena capacity "
                f"is {self.pool.allocator.capacity} (chunked bundles "
                "are prompt-only, so the decode budget no longer "
                "counts against this arena)"
            )
        t_req = time.perf_counter()
        deadline = time.monotonic() + 600.0
        with self._cv:
            t_lock = time.perf_counter()
            queue_s = t_lock - t_req
            # Admission-ordering guard: never promise more pages than
            # the arena holds, so every admitted prefill can finish
            # once its peers export. Blocks instead of deadlocking.
            # Deliberately does NOT wait out an in-flight chunk's
            # device call: start_chunked is host-only bookkeeping
            # (even the shared-prefix attach is deferred into the
            # first chunk_step's busy window), so admission slips in
            # mid-chunk — the door wait is lock + capacity, never
            # someone else's compute.
            while (
                self._reserved + n_prompt_pages
                > self.pool.allocator.capacity
            ):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        "prefill arena oversubscribed — in-flight "
                        "chunked admissions never drained"
                    )
                self._cv.wait(0.25)
            job_index = self._job_index
            self._job_index += 1
            t0 = time.monotonic()
            # Raise-capable work (rng fold, start_chunked) runs AFTER
            # the reservation only under exception cover: a failure
            # here must hand back the counters it bumped, or the door
            # predicate above wedges every later admission (TPU019/
            # TPU021 — the queue-wait-leak bug class from PR 11).
            rng = jax.random.fold_in(
                jax.random.key(self._seed_base), job_index
            )
            self._reserved += n_prompt_pages
            self.prefill_inflight += 1
            cp = None
            try:
                cp = self.pool.start_chunked(
                    prompt, len(prompt), rng, self.prefill_chunk_pages
                )
                if cp.resumed:
                    self.prefill_resumes += 1
                admit_s = time.perf_counter() - t_lock
            except BaseException:
                # abandon_chunked may itself raise; the counter
                # restitution must survive that or the door predicate
                # wedges (TPU021).
                try:
                    if cp is not None:
                        self.pool.abandon_chunked(cp)
                finally:
                    self._reserved -= n_prompt_pages
                    self.prefill_inflight -= 1
                    self._cv.notify_all()
                raise
        chunk_w = max(1, self.prefill_chunk_pages) * self.pool.page
        token = None
        try:
            token = _ChunkTicket(
                remaining=-(-(len(prompt) - cp.cursor) // chunk_w),
                seq=job_index,
            )
            queue_chunks_s = 0.0
            compute_s = 0.0
            t_mark = time.perf_counter()
            with self._cv:
                self._rr.append(token)
                self._cv.notify_all()
            while True:
                with self._cv:
                    token.blocked = False
                    while self._chunk_busy or self._turn() is not token:
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                "prefill chunk turn starved — peers "
                                "never yielded the engine"
                            )
                        self._cv.wait(0.25)
                        token.blocked = False
                    t_got = time.perf_counter()
                    queue_chunks_s += t_got - t_mark
                    # The device call runs with the mutex RELEASED
                    # (see _unlocked); _chunk_busy keeps it exclusive
                    # while admissions slip in between.
                    self._chunk_busy = True
                    try:
                        status = self.pool.chunk_step(
                            cp, unlocked=self._unlocked
                        )
                    finally:
                        self._chunk_busy = False
                    token.remaining = -(
                        -(len(prompt) - cp.cursor) // chunk_w
                    )
                    if status == "stalled":
                        # Trie-held pages from peers' checkpoints own
                        # the arena right now; stand aside and wait
                        # for an export or an abandon to free some.
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                "prefill arena exhausted mid-chunk — "
                                "no peer freed pages in time"
                            )
                        token.blocked = True
                        self._cv.notify_all()
                        self._cv.wait(0.25)
                        t_mark = time.perf_counter()
                        continue
                    t_chunk = time.perf_counter()
                    compute_s += t_chunk - t_got
                    self.prefill_chunks += 1
                    self._events.emit(
                        "serve_prefill_chunk",
                        prompt_tokens=len(prompt), cursor=cp.cursor,
                        final=status == "done",
                        chunk_s=round(t_chunk - t_got, 6),
                    )
                    if status == "done":
                        slot = 0  # transient: finalize->export->release
                        self.pool.finalize_chunked(slot, cp, max_new - 1)
                        t_compute = time.perf_counter()
                        compute_s += t_compute - t_chunk
                        state = self.pool.export_slot(
                            slot, page_ids=cp.page_ids
                        )
                        self.pool.release_slot(slot)
                        # The slot owned (and just released) the pages;
                        # empty the cursor so a late failure's abandon
                        # can't double-release them.
                        cp.page_ids = []
                        export_s = time.perf_counter() - t_compute
                        # Done with chunk turns — free the head slot
                        # now so peers don't idle through the bundle
                        # encode below.
                        self._rr.remove(token)
                        self._cv.notify_all()
                        break
                    self._cv.notify_all()
                t_mark = time.perf_counter()
            stages = {
                "queue": round(queue_s, 6),
                "admit": round(admit_s, 6),
                "queue_chunks": round(queue_chunks_s, 6),
                "compute": round(compute_s, 6),
                "export": round(export_s, 6),
            }
            tmeta: Dict[str, Any] = {
                "stages": stages,
                "wall_s": round(
                    queue_s + admit_s + queue_chunks_s + compute_s
                    + export_s, 6
                ),
            }
            if ctx is not None:
                tmeta.update(ctx.meta())
            state["trace"] = tmeta
            state["prompt"] = [int(t) for t in prompt]
            if session:
                state["session"] = str(session)
            data = encode_bundle(state)
            self.migrations += 1
            self.migration_bytes += len(data)
            reqtrace.stage(
                self._tracer, ctx, "req_queue_wait", queue_s,
                role="prefill",
            )
            reqtrace.stage(
                self._tracer, ctx, "req_admit", admit_s,
                role="prefill", shared_pages=cp.shared_n,
            )
            reqtrace.stage(
                self._tracer, ctx, "req_queue_chunks", queue_chunks_s,
                role="prefill", chunks=cp.n_chunks,
            )
            reqtrace.stage(
                self._tracer, ctx, "req_prefill_compute", compute_s,
                prompt_tokens=len(prompt),
            )
            reqtrace.stage(
                self._tracer, ctx, "req_page_export", export_s,
                pages=state["n_pages"],
            )
            fields = dict(
                pages=state["n_pages"], bytes=len(data),
                wall_s=round(time.monotonic() - t0, 6),
                direction="export", shared_pages=cp.shared_n,
            )
            if ctx is not None:
                fields["trace"] = ctx.trace_id
            self._events.emit("serve_migration", **fields)
            return data
        except BaseException:
            with self._cv:
                # Abandon keeps trie-checkpointed full pages held:
                # a re-submitted identical prompt resumes from the
                # last completed page instead of restarting.
                self.pool.abandon_chunked(cp)
            raise
        finally:
            with self._cv:
                # Counters first: nothing before them may raise, or a
                # failed ticket teardown would wedge the door
                # predicate forever (TPU021).
                self._reserved -= n_prompt_pages
                self.prefill_inflight -= 1
                if token is not None and token in self._rr:
                    # failure paths still hold a queue ticket
                    self._rr.remove(token)
                self._cv.notify_all()


class DecodeEngine:
    """One decode replica: bundle import + continuous chunked decode.

    ``submit`` splices a bundle into a free slot; ``collect`` drives
    shared decode chunks (all active slots advance together — the
    same continuous-batching math as the slot scheduler) until that
    slot's budget is spent, then frees its pages."""

    def __init__(
        self,
        model,
        params,
        *,
        sampling,
        page: int,
        kv_quant: str = "",
        n_slots: int = 4,
        arena_pages: Optional[int] = None,
        eos_id: Optional[int] = None,
        seed_base: int = 0,
        chunk: int = 4,
        spec_k: int = 0,
        spec_min_accept: float = 0.25,
        prefill_chunk_pages: int = 0,
        piggyback: float = 0.0,
        spill=None,
        affinity_k: int = 0,
        events=None,
        tracer=None,
    ):
        from tpufw.infer.pages import PagedSlotPool

        cache_len = int(model.cfg.max_seq_len)
        per_row = cache_len // page
        pages = arena_pages or n_slots * per_row + 1
        pool_model, row_model = _paged_models(model, page, kv_quant, pages)
        # Prefix trie on the decode side ONLY with piggyback prefill
        # enabled: the splice path never trie-registers (a hold would
        # pin migrated pages past their row), but piggybacked chunked
        # prefills checkpoint into the trie exactly like a prefill
        # replica's — which is what the router's prefix-affinity
        # steering keys on at the decode pool.
        piggy = bool(
            max(0, int(prefill_chunk_pages)) and float(piggyback) > 0
        )
        self.pool = PagedSlotPool.create_paged(
            pool_model, row_model, params, n_slots,
            sampling=sampling, eos_id=eos_id, prefix_cache=piggy,
        )
        self.page = page
        self.n_slots = n_slots
        self.chunk = max(1, chunk)
        self._eos = eos_id
        self._seed_base = seed_base
        self._chunk_index = 0
        self._job_index = 0
        # Prefill/decode fungibility: with a chunk size and a spare-
        # capacity waterline set, this replica accepts RAW prompts
        # (no prefill hop, no bundle) and prefills them chunk-by-chunk
        # inside the same passes that advance its decode slots — the
        # router's piggyback path under prefill-side load skew.
        self.prefill_chunk_pages = max(0, int(prefill_chunk_pages))
        self.piggyback = max(0.0, float(piggyback))
        self._events = events if events is not None else obs_events.NULL
        self._tracer = tracer if tracer is not None else obs_trace.NULL
        # KV fabric: spill tier (trie pages under piggyback, session
        # bundles at drain — "session" entries persist to the shared
        # directory the router re-homes from), affinity digests, and
        # the drain latch that turns scale-in into migration.
        self._spill = spill
        self._affinity_k = max(0, int(affinity_k))
        self._digest_cache: Dict[str, Any] = {}
        if spill is not None:
            attach_spill(self.pool, spill, events=self._events)
        self._draining = False
        # Set (lock-free, atomic attribute write) by drain() BEFORE it
        # contends for ``_cv``: the collect loop holds the lock across
        # chunks, so without a yield point the drain could only latch
        # in the submit->collect gap. The loop checks this flag at
        # every chunk boundary and waits the lock away so the export
        # sees the slots live.
        self._drain_pending = False
        self.sessions_drained = 0
        self.sessions_resumed = 0
        # Speculative self-drafting (n-gram proposals against the
        # request's own history, verified by spec_steps' single
        # jitted pass). No draft model on a replica — the monolithic
        # scheduler owns that path; here speculation must cost zero
        # extra HBM so migration parity stays trivial.
        self.spec_k = max(0, int(spec_k))
        self._ema = None
        self.spec_passes = 0
        if self.spec_k:
            from tpufw.infer.speculative import AcceptEMA

            if self.spec_k + 1 > page:
                raise ValueError(
                    f"spec_k={self.spec_k} needs spec_k+1 <= page="
                    f"{page} (verify writes one block per pass)"
                )
            rp = getattr(sampling, "repetition_penalty", None)
            if rp is not None and rp != 1.0:
                # Acceptance at position j changes the penalized
                # distribution at j+1 — speculation can't honour the
                # penalty, so this replica runs plain chunks.
                self._events.emit(
                    "serve_spec", level="warn", k=self.spec_k,
                    mode="plain_fallback", reason="repetition_penalty",
                )
                self.spec_k = 0
            else:
                self._ema = AcceptEMA(
                    n_slots, min_accept=spec_min_accept,
                )
        self._cv = threading.Condition()
        #: slot -> {"tokens": [...], "budget": int, "done": bool} plus
        #: the reqtrace bookkeeping collect_ex reports (splice_s,
        #: first_flush_s, n_chunks, ctx).
        self._jobs: Dict[int, Dict[str, Any]] = {}
        self.migrations = 0
        self.migration_bytes = 0

    # ---- router signals -------------------------------------------

    def signals(self) -> Dict[str, Any]:
        # wire: produces role-signals
        a = self.pool.allocator
        with self._cv:
            active = len(self._jobs)
            inflight = sum(
                1 for j in self._jobs.values()
                if j.get("cp") is not None
            )
        sig = {
            "role": "decode",
            "pages_total": a.capacity,
            "pages_in_use": a.in_use,
            "slots_total": self.n_slots,
            "slots_active": active,
            "migrations": self.migrations,
        }
        if self.spec_k:
            sig["spec_k"] = self.spec_k
            sig["spec_passes"] = self.spec_passes
        if self.prefill_chunk_pages and self.piggyback:
            sig["prefill_chunk_pages"] = self.prefill_chunk_pages
            sig["piggyback_waterline"] = self.piggyback
            sig["prefill_inflight"] = inflight
        # Draining rides the signals so the router stops steering new
        # work here the moment the drain latch flips (the reprobe after
        # a failed decode reads this too).
        sig["draining"] = 1 if self._draining else 0
        if self.sessions_drained or self.sessions_resumed:
            sig["sessions_drained"] = self.sessions_drained
            sig["sessions_resumed"] = self.sessions_resumed
        _fabric_signals(sig, self.pool, self._spill)
        if self._affinity_k and self.pool.prefix is not None:
            # wire: produces role-signals via prefix_digests
            sig["prefix_digests"] = advertised_digests(
                self.pool, self._spill, self._affinity_k,
                self._digest_cache,
            )
        return sig

    def can_accept(self, n_pages: int) -> bool:
        with self._cv:
            if self._draining or len(self._jobs) >= self.n_slots:
                return False
            deficit = self._cp_deficit_locked()
        return n_pages + deficit <= self.pool.allocator.n_free

    def _cp_deficit_locked(self) -> int:
        """Pages still owed to in-flight piggyback prefills (caller
        holds ``_cv``). Admissions that would eat into this sum are
        refused — the chunked rows must always be able to finish."""
        return sum(
            j["cp"].deficit for j in self._jobs.values()
            if j.get("cp") is not None
        )

    def can_piggyback(self, n_pages: int) -> bool:
        """Would ``submit_raw`` accept a raw prompt needing
        ``n_pages`` right now? Mirrors its admission test: pages must
        FIT (hard feasibility — this row plus every in-flight chunked
        deficit inside the arena), and the pool's idle-slot fraction
        must clear the ``piggyback`` waterline. Slots, not pages, are
        the waterline currency: a decode pass computes every slot row
        whether occupied or not, so "spare chunk capacity" IS idle
        slots — a mostly-empty arena on a fully-busy pool has no spare
        compute to scavenge."""
        if not (self.prefill_chunk_pages and self.piggyback):
            return False
        a = self.pool.allocator
        with self._cv:
            n_jobs = len(self._jobs)
            if self._draining or n_jobs >= self.n_slots:
                return False
            deficit = self._cp_deficit_locked()
        return (
            a.n_free - deficit - n_pages >= 0
            and self.n_slots - n_jobs
            >= self.piggyback * self.n_slots
        )

    # ---- bundle import --------------------------------------------

    def submit(self, data: bytes) -> int:
        """Import a serialized bundle; returns the slot handle for
        ``collect``. BundleError/ValueError mean the bundle was
        rejected with the arena untouched."""
        # wire: consumes bundle-header via state
        t0 = time.monotonic()
        t0p = time.perf_counter()
        state = decode_bundle(data)
        ctx = reqtrace.parse(state.get("trace"))
        ctx = ctx.child() if ctx is not None else None
        # Resumed session bundle (drain export): seed the emitted list
        # so the client receives one continuous sequence, and lift the
        # budget by the tokens already emitted so the budget_left math
        # (budget - (len(tokens) - 1)) lands exactly at the origin
        # replica's remaining count — zero-divergence resumption.
        emitted = state.get("tokens")
        resumed = isinstance(emitted, list) and len(emitted) > 0
        if resumed:
            tokens0 = [int(t) for t in emitted]
            budget0 = int(state["remaining"]) + len(tokens0) - 1
        else:
            tokens0 = [int(state["token"])]
            budget0 = int(state["remaining"])
        with self._cv:
            if self._draining:
                raise RuntimeError(
                    "decode replica draining — no new admissions"
                )
            free = [
                s for s in range(self.n_slots) if s not in self._jobs
            ]
            if not free:
                raise RuntimeError("decode replica: no free slot")
            slot = free[0]
            # Chunked prefill engines export prompt-only bundles
            # (n_pages covers the prompt, not the decode budget): the
            # decode side owns the residency decision, so size the
            # grant for the row's full life. Monolithic bundles
            # already carry their budget pages — the max is a no-op.
            n_alloc = max(
                int(state["n_pages"]),
                self.pool.n_pages_for(
                    int(state["cache_index"]) + int(state["remaining"])
                ),
            )
            deficit = self._cp_deficit_locked()
            if deficit and self.pool.allocator.n_free - n_alloc < deficit:
                raise RuntimeError(
                    "decode replica: bundle would starve an in-flight "
                    f"piggyback prefill ({n_alloc} pages wanted, "
                    f"{deficit} owed, {self.pool.allocator.n_free} free)"
                )
            ids = self.pool.allocator.alloc(n_alloc)
            if ids is None:
                raise RuntimeError(
                    "decode replica: arena cannot fit the bundle "
                    f"({n_alloc} pages, "
                    f"{self.pool.allocator.n_free} free)"
                )
            try:
                self.pool.splice_slot(slot, state, ids)
            except Exception:
                self.pool.allocator.release(ids)
                raise
            splice_s = time.perf_counter() - t0p
            job = {
                "tokens": tokens0,
                "budget": budget0,
                "done": bool(state["done"])
                or int(state["remaining"]) <= 0,
                # Prompt ids when the producer shipped them (optional
                # header field): the n-gram self-draft mines proposals
                # from prompt + generated history.
                "history": [
                    int(t) for t in (state.get("prompt") or [])
                ],
                # Sticky session id (optional header field): drain
                # exports this slot under it so the router can re-home.
                "session": state.get("session") or None,
                "ctx": ctx,
                "splice_s": splice_s,
                # perf_counter at splice end: first_flush measures
                # from here to the first decode-chunk extension.
                "t_ready": time.perf_counter(),
                "first_flush_s": None,
                "n_chunks": 0,
            }
            self._jobs[slot] = job
            if self._ema is not None and not job["done"]:
                self._ema.occupy(slot)
            if job["done"]:
                # Prefill already finished this request (EOS as the
                # first sampled token, or a zero budget): no decode
                # chunk will ever retire the slot, so free its pages
                # here or they leak until the arena saturates.
                self.pool.release_slot(slot)
                # The first (and only) token arrived inside the
                # bundle — it is flushed the moment the splice lands.
                job["first_flush_s"] = 0.0
            if resumed:
                self.sessions_resumed += 1
            self.migrations += 1
            self.migration_bytes += len(data)
            self._cv.notify_all()
        reqtrace.stage(
            self._tracer, ctx, "req_splice", splice_s,
            pages=int(state["n_pages"]), slot=slot,
        )
        fields = dict(
            pages=int(state["n_pages"]), bytes=len(data),
            wall_s=round(time.monotonic() - t0, 6),
            direction="import",
        )
        if ctx is not None:
            fields["trace"] = ctx.trace_id
        self._events.emit("serve_migration", **fields)
        return slot

    def submit_raw(
        self, prompt: Sequence[int], max_new: int, trace=None,
        session: Optional[str] = None,
    ) -> int:
        """Piggyback admission: accept a RAW prompt — no prefill hop,
        no bundle migration — and prefill it chunk-by-chunk inside the
        same passes that advance the resident decode slots. Admission
        requires the pool's idle-slot fraction to clear the
        ``piggyback`` waterline AND the arena to fit this row's full
        page need on top of every in-flight piggyback deficit, so
        resident decodes keep headroom and chunked rows can always
        finish. Raises RuntimeError when the waterline (or a free
        slot, or the pages) is missing; the router falls back to the
        dedicated-prefill path."""
        # wire: consumes control-frame via prompt
        import jax

        if not (self.prefill_chunk_pages and self.piggyback):
            raise RuntimeError(
                "piggyback admission disabled — needs both "
                "TPUFW_SERVE_PREFILL_CHUNK and TPUFW_SERVE_PIGGYBACK"
            )
        ctx = reqtrace.parse(trace)
        ctx = ctx.child() if ctx is not None else None
        prompt = [int(t) for t in prompt]
        need = len(prompt) + max_new - 1
        n_total = self.pool.n_pages_for(need)
        a = self.pool.allocator
        if n_total > a.capacity:
            raise ValueError(
                f"prompt+budget needs {n_total} pages; arena "
                f"capacity is {a.capacity}"
            )
        with self._cv:
            if self._draining:
                raise RuntimeError(
                    "decode replica draining — no new admissions"
                )
            free = [
                s for s in range(self.n_slots) if s not in self._jobs
            ]
            if not free:
                raise RuntimeError("decode replica: no free slot")
            deficit = self._cp_deficit_locked()
            if a.n_free - deficit - n_total < 0:
                raise RuntimeError(
                    "decode replica: arena cannot seat the row — "
                    f"{a.n_free} free minus {deficit} owed leaves "
                    f"less than the {n_total} pages wanted"
                )
            if (
                self.n_slots - len(self._jobs)
                < self.piggyback * self.n_slots
            ):
                raise RuntimeError(
                    "decode replica: piggyback waterline — "
                    f"{self.n_slots - len(self._jobs)} idle of "
                    f"{self.n_slots} slots clears less than "
                    f"{self.piggyback:.0%}"
                )
            slot = free[0]
            job_index = self._job_index
            self._job_index += 1
            # Same stream a dedicated prefill replica would draw, so a
            # piggybacked request samples identically to a migrated one.
            rng = jax.random.fold_in(
                jax.random.key(self._seed_base), job_index
            )
            cp = self.pool.start_chunked(
                prompt, need, rng, self.prefill_chunk_pages
            )
            self._jobs[slot] = {  # resource: transfers pages
                "tokens": [],
                "budget": max_new - 1,
                "done": False,
                "history": list(prompt),
                "session": str(session) if session else None,
                "ctx": ctx,
                "splice_s": 0.0,
                "t_ready": time.perf_counter(),
                "first_flush_s": None,
                "n_chunks": 0,
                "cp": cp,
                "prefill_s": 0.0,
                "prefill_queue_s": 0.0,
                "prefill_chunks": 0,
            }
            self._cv.notify_all()
        reqtrace.stage(
            self._tracer, ctx, "req_piggyback_admit", 0.0,
            slot=slot, pages=n_total,
        )
        return slot

    # ---- drain (scale-in / SIGTERM) -------------------------------

    def drain(self) -> Dict[str, Any]:
        """Turn scale-down from "drop sessions" into "migrate them":
        latch the drain flag (admissions start refusing), export every
        live session's slot as a spill bundle to the session store
        (``SpillTier`` persists kind "session" to the shared
        directory), release the slots, and mark the jobs drained so
        in-flight ``collect_ex`` calls return immediately with the
        ``drained`` flag. The router re-homes each sticky session onto
        a surviving replica, which restores through the normal splice
        path — zero token divergence under greedy decode (the engine
        default). Sessions mid-piggyback-prefill (no slot yet) and
        sessionless jobs have nothing to resume; their partial work is
        dropped and the caller sees a plain drained reply. Idempotent:
        a second drain finds no live jobs."""
        # wire: produces session-bundle via spill-tier
        t0 = time.monotonic()
        exported: List[str] = []
        dropped = 0
        # Ask the chunk-driving collector (which holds _cv across
        # device calls) to yield at its next chunk boundary — without
        # this the drain only ever latches between requests.
        self._drain_pending = True
        with self._cv:
            self._drain_pending = False
            self._draining = True
            for slot, job in list(self._jobs.items()):
                if job["done"]:
                    continue
                session = job.get("session")
                cp = job.get("cp")
                if cp is not None:
                    # resource: releases pages
                    self.pool.abandon_chunked(cp)
                    job["cp"] = None
                    dropped += 1
                elif session and self._spill is not None:
                    # Export BEFORE release: after release the table
                    # row is zeroed and the pages may be reassigned.
                    state = self.pool.export_slot(slot)
                    state["session"] = str(session)
                    state["tokens"] = [int(t) for t in job["tokens"]]
                    if job.get("history"):
                        state["prompt"] = [
                            int(t) for t in job["history"]
                        ]
                    data = encode_bundle(state)
                    self._spill.put(
                        "session", str(session), data,
                        int(state["n_pages"]),
                    )
                    self.pool.release_slot(slot)
                    if self._ema is not None:
                        self._ema.vacate(slot)
                    self.sessions_drained += 1
                    exported.append(str(session))
                else:
                    self.pool.release_slot(slot)
                    if self._ema is not None:
                        self._ema.vacate(slot)
                    dropped += 1
                job["done"] = True
                job["drained"] = True
            self._cv.notify_all()
        self._events.emit(
            "serve_spill", entry="session", direction="out",
            sessions=len(exported), dropped=dropped,
            wall_s=round(time.monotonic() - t0, 6),
        )
        return {
            "drained": True, "sessions": exported, "dropped": dropped,
        }

    # ---- decode loop ----------------------------------------------

    def _run_prefill_chunks_locked(self) -> bool:
        """Advance every piggybacked prefill by one page-aligned chunk
        (caller holds ``_cv``). A finished prefill finalizes into its
        slot and joins the next decode pass — mixed prefill+decode
        pools, no separate tick. Returns whether any chunk ran."""
        progressed = False
        for slot, job in list(self._jobs.items()):
            cp = job.get("cp")
            if cp is None or job["done"]:
                continue
            t0 = time.perf_counter()
            status = self.pool.chunk_step(cp)
            if status == "stalled":
                continue  # retry after a peer frees pages
            dt = time.perf_counter() - t0
            progressed = True
            job["prefill_s"] += dt
            job["prefill_chunks"] += 1
            self._events.emit(
                "serve_prefill_chunk",
                prompt_tokens=len(cp.prompt), cursor=cp.cursor,
                final=status == "done", chunk_s=round(dt, 6),
                slot=slot,
            )
            if status != "done":
                continue
            job["cp"] = None
            job["tokens"] = [cp.first_int]
            t1 = time.perf_counter()
            job["prefill_queue_s"] = max(
                0.0, (t1 - job["t_ready"]) - job["prefill_s"]
            )
            job["first_flush_s"] = t1 - job["t_ready"]
            reqtrace.stage(
                self._tracer, job["ctx"], "req_first_token",
                job["first_flush_s"], slot=slot,
            )
            if cp.done0 or job["budget"] <= 0:
                # EOS as the first sampled token (or a zero budget):
                # complete before ever owning a pool slot, so the
                # pages go straight back — no trie here, abandon
                # frees everything.
                job["done"] = True
                self.pool.abandon_chunked(cp)
            else:
                self.pool.finalize_chunked(slot, cp, job["budget"])
                if self._ema is not None:
                    self._ema.occupy(slot)
        return progressed

    def _run_chunk_locked(self) -> None:
        """One shared decode chunk (caller holds ``_cv``). Every
        active slot advances; retired slots free their pages.

        With ``spec_k`` set the pass may run speculatively: n-gram
        proposals from each slot's history, verified in ONE target
        call, per-slot advance = its own accept count (+1 bonus).
        The acceptance EMA decides spec-vs-plain per pass, so
        low-yield traffic degrades to plain chunks and periodically
        re-probes — a migrated request decodes bit-equal either way
        (greedy verify is exact)."""
        import jax
        import numpy as np

        progressed = self._run_prefill_chunks_locked()
        live = {
            s: j for s, j in self._jobs.items()
            if not j["done"] and j.get("cp") is None
        }
        if not live:
            if not progressed and any(
                j.get("cp") is not None for j in self._jobs.values()
            ):
                # Every piggyback prefill is stalled on pages and no
                # decode slot is live to free any: sleep on the
                # condition instead of spinning until a release lands.
                # tpulint: disable=TPU020 — deliberate timed backoff,
                # not a predicate wait: the caller's collect loop IS
                # the enclosing retry loop, and a spurious wakeup just
                # re-polls the stall condition one tick early.
                self._cv.wait(0.001)
            return
        use_spec = self._ema is not None and self._ema.use_spec(
            sorted(live)
        )
        k = self.spec_k if use_spec else self.chunk
        t0 = time.perf_counter()
        key = jax.random.fold_in(
            jax.random.key(self._seed_base + 1), self._chunk_index
        )
        chunk_index = self._chunk_index
        self._chunk_index += 1
        n_emit = accept = None
        if use_spec:
            from tpufw.infer import speculative as spec_mod

            props = np.zeros((self.n_slots, k), np.int32)
            for slot, job in live.items():
                props[slot] = spec_mod.ngram_propose(
                    job["history"] + job["tokens"], k
                )
            out, n_emit, accept = self.pool.spec_steps(props, key)
            out = np.asarray(out)
            n_emit = np.asarray(n_emit)
            accept = np.asarray(accept)
        else:
            out = np.asarray(
                # tpulint: disable=TPU003 — exclusive if/else arms:
                # exactly ONE of spec_steps/decode_steps consumes this
                # chunk's key.
                self.pool.decode_steps(jax.random.split(key, k))
            )
        t1 = time.perf_counter()
        chunk_s = t1 - t0
        accept_frac = 0.0
        for slot, job in live.items():
            budget_left = job["budget"] - (len(job["tokens"]) - 1)
            if use_spec:
                take = min(int(n_emit[slot]), budget_left)
                row = out[slot, :take].tolist()
                self._ema.update(slot, int(accept[slot]) / k)
                accept_frac += int(accept[slot]) / k
            else:
                row = out[slot].tolist()[: min(k, budget_left)]
            if self._eos is not None and self._eos in row:
                row = row[: row.index(self._eos) + 1]
            job["tokens"].extend(row)
            job["n_chunks"] += 1
            if row and job["first_flush_s"] is None:
                # First decode tokens for this request just became
                # host-visible: the splice->flush gap is the decode
                # side's contribution to TTFT beyond the first
                # (bundled) token.
                job["first_flush_s"] = t1 - job["t_ready"]
                reqtrace.stage(
                    self._tracer, job["ctx"], "req_first_token",
                    job["first_flush_s"], slot=slot,
                )
            reqtrace.stage(
                self._tracer, job["ctx"], "req_decode_chunk", chunk_s,
                slot=slot, chunk_index=chunk_index,
                new_tokens=len(row),
            )
            if (
                len(job["tokens"]) - 1 >= job["budget"]
                or (self._eos is not None and row
                    and row[-1] == self._eos)
            ):
                job["done"] = True
                self.pool.release_slot(slot)
                if self._ema is not None:
                    self._ema.vacate(slot)
        if use_spec:
            self.spec_passes += 1
            self._events.emit(
                "serve_spec", k=k, mode="pass", rows=len(live),
                accept_rate=round(accept_frac / len(live), 4),
            )
        self._cv.notify_all()

    def collect(self, slot: int, timeout: float = 600.0) -> List[int]:
        """Block until ``slot``'s request completes; returns its full
        token list (first token included). Exactly one caller drives
        chunks at a time; other waiters sleep on the condition."""
        return self.collect_ex(slot, timeout)["tokens"]

    def collect_ex(
        self, slot: int, timeout: float = 600.0
    ) -> Dict[str, Any]:
        """``collect`` plus the decode-side stage timings the router
        folds into the request's TTFT decomposition: ``splice_s``
        (bundle parse + page alloc + splice), ``first_flush_s``
        (splice end -> first decode-chunk flush; 0.0 when the bundled
        token already finished the request), ``n_chunks``."""
        # wire: produces decode-reply
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                job = self._jobs.get(slot)
                if job is None:
                    raise KeyError(f"no active job in slot {slot}")
                if job["done"]:
                    del self._jobs[slot]
                    out = {
                        "tokens": job["tokens"],
                        "splice_s": round(job["splice_s"], 6),
                        "first_flush_s": round(
                            job["first_flush_s"] or 0.0, 6
                        ),
                        "n_chunks": job["n_chunks"],
                    }
                    if "prefill_chunks" in job:
                        # Piggybacked request: the replica did its
                        # prefill too — stage timings for the
                        # router's TTFT decomposition.
                        out["piggyback"] = True
                        out["prefill_s"] = round(job["prefill_s"], 6)
                        out["prefill_queue_s"] = round(
                            job["prefill_queue_s"], 6
                        )
                        out["prefill_chunks"] = job["prefill_chunks"]
                    if job.get("drained"):
                        # The replica drained mid-request: the reply
                        # carries the drained flag (+ session id when
                        # resumable) so the router re-homes instead of
                        # returning a truncated generation.
                        out["drained"] = True
                        if job.get("session"):
                            out["session"] = job["session"]
                    return out
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"slot {slot} did not finish in {timeout}s"
                    )
                if self._drain_pending:
                    # A drain is blocked on this lock: yield it for a
                    # beat so the export runs against live slots.
                    # tpulint: disable=TPU020 — deliberate timed
                    # yield, not a predicate wait: this loop IS the
                    # retry loop, and the drain marks the job done
                    # before the wait expires.
                    self._cv.wait(0.002)
                    continue
                self._run_chunk_locked()


# -------------------------------------------------- role entrypoints

def role_telemetry(role: str):
    """(events, tracer) for a replica role from TPUFW_TELEMETRY_DIR —
    per-role files (``events-<role>.jsonl`` / ``trace-<role>.json``)
    so the fleet's artifacts land side by side for trace_merge to
    stitch by trace_id. Null implementations when the dir is unset."""
    tdir = env_opt_str("telemetry_dir")
    if not tdir:
        return obs_events.NULL, obs_trace.NULL
    os.makedirs(tdir, exist_ok=True)
    events = obs_events.EventLog(
        os.path.join(tdir, f"events-{role}.jsonl")
    )
    tracer = obs_trace.Tracer(
        os.path.join(tdir, f"trace-{role}.json"),
        process_name=role, max_events=200_000,
    )
    return events, tracer


def _build_engine(role: str):
    """Construct the engine a replica container runs, from the same
    TPUFW_* contract the monolithic server reads."""
    from tpufw.infer import SamplingConfig
    from tpufw.workloads.serve import build_generator

    model, params, _cfg, restored = build_generator()
    page = env_int("serve_page", 16)
    kv_quant = env_str("serve_kv_quant", "")
    n_slots = max(1, env_int("serve_slots", 8))
    sampling = SamplingConfig(temperature=0.0)
    events, tracer = role_telemetry(role)
    # KV fabric: TPUFW_KV_SPILL pages of host RAM (0 = off) with
    # TPUFW_KV_SPILL_DIR as the overflow + session-store directory;
    # either knob alone enables the tier. The advertisement depth
    # matches the router's TPUFW_ROUTER_PREFIX_AFFINITY so both ends
    # hash the same k chunks.
    spill_pages = max(0, env_int("kv_spill", 0))
    spill_dir = env_str("kv_spill_dir", "")
    spill = None
    if spill_pages or spill_dir:
        from tpufw.infer.spill import SpillTier

        spill = SpillTier(spill_pages, spill_dir)
    common = dict(
        sampling=sampling, page=page, kv_quant=kv_quant,
        n_slots=n_slots, seed_base=env_int("seed", 0),
        prefill_chunk_pages=max(0, env_int("serve_prefill_chunk", 0)),
        spill=spill,
        affinity_k=max(0, env_int("router_prefix_affinity", 0)),
        events=events, tracer=tracer,
    )
    if role == "prefill":
        return PrefillEngine(model, params, **common), restored
    return (
        DecodeEngine(
            model, params,
            chunk=max(1, env_int("serve_chunk", 0)
                      or env_int("stream_chunk", 16)),
            spec_k=env_int("serve_spec_k", 0),
            spec_min_accept=env_float("serve_spec_min_accept", 0.25),
            piggyback=max(0.0, env_float("serve_piggyback", 0.0)),
            **common,
        ),
        restored,
    )


def serve_prefill(engine: PrefillEngine, port: int):
    """Framed-TCP prefill server: JSON request in, bundle out. The
    request's optional ``trace`` field (X-TPUFW-Trace wire form)
    flows into the engine so its stage spans correlate."""

    def handle(frame: bytes) -> bytes:
        # wire: consumes control-frame via req
        req = json.loads(frame.decode("utf-8"))
        if req.get("signals"):
            return json.dumps(engine.signals()).encode()
        prompt = req.get("prompt")
        max_new = req.get("max_new")
        if prompt is None or max_new is None:
            # A signals-shaped (or otherwise field-less) frame must
            # get a structured error reply, not a KeyError traceback
            # laundered through the accept loop.
            return json.dumps(
                {"error": "bad prefill frame: need prompt and max_new"}
            ).encode()
        return engine.prefill(
            [int(t) for t in prompt], int(max_new),
            trace=req.get("trace"), session=req.get("session"),
        )

    srv, bound = transport.serve_frames(port)
    threading.Thread(
        target=transport.accept_loop, args=(srv, handle), daemon=True
    ).start()
    return srv, bound


def serve_decode(engine: DecodeEngine, port: int):
    """Framed-TCP decode server: bundle in, JSON token list out (plus
    the decode-side stage timings — splice_s / first_flush_s /
    n_chunks — the router folds into its TTFT decomposition)."""

    def handle(frame: bytes) -> bytes:
        # wire: consumes control-frame via req
        if frame[:1] == b"{":  # JSON control frame (bundles open TPFB)
            req = json.loads(frame.decode("utf-8"))
            if req.get("signals"):
                return json.dumps(engine.signals()).encode()
            if req.get("drain"):
                # Scale-in hook (manifest 13's preStop + kv_smoke):
                # export live sessions to the store, refuse new work.
                # wire: produces control-frame via drain-reply
                return json.dumps(engine.drain()).encode()
            if req.get("prompt") is not None:
                # Raw-prompt piggyback admission: the router steers
                # here when spare chunk capacity clears the waterline.
                try:
                    slot = engine.submit_raw(
                        [int(t) for t in req["prompt"]],
                        int(req.get("max_new", 1)),
                        trace=req.get("trace"),
                        session=req.get("session"),
                    )
                except (ValueError, RuntimeError) as e:
                    return json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode()
                out = engine.collect_ex(slot)
                return json.dumps({**out, **engine.signals()}).encode()
            return json.dumps({"error": "expected a page bundle"}).encode()
        try:
            slot = engine.submit(frame)
        except (BundleError, ValueError, RuntimeError) as e:
            return json.dumps(
                {"error": f"{type(e).__name__}: {e}"}
            ).encode()
        out = engine.collect_ex(slot)
        return json.dumps({**out, **engine.signals()}).encode()

    srv, bound = transport.serve_frames(port)
    threading.Thread(
        target=transport.accept_loop, args=(srv, handle), daemon=True
    ).start()
    return srv, bound


def install_drain_handler(engine) -> None:
    """SIGTERM -> drain: kubelet sends TERM at pod deletion/scale-in
    (manifest 13 also hits the peer-port drain op from a preStop hook,
    belt and braces), so live sessions export to the session store,
    then the process lingers TPUFW_SERVE_DRAIN_GRACE_S seconds —
    enough for in-flight collect replies (carrying the ``drained``
    flag) to flush to the router — before exiting."""

    import signal

    def _on_term(signum, frame):
        try:
            engine.drain()
            time.sleep(max(0.0, env_float("serve_drain_grace_s", 5.0)))
        finally:
            raise SystemExit(0)

    signal.signal(signal.SIGTERM, _on_term)


def main_role(role: str) -> int:
    """Container entrypoint for TPUFW_SERVE_ROLE != "". Blocks
    forever (the pod's lifetime IS the replica's lifetime)."""
    if role == "router":
        from tpufw.serve.router import main_router

        return main_router()
    engine, restored = _build_engine(role)
    port = env_int("serve_peer_port", DEFAULT_PEER_PORT)
    if role == "prefill":
        srv, bound = serve_prefill(engine, port)
    elif role == "decode":
        srv, bound = serve_decode(engine, port)
        install_drain_handler(engine)
    else:
        raise ValueError(
            f"unknown TPUFW_SERVE_ROLE={role!r} "
            "(want prefill|decode|router or empty)"
        )
    print(json.dumps(
        {"serving_role": role, "port": bound, "restored": restored}
    ), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.close()
        engine._tracer.close()
        engine._events.close()
    return 0
