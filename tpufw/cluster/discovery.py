"""Replica discovery for the disaggregated-serving router: where do
the prefill and decode pools live?

Resolution order (first match wins), mirroring bootstrap.py's shape:

1. Explicit ``TPUFW_ROUTER_PREFILL`` / ``TPUFW_ROUTER_DECODE`` —
   comma-separated ``host:port`` lists. Escape hatch for tests,
   bare-metal, and the loopback CI smoke.
2. JobSet DNS: the disagg manifest (deploy/manifests/13-*) runs the
   prefill and decode pools as replicated jobs of ONE JobSet with
   ``enableDNSHostnames``, so replica ``i`` of job ``j`` is reachable
   at ``<jobset>-<j>-<i>-0.<jobset>`` (same convention bootstrap.py
   uses for the coordinator). ``TPUFW_ROUTER_PREFILL_REPLICAS`` /
   ``TPUFW_ROUTER_DECODE_REPLICAS`` give the counts; the replicated
   job names default to ``prefill`` / ``decode``.

Ports default to the replicas' ``TPUFW_SERVE_PEER_PORT`` contract.
"""

from __future__ import annotations

# tpulint: disable-file=TPU004 — like bootstrap.py, this module reads
# through an injectable ``env: Mapping`` (tests pass dicts) rather
# than the typed os.environ helpers. The knobs are cataloged in
# docs/ENV.md; the helper round-trip requirement stops at this
# discovery boundary.

import os
from typing import List, Mapping, Optional, Tuple

DEFAULT_PEER_PORT = 8477  # = tpufw.serve.roles.DEFAULT_PEER_PORT

Addr = Tuple[str, int]


def _parse_addr_list(spec: str, default_port: int) -> List[Addr]:
    out: List[Addr] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if sep:
            out.append((host, int(port)))
        else:
            out.append((part, default_port))
    return out


def _jobset_addrs(
    env: Mapping[str, str], job: str, count: int, port: int
) -> List[Addr]:
    jobset = env["JOBSET_NAME"]
    return [
        (f"{jobset}-{job}-{i}-0.{jobset}", port) for i in range(count)
    ]


def discover_replicas(
    env: Optional[Mapping[str, str]] = None,
) -> Tuple[List[Addr], List[Addr]]:
    """(prefill_addrs, decode_addrs) for the router's pools. Raises
    ValueError when neither the explicit lists nor a countable JobSet
    environment is present — a router with zero replicas must fail at
    startup, not 503 forever."""
    env = os.environ if env is None else env
    port = int(env.get("TPUFW_SERVE_PEER_PORT", DEFAULT_PEER_PORT))

    explicit_p = env.get("TPUFW_ROUTER_PREFILL", "")
    explicit_d = env.get("TPUFW_ROUTER_DECODE", "")
    if explicit_p or explicit_d:
        prefill = _parse_addr_list(explicit_p, port)
        decode = _parse_addr_list(explicit_d, port)
        if not prefill or not decode:
            raise ValueError(
                "TPUFW_ROUTER_PREFILL / TPUFW_ROUTER_DECODE must BOTH "
                "name at least one host:port (got "
                f"{len(prefill)} prefill, {len(decode)} decode)"
            )
        return prefill, decode

    if "JOBSET_NAME" in env:
        n_prefill = int(env.get("TPUFW_ROUTER_PREFILL_REPLICAS", "0"))
        n_decode = int(env.get("TPUFW_ROUTER_DECODE_REPLICAS", "0"))
        if n_prefill <= 0 or n_decode <= 0:
            raise ValueError(
                "JobSet environment detected (JOBSET_NAME set) but "
                "TPUFW_ROUTER_PREFILL_REPLICAS / "
                "TPUFW_ROUTER_DECODE_REPLICAS are missing — the "
                "deploy/ disagg manifest sets them to the replicated "
                "jobs' replica counts"
            )
        return (
            _jobset_addrs(env, "prefill", n_prefill, port),
            _jobset_addrs(env, "decode", n_decode, port),
        )

    raise ValueError(
        "no replica discovery source: set TPUFW_ROUTER_PREFILL + "
        "TPUFW_ROUTER_DECODE (host:port lists) or run inside the "
        "disagg JobSet"
    )
