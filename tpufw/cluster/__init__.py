from tpufw.cluster.bootstrap import (  # noqa: F401
    ClusterConfig,
    initialize_cluster,
    resolve_cluster_env,
)
from tpufw.cluster.discovery import discover_replicas  # noqa: F401
