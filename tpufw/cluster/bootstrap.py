"""Multi-host bootstrap: pod environment -> ``jax.distributed.initialize``.

This is the TPU-native replacement for NCCL env-var wiring (SURVEY.md §2c,
§5): each JobSet worker pod derives (coordinator_address, num_processes,
process_id) from its environment, calls ``jax.distributed.initialize``, and
from then on XLA emits ICI collectives inside the slice — DCN carries only
this bootstrap handshake.

Resolution order (first match wins):
1. Explicit ``TPUFW_*`` variables — escape hatch for tests/bare-metal.
2. JobSet + headless-Service environment (the deploy/ manifests set these
   from the downward API): JOBSET_NAME, REPLICATED_JOB_NAME,
   JOB_COMPLETION_INDEX, TPUFW_WORKERS_PER_SLICE, TPUFW_COORDINATOR_SVC.
3. GKE TPU node-pool conventions: TPU_WORKER_ID, TPU_WORKER_HOSTNAMES
   (comma-separated; worker 0 is the coordinator).
4. Single process (no distributed init) — BASELINE configs 1-3.

Worker identity must be *stable across pod restarts* (SURVEY.md §7.4 #2):
every source above is an index assigned by the controller (completion index
/ worker id), never a hostname hash, so a restarted pod rejoins with the
same process_id and the coordinator's barrier can release.
"""

from __future__ import annotations

# tpulint: disable-file=TPU004 — this module reads through an
# injectable ``env: Mapping`` (tests pass dicts), and its resolution
# order deliberately mixes TPUFW_* escape hatches with JobSet/GKE
# variables the typed helpers don't model. The knobs are cataloged in
# docs/ENV.md; the helper round-trip requirement stops at this
# process-bootstrap boundary.

import dataclasses
import os
import time
from typing import Mapping, Optional

DEFAULT_COORDINATOR_PORT = 8476


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    coordinator_address: Optional[str]  # None => single-process
    num_processes: int = 1
    process_id: int = 0
    source: str = "single"

    @property
    def is_distributed(self) -> bool:
        return self.coordinator_address is not None and self.num_processes > 1


def resolve_cluster_env(
    env: Optional[Mapping[str, str]] = None,
) -> ClusterConfig:
    env = os.environ if env is None else env

    if "TPUFW_COORDINATOR" in env:
        if "TPUFW_NUM_PROCESSES" not in env:
            # Same silent-gang-split hazard as the JobSet branch below: a
            # coordinator with a defaulted process count of 1 would no-op
            # the distributed init on every pod. Fail loudly instead.
            raise ValueError(
                "TPUFW_COORDINATOR is set but TPUFW_NUM_PROCESSES is "
                "missing — set it to the gang size (and TPUFW_PROCESS_ID "
                "per worker)"
            )
        return ClusterConfig(
            coordinator_address=env["TPUFW_COORDINATOR"],
            num_processes=int(env["TPUFW_NUM_PROCESSES"]),
            process_id=int(env.get("TPUFW_PROCESS_ID", "0")),
            source="explicit",
        )

    if "JOBSET_NAME" in env and "JOB_COMPLETION_INDEX" in env:
        if "TPUFW_WORKERS_PER_SLICE" not in env:
            # Defaulting to 1 would silently turn an N-pod gang into N
            # independent single-process runs; fail loudly instead.
            raise ValueError(
                "JobSet environment detected (JOBSET_NAME set) but "
                "TPUFW_WORKERS_PER_SLICE is missing — set it to the "
                "replicated job's worker count (deploy/ manifests do)"
            )
        num = int(env["TPUFW_WORKERS_PER_SLICE"])
        pid = int(env["JOB_COMPLETION_INDEX"])
        svc = env.get("TPUFW_COORDINATOR_SVC")
        if svc is None:
            # Headless-Service DNS for pod 0 of the replicated job:
            # <jobset>-<job>-0-0.<jobset> is the JobSet pod DNS convention.
            job = env.get("REPLICATED_JOB_NAME", "worker")
            svc = (
                f"{env['JOBSET_NAME']}-{job}-0-0.{env['JOBSET_NAME']}"
            )
        port = int(env.get("TPUFW_COORDINATOR_PORT", DEFAULT_COORDINATOR_PORT))
        return ClusterConfig(
            coordinator_address=f"{svc}:{port}",
            num_processes=num,
            process_id=pid,
            source="jobset",
        )

    if "TPU_WORKER_ID" in env and "TPU_WORKER_HOSTNAMES" in env:
        hosts = [
            h.strip()
            for h in env["TPU_WORKER_HOSTNAMES"].split(",")
            if h.strip()
        ]
        if not hosts:
            raise ValueError(
                "TPU_WORKER_HOSTNAMES is set but contains no hostnames"
            )
        port = int(env.get("TPUFW_COORDINATOR_PORT", DEFAULT_COORDINATOR_PORT))
        return ClusterConfig(
            coordinator_address=f"{hosts[0]}:{port}",
            num_processes=len(hosts),
            process_id=int(env["TPU_WORKER_ID"]),
            source="gke_tpu",
        )

    return ClusterConfig(coordinator_address=None)


def initialize_cluster(
    config: Optional[ClusterConfig] = None,
    timeout_s: float = 300.0,
) -> ClusterConfig:
    """Idempotent ``jax.distributed.initialize`` from the resolved env.

    Must run before any backend use. Single-process configs no-op, so
    workloads call this unconditionally (configs 1-3 need no changes to
    become config 4).
    """
    import jax

    config = config or resolve_cluster_env()
    if not config.is_distributed:
        return config
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:  # jax >= 0.5
        if is_init():
            return config
    else:  # jax 0.4.x: the client handle is the only initialized signal
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return config
    if config.process_id >= config.num_processes or config.process_id < 0:
        raise ValueError(
            f"process_id {config.process_id} out of range for "
            f"{config.num_processes} processes"
        )
    deadline = time.monotonic() + timeout_s
    last_err: Exception | None = None
    # Retry: during gang (re)starts the coordinator pod may come up last;
    # failing hard here would turn one slow pod into a crash loop.
    # tpulint: disable=TPU016 — intentional: every host loops on the SAME
    # rendezvous until it succeeds; initialize() carries its own timeout,
    # so a host whose clock runs out raises instead of silently diverging.
    while time.monotonic() < deadline:
        try:
            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
            return config
        except RuntimeError as e:
            msg = str(e).lower()
            # jax has raised both "already initialized" and "should only be
            # called once" for a repeat initialize across versions.
            if "already initialized" in msg or "called once" in msg:
                return config
            last_err = e
            time.sleep(min(5.0, max(0.5, deadline - time.monotonic())))
        except Exception as e:  # connection errors surface as various types
            last_err = e
            time.sleep(min(5.0, max(0.5, deadline - time.monotonic())))
    raise TimeoutError(
        f"jax.distributed.initialize failed for {config}: {last_err}"
    )
