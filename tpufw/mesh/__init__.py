from tpufw.mesh.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
    MESH_AXES,
    MeshConfig,
    build_mesh,
    logical_axis_rules,
    mesh_sharding,
)
