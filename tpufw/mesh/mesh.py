"""Device mesh + named-axis sharding: tpufw's communication backend.

The reference wires no communication backend at all — it is single-node,
single-GPU, and the north star names NCCL env-var wiring only as the thing to
*replace* (SURVEY.md §2c). tpufw's replacement is the TPU-idiomatic one: a
``jax.sharding.Mesh`` with five named axes, GSPMD/pjit sharding annotations,
and XLA-inserted collectives riding ICI. No user-level comm code exists
anywhere in this framework; every parallelism strategy is a (logical axis ->
mesh axis) rule set consumed here.

Axes
----
- ``data``     — pure data parallelism (gradient psum across replicas)
- ``pipe``     — pipeline parallelism over the layer stack (GPipe schedule,
                 point-to-point ppermute handoffs — tpufw.parallel.pipeline)
- ``fsdp``     — data parallelism with parameter/optimizer sharding (ZeRO-3
                 style: XLA all-gathers params per layer, reduce-scatters grads)
- ``sequence`` — context parallelism for long sequences (ring attention /
                 all-to-all, see tpufw.parallel)
- ``tensor``   — Megatron-style tensor parallelism inside a host's ICI domain
- ``expert``   — expert parallelism for MoE (Mixtral, BASELINE config 5)

Any axis of size 1 is free; configs 1-5 are all instances of one MeshConfig.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_DATA = "data"
AXIS_PIPE = "pipe"
AXIS_FSDP = "fsdp"
AXIS_SEQUENCE = "sequence"
AXIS_TENSOR = "tensor"
AXIS_EXPERT = "expert"

# Order matters: leftmost axes get the slowest-varying device dimension, so
# `tensor` (rightmost) stays within the densest ICI neighborhood and `data`
# (leftmost) spans hosts/DCN — the layout the scaling playbook prescribes.
# `pipe` sits next to `data`: stage handoffs are low-bandwidth point-to-point
# activations, the cheapest collective to push toward the sparse end.
MESH_AXES: tuple[str, ...] = (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for the five named mesh axes. -1 on at most one axis = "fill".

    ``dcn_data`` > 1 declares a multi-slice deployment: that many ICI
    slices joined over DCN, with pure data parallelism across slices (the
    only parallelism whose collectives amortize over DCN's bandwidth).
    The other five sizes then describe ONE slice; the built mesh's
    ``data`` axis has size ``dcn_data * data`` with DCN as the
    slowest-varying dimension, so every other axis's collectives stay
    inside a slice's ICI domain.
    """

    data: int = 1
    pipe: int = 1
    fsdp: int = -1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1
    dcn_data: int = 1

    def sizes(self, n_devices: int) -> dict[str, int]:
        """Per-slice axis sizes (n_devices = devices in one slice)."""
        raw = {
            AXIS_DATA: self.data,
            AXIS_PIPE: self.pipe,
            AXIS_FSDP: self.fsdp,
            AXIS_EXPERT: self.expert,
            AXIS_SEQUENCE: self.sequence,
            AXIS_TENSOR: self.tensor,
        }
        bad = [k for k, v in raw.items() if v != -1 and v < 1]
        if bad:
            raise ValueError(f"axis sizes must be >=1 or -1 (fill), got {raw}")
        fills = [k for k, v in raw.items() if v == -1]
        if len(fills) > 1:
            raise ValueError(f"at most one axis may be -1, got {fills}")
        fixed = math.prod(v for v in raw.values() if v != -1)
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {raw}"
                )
            raw[fills[0]] = n_devices // fixed
            fixed = n_devices
        if fixed != n_devices:
            raise ValueError(
                f"mesh {raw} needs {fixed} devices, have {n_devices}"
            )
        return raw

    def model_parallel_size(self, n_devices: int) -> int:
        """Devices holding one replica's model shards (excl. data/fsdp)."""
        sizes = self.sizes(n_devices)
        return (
            sizes[AXIS_TENSOR]
            * sizes[AXIS_SEQUENCE]
            * sizes[AXIS_EXPERT]
            * sizes[AXIS_PIPE]
        )


def build_mesh(
    config: MeshConfig | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the named device mesh for a MeshConfig.

    Uses ``mesh_utils.create_device_mesh`` when the devices are real TPUs so
    the physical ICI topology is respected; falls back to a plain reshape for
    CPU/virtual meshes (tests, dryrun_multichip).
    """
    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if config.dcn_data > 1:
        if len(devices) % config.dcn_data:
            raise ValueError(
                f"{len(devices)} devices not divisible into "
                f"{config.dcn_data} DCN slices"
            )
        sizes = config.sizes(len(devices) // config.dcn_data)
        shape = tuple(sizes[a] for a in MESH_AXES)
        dcn_shape = tuple(
            config.dcn_data if a == AXIS_DATA else 1 for a in MESH_AXES
        )
        if devices[0].platform == "tpu":
            # Real slices: let a genuine misconfiguration (wrong slice
            # count / ICI-incompatible shape) raise — a silent reshape
            # would put per-step collectives over DCN.
            dev_array = mesh_utils.create_hybrid_device_mesh(
                shape, dcn_shape, devices=devices
            )
        else:
            # CPU/virtual devices carry no slice_index: emulate with DCN as
            # the slowest-varying dim (same layout the hybrid mesh yields).
            combined = tuple(a * b for a, b in zip(dcn_shape, shape))
            dev_array = np.array(devices).reshape(combined)
        return Mesh(dev_array, MESH_AXES)
    sizes = config.sizes(len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)
    if devices[0].platform == "tpu":
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, NotImplementedError):
            dev_array = np.array(devices).reshape(shape)
    else:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


# Logical axis names used by every model in tpufw.models. Sharding strategy
# changes are rule edits here, never model edits.
def logical_axis_rules(
    *,
    fsdp_also_data: bool = True,
) -> tuple[tuple[str, tuple[str, ...] | None], ...]:
    """(logical axis -> mesh axes) rules for flax logical partitioning.

    ``batch`` spans every data-like axis; parameters shard their largest dim
    over ``fsdp`` (ZeRO-3) and their model-parallel dim over ``tensor``;
    ``expert`` maps experts onto the expert axis; activations' sequence dim
    maps onto ``sequence`` for context parallelism.
    """
    batch_axes: tuple[str, ...] = (
        (AXIS_DATA, AXIS_FSDP) if fsdp_also_data else (AXIS_DATA,)
    )
    return (
        ("batch", batch_axes),
        ("act_seq", (AXIS_SEQUENCE,)),
        ("act_embed", None),
        ("act_heads", (AXIS_TENSOR,)),
        ("act_mlp", (AXIS_TENSOR,)),
        ("act_vocab", (AXIS_TENSOR,)),
        # Parameter axes.
        ("embed", (AXIS_FSDP,)),
        ("mlp", (AXIS_TENSOR,)),
        ("heads", (AXIS_TENSOR,)),
        ("q_heads", (AXIS_TENSOR,)),
        ("kv_heads", (AXIS_TENSOR,)),
        ("head_dim", None),
        ("lora", None),  # LoRA rank axis: tiny, replicated
        # MLA (deepseek) latent axes: small next to embed/mlp dims;
        # replicated keeps the absorbed-decode einsums local.
        ("kv_latent", None),
        ("q_latent", None),
        ("vocab", (AXIS_TENSOR,)),
        ("expert", (AXIS_EXPERT,)),
        ("expert_mlp", (AXIS_TENSOR,)),
        ("norm", None),
        # Conv/ResNet axes.
        ("conv_h", None),
        ("conv_w", None),
        ("conv_in", None),
        ("conv_out", (AXIS_FSDP,)),
    )


def mesh_sharding(
    mesh: Mesh, spec: PartitionSpec | None = None
) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else PartitionSpec())
