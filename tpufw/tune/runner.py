"""Compile-and-measure autotuning: time real train steps per candidate.

The search is bench.py-shaped: each surviving candidate from
tpufw.tune.space builds a real Trainer, compiles the real jitted step,
runs a few timed steps, and reports the median. Selection is purely
empirical — no cost model picks the winner, the clock does. Two rules
keep a search from ever being worse than not searching:

- **quarantine, never abort**: a candidate that fails to compile or
  OOMs (the analytic pre-prune is first-order) is recorded and skipped;
  the search continues with what remains;
- **wall-clock budget**: once the budget is spent, remaining candidates
  are marked skipped — but the first candidate always runs, so a
  too-tight budget degrades to "measure the baseline", not "crash".

Winners persist via tpufw.tune.cache; ``apply_autotune`` is the
Trainer-facing entry consulted from ``Trainer.run`` when
``TrainerConfig.autotune != "off"``.
"""

from __future__ import annotations

import dataclasses
import os
import statistics
import time
from typing import Callable, Optional

from tpufw.tune import cache as tune_cache
from tpufw.tune.space import (
    Candidate,
    SearchSpace,
    enumerate_candidates,
)

_FLASH_ENV = ("TPUFW_FLASH_BQ", "TPUFW_FLASH_BKV")


@dataclasses.dataclass
class Trial:
    candidate: Candidate
    status: str  # "ok" | "quarantined" | "skipped_budget"
    median_step_s: Optional[float] = None
    error: Optional[str] = None


@dataclasses.dataclass
class TuneResult:
    best: Optional[Candidate]
    best_step_s: Optional[float]
    trials: list
    pruned: list
    tune_s: float
    cache_hit: bool = False
    cache_key: Optional[str] = None
    mode: str = "search"

    def summary(self) -> dict:
        """The JSON-able record bench.py and train logs echo."""
        return {
            "mode": self.mode,
            "cache_hit": self.cache_hit,
            "cache_key": self.cache_key,
            "tune_s": round(self.tune_s, 3),
            "config": self.best.as_dict() if self.best else None,
            "best_step_s": self.best_step_s,
            "n_measured": sum(1 for t in self.trials if t.status == "ok"),
            "n_quarantined": sum(
                1 for t in self.trials if t.status == "quarantined"
            ),
            "n_pruned": len(self.pruned),
        }


def search(
    candidates: list[Candidate],
    measure_fn: Callable[[Candidate], float],
    budget_s: float = 120.0,
    pruned: Optional[list] = None,
    events=None,
) -> TuneResult:
    """Measure candidates under a wall-clock budget; best = min median.

    ``measure_fn(candidate) -> median_step_seconds`` does all the real
    work (tests inject a fake); any exception it raises quarantines that
    candidate only. The first candidate is always measured even if the
    budget is already blown, so the result is never empty-by-budget.
    ``events`` (tpufw.obs event log) gets one ``tune_trial`` line per
    candidate as it resolves — a hung measure is then localizable to
    the exact candidate from the event stream.
    """
    if events is None:
        from tpufw.obs import events as events_mod

        events = events_mod.NULL
    t0 = time.perf_counter()
    trials: list[Trial] = []
    measured_any = False

    def log_trial(t: Trial) -> None:
        trials.append(t)
        events.emit(
            "tune_trial",
            trial=len(trials) - 1,
            status=t.status,
            candidate=t.candidate.as_dict(),
            median_step_s=t.median_step_s,
            error=t.error,
        )

    for cand in candidates:
        if measured_any and time.perf_counter() - t0 > budget_s:
            log_trial(Trial(cand, "skipped_budget"))
            continue
        try:
            med = float(measure_fn(cand))
        except Exception as e:  # noqa: BLE001 — quarantine, never abort
            log_trial(
                Trial(cand, "quarantined", error=f"{type(e).__name__}: {e}")
            )
            continue
        log_trial(Trial(cand, "ok", median_step_s=med))
        measured_any = True
    ok = [t for t in trials if t.status == "ok"]
    best = min(ok, key=lambda t: t.median_step_s, default=None)
    return TuneResult(
        best=best.candidate if best else None,
        best_step_s=best.median_step_s if best else None,
        trials=trials,
        pruned=list(pruned or []),
        tune_s=time.perf_counter() - t0,
    )


def _set_flash_env(bq: Optional[int], bkv: Optional[int]) -> dict:
    """Point the kernel's env override at the candidate's blocks (None
    pops, restoring the size heuristic). Returns the previous values so
    measurement can restore them."""
    prev = {k: os.environ.get(k) for k in _FLASH_ENV}
    for k, v in zip(_FLASH_ENV, (bq, bkv)):
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    return prev


def _restore_env(prev: dict) -> None:
    for k, v in prev.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _candidate_model(model, cand: Candidate):
    """The model to measure/run with: same module unless the remat
    policy differs (apply_fn bakes the policy in, so that needs a
    rebuilt module)."""
    mcfg = getattr(model, "cfg", None)
    if (
        mcfg is None
        or not getattr(mcfg, "remat", False)
        or getattr(mcfg, "remat_policy", None) == cand.remat_policy
    ):
        return model
    return type(model)(
        dataclasses.replace(mcfg, remat_policy=cand.remat_policy)
    )


def candidate_program_name(cand: Candidate) -> str:
    """Stable perf-observatory program name for one tune candidate —
    the key the measured trial's cost/MFU lands under in
    ``programs.json`` (tpufw.obs.perf), so "did the autotuner win"
    reads as a utilization comparison, not just step wall."""
    parts = [
        f"tune:{cand.remat_policy}",
        f"ga{cand.grad_accum}",
        f"lc{cand.loss_chunk_size}",
    ]
    if cand.flash_bq or cand.flash_bkv:
        parts.append(f"fb{cand.flash_bq}x{cand.flash_bkv}")
    if cand.pipeline_schedule:
        parts.append(
            f"{cand.pipeline_schedule}v{cand.pipeline_vstages}"
        )
    return "-".join(parts)


def make_measure_fn(
    model,
    trainer_cfg,
    mesh,
    tx=None,
    n_steps: int = 3,
    warmup_steps: int = 1,
    seed: int = 0,
    perf=None,
) -> Callable[[Candidate], float]:
    """A measure_fn that builds a REAL Trainer per candidate and times
    the REAL jitted step on synthetic tokens. Each candidate gets a
    fresh state (fresh params + optimizer): steps/candidate is tiny, so
    init cost dominates fairness concerns less than sharing donated
    state across incompatible compiled steps would."""
    import jax
    import numpy as np

    from tpufw.train.trainer import Trainer

    vocab = getattr(getattr(model, "cfg", None), "vocab_size", 32000)
    rng = np.random.default_rng(seed)
    tokens = rng.integers(
        0, vocab, (trainer_cfg.batch_size, trainer_cfg.seq_len),
        dtype=np.int32,
    )

    def measure(cand: Candidate) -> float:
        cfg = dataclasses.replace(
            trainer_cfg,
            grad_accum=cand.grad_accum,
            loss_chunk_size=cand.loss_chunk_size,
            sync_every=1,
            checkpoint_dir=None,
            profile_dir=None,
            eval_every=0,
            handle_preemption=False,
            autotune="off",
        )
        prev = _set_flash_env(cand.flash_bq, cand.flash_bkv)
        try:
            trainer = Trainer(_candidate_model(model, cand), cfg,
                              mesh=mesh, tx=tx)
            trainer.init_state(seed=seed)
            batch = {"tokens": tokens}
            from tpufw.parallel.context import use_mesh

            with use_mesh(mesh):
                step = trainer.compiled_step(batch)
                state = trainer.state
                if perf is not None:
                    perf.observe_jit(
                        candidate_program_name(cand), step, (state, batch)
                    )
                for _ in range(max(warmup_steps, 1)):
                    state, m = step(state, batch)
                    jax.block_until_ready(m["loss"])
                times = []
                for _ in range(max(n_steps, 1)):
                    t0 = time.perf_counter()
                    state, m = step(state, batch)
                    jax.block_until_ready(m["loss"])
                    times.append(time.perf_counter() - t0)
            med = statistics.median(times)
            if perf is not None:
                perf.record_wall(candidate_program_name(cand), med)
            return med
        finally:
            _restore_env(prev)

    return measure


def make_pipeline_measure_fn(
    model_cfg,
    pipe,
    trainer_cfg,
    mesh_cfg,
    tx=None,
    n_steps: int = 3,
    warmup_steps: int = 1,
    seed: int = 0,
    perf=None,
) -> Callable[[Candidate], float]:
    """make_measure_fn's PipelineTrainer twin: a fresh trainer per
    candidate so each schedule's shard_map step compiles against its
    own stage layout. The candidate's schedule rides in via the
    TrainerConfig knob (the ctor's single override point), so the
    measured step is exactly the one apply_candidate would install."""
    import jax
    import numpy as np

    from tpufw.train.pipeline_trainer import PipelineTrainer

    vocab = getattr(model_cfg, "vocab_size", 32000)
    rng = np.random.default_rng(seed)
    tokens = rng.integers(
        0, vocab, (trainer_cfg.batch_size, trainer_cfg.seq_len),
        dtype=np.int32,
    )

    def measure(cand: Candidate) -> float:
        sched = {}
        if cand.pipeline_schedule:
            sched = dict(
                pipeline_schedule=cand.pipeline_schedule,
                pipeline_vstages=cand.pipeline_vstages,
            )
        cfg = dataclasses.replace(
            trainer_cfg,
            # n_microbatches IS the accumulation on this trainer (the
            # ctor rejects grad_accum != 1), so that axis is pinned.
            grad_accum=1,
            loss_chunk_size=cand.loss_chunk_size,
            sync_every=1,
            checkpoint_dir=None,
            profile_dir=None,
            eval_every=0,
            handle_preemption=False,
            autotune="off",
            **sched,
        )
        mc = model_cfg
        if (
            getattr(model_cfg, "remat", False)
            and getattr(model_cfg, "remat_policy", None)
            != cand.remat_policy
        ):
            mc = dataclasses.replace(
                model_cfg, remat_policy=cand.remat_policy
            )
        prev = _set_flash_env(cand.flash_bq, cand.flash_bkv)
        try:
            trainer = PipelineTrainer(mc, pipe, cfg, mesh_cfg, tx=tx)
            trainer.init_state(seed=seed)
            batch = {"tokens": tokens}
            step = trainer._compiled_step(batch)
            state = trainer.state
            if perf is not None:
                perf.observe_jit(
                    candidate_program_name(cand), step, (state, batch)
                )
            for _ in range(max(warmup_steps, 1)):
                state, m = step(state, batch)
                jax.block_until_ready(m["loss"])
            times = []
            for _ in range(max(n_steps, 1)):
                t0 = time.perf_counter()
                state, m = step(state, batch)
                jax.block_until_ready(m["loss"])
                times.append(time.perf_counter() - t0)
            med = statistics.median(times)
            if perf is not None:
                perf.record_wall(candidate_program_name(cand), med)
            return med
        finally:
            _restore_env(prev)

    return measure


def _trainer_model_cfg(trainer):
    """The model config for either trainer kind: the flax Trainer
    wraps a module (``trainer.model.cfg``), the PipelineTrainer holds
    the config directly (``trainer.model_cfg``)."""
    model = getattr(trainer, "model", None)
    mcfg = getattr(model, "cfg", None)
    if mcfg is None:
        mcfg = getattr(trainer, "model_cfg", None)
    return mcfg


def _trainer_cache_key(trainer) -> str:
    mcfg = _trainer_model_cfg(trainer)
    mesh_shape = tuple(trainer.mesh.shape.values())
    pipe = getattr(trainer, "pipe", None)
    return tune_cache.cache_key(
        mcfg
        if mcfg is not None
        else {"model": type(trainer.model).__name__},
        trainer.cfg.batch_size,
        trainer.cfg.seq_len,
        mesh_shape,
        # Stage/microbatch counts change the step being tuned (and
        # which schedules are valid) without changing the model config
        # — a pp2xM4 winner must not apply to pp4xM8. The SCHEDULE is
        # deliberately not in the key: it is the searched dimension.
        extra=(
            f"pp{pipe.n_stages}x{pipe.n_microbatches}"
            if pipe is not None
            else None
        ),
    )


def _relayout_pipe_state(state, old_pipe, new_pipe):
    """Convert a live PipeTrainState between the canonical [S, ...]
    and interleaved [v, S, ...] stage layouts — pure reshapes, applied
    to the stage stacks and (by shape match, the same trick
    PipelineTrainer._state_shardings uses) their optimizer moments."""
    import jax

    from tpufw.parallel.pipeline import (
        to_canonical_stages,
        to_virtual_stages,
    )

    if new_pipe.virtual_layout:
        conv = lambda t: to_virtual_stages(  # noqa: E731
            t, new_pipe.n_virtual, new_pipe.n_stages
        )
    else:
        conv = lambda t: to_canonical_stages(  # noqa: E731
            t, new_pipe.n_stages
        )
    old_shapes = {
        tuple(x.shape) for x in jax.tree.leaves(state.params["stages"])
    }

    def conv_if_stage(leaf):
        if (
            hasattr(leaf, "shape")
            and tuple(leaf.shape) in old_shapes
        ):
            return conv(leaf)
        return leaf

    params = dict(state.params)
    params["stages"] = conv(state.params["stages"])
    return state.replace(
        params=params,
        opt_state=jax.tree.map(conv_if_stage, state.opt_state),
    )


def _apply_pipeline_candidate(trainer, cand: Candidate) -> None:
    """Install a winner on a live PipelineTrainer. Schedule changes
    re-layout the state in place (reshapes + a re-shard) so a tuned
    run keeps its step counter and optimizer moments; grad_accum is
    not a pipeline knob (n_microbatches IS the accumulation) and is
    left alone."""
    import dataclasses as _dc

    import jax

    trainer.cfg.loss_chunk_size = cand.loss_chunk_size
    trainer.cfg.sync_every = cand.sync_every
    _set_flash_env(cand.flash_bq, cand.flash_bkv)
    if cand.pipeline_schedule:
        old = trainer.pipe
        new = _dc.replace(
            old,
            schedule=cand.pipeline_schedule,
            n_virtual=(
                cand.pipeline_vstages
                if cand.pipeline_schedule == "interleaved"
                else 1
            ),
        )
        if new != old:
            new.validate(trainer.model_cfg, trainer.cfg.batch_size)
            trainer.pipe = new
            if (
                trainer.state is not None
                and new.virtual_layout != old.virtual_layout
            ):
                trainer.state = _relayout_pipe_state(
                    trainer.state, old, new
                )
            trainer._shardings = trainer._state_shardings(
                trainer._abstract_state()
            )
            if trainer.state is not None:
                trainer.state = jax.device_put(
                    trainer.state, trainer._shardings
                )
    trainer._step_fn = None
    trainer._eval_fn = None


def apply_candidate(trainer, cand: Candidate) -> None:
    """Install a winner on a live Trainer: config knobs, a rebuilt model
    when the remat policy changed (re-pointing state.apply_fn if state
    already exists), and the flash env override. Compiled steps are
    dropped — they baked in the old knobs. PipelineTrainers take the
    pipeline branch (schedule swap + state re-layout)."""
    if hasattr(trainer, "pipe"):
        _apply_pipeline_candidate(trainer, cand)
        return
    trainer.cfg.grad_accum = cand.grad_accum
    trainer.cfg.loss_chunk_size = cand.loss_chunk_size
    trainer.cfg.sync_every = cand.sync_every
    new_model = _candidate_model(trainer.model, cand)
    if new_model is not trainer.model:
        trainer.model = new_model
        if trainer.state is not None:
            trainer.state = trainer.state.replace(apply_fn=new_model.apply)
    _set_flash_env(cand.flash_bq, cand.flash_bkv)
    trainer._compiled.clear()


def apply_autotune(
    trainer,
    space: Optional[SearchSpace] = None,
    events=None,
    perf=None,
) -> Optional[TuneResult]:
    """The Trainer.run entry: resolve TrainerConfig.autotune.

    - ``"cached"``: apply the persisted winner if one exists, else no-op.
    - ``"search"``: cache hit applies instantly; miss runs the budgeted
      compile-and-measure search, persists the winner, applies it.

    Returns the TuneResult (also stashed as ``trainer.last_tune``) or
    None when mode is "off"/unknown. ``events`` (tpufw.obs event log)
    gets per-candidate ``tune_trial`` lines and one ``tune_result``;
    ``perf`` (tpufw.obs.perf observatory) gets each measured trial's
    compiled cost + MFU under its ``candidate_program_name``.
    """
    if events is None:
        from tpufw.obs import events as events_mod

        events = events_mod.NULL
    mode = getattr(trainer.cfg, "autotune", "off")
    if mode not in ("cached", "search"):
        return None
    key = _trainer_cache_key(trainer)
    cached = tune_cache.load_candidate(key)
    if cached is not None:
        apply_candidate(trainer, cached)
        result = TuneResult(
            best=cached, best_step_s=None, trials=[], pruned=[],
            tune_s=0.0, cache_hit=True, cache_key=key, mode=mode,
        )
        trainer.last_tune = result
        events.emit("tune_result", **result.summary())
        return result
    if mode == "cached":
        result = TuneResult(
            best=None, best_step_s=None, trials=[], pruned=[],
            tune_s=0.0, cache_hit=False, cache_key=key, mode=mode,
        )
        trainer.last_tune = result
        events.emit("tune_result", **result.summary())
        return result

    import jax

    from tpufw.utils.hardware import detect_chip

    on_tpu = jax.devices()[0].platform == "tpu"
    # HBM pruning only means something against a real chip's HBM; the
    # CPU table entry is a placeholder and would mis-prune.
    hbm = detect_chip().hbm_bytes if on_tpu else None
    mcfg = _trainer_model_cfg(trainer)
    dp = trainer.mesh.shape["data"] * trainer.mesh.shape["fsdp"]
    pipe = getattr(trainer, "pipe", None)
    if pipe is not None and space is None:
        # Default pipeline space: the schedule axis IS the search (the
        # flax knobs that don't exist here — grad_accum, remat swaps —
        # are pinned), interleaved at the cheapest valid v.
        space = SearchSpace(
            grad_accums=(1,),
            remat_policies=(getattr(mcfg, "remat_policy", "dots"),),
            pipeline_schedules=(
                None, ("1f1b", 1), ("interleaved", 2), ("zb1", 1),
            ),
        )
    candidates, pruned = enumerate_candidates(
        mcfg,
        trainer.cfg.batch_size,
        trainer.cfg.seq_len,
        space=space,
        dp_shards=dp,
        n_shards=dp,
        hbm_bytes=hbm,
        pipe_stages=pipe.n_stages if pipe is not None else 0,
        pipe_microbatches=(
            pipe.n_microbatches if pipe is not None else 0
        ),
    )
    if pipe is not None:
        from tpufw.mesh import MeshConfig

        shape = dict(trainer.mesh.shape)
        measure = make_pipeline_measure_fn(
            trainer.model_cfg,
            pipe,
            trainer.cfg,
            MeshConfig(
                data=shape.get("data", 1),
                pipe=shape.get("pipe", 1),
                fsdp=shape.get("fsdp", 1),
                tensor=shape.get("tensor", 1),
                expert=shape.get("expert", 1),
            ),
            tx=trainer.tx,
            n_steps=getattr(trainer.cfg, "autotune_steps", 3),
            perf=perf if perf is not None and perf.enabled else None,
        )
    else:
        measure = make_measure_fn(
            trainer.model, trainer.cfg, trainer.mesh, tx=trainer.tx,
            n_steps=getattr(trainer.cfg, "autotune_steps", 3),
            perf=perf if perf is not None and perf.enabled else None,
        )
    result = search(
        candidates,
        measure,
        budget_s=getattr(trainer.cfg, "autotune_budget_s", 120.0),
        pruned=pruned,
        events=events,
    )
    result.cache_key = key
    result.mode = mode
    if result.best is not None:
        tune_cache.store(
            key,
            result.best,
            median_step_s=result.best_step_s,
            tune_s=result.tune_s,
        )
        apply_candidate(trainer, result.best)
    trainer.last_tune = result
    events.emit("tune_result", **result.summary())
    return result
