"""Search space for the train-step autotuner: candidates + validity.

Every performance-critical knob the bench sweeps hand-picked per machine
(docs/PERF.md: remat policy, batch/grad-accum split, CE chunk, flash
block sizes, sync window) becomes one axis of a small Cartesian space.
Two filters keep compile-and-measure tractable:

- **validity**: divisibility constraints the trainer itself enforces
  (grad_accum over the data x fsdp row sharding, flash blocks over the
  padded sequence) are checked here so invalid candidates never reach a
  compile;
- **HBM pre-pruning**: the analytic per-device estimate
  (tpufw.tools.estimate_memory.estimate_train) runs first, and any
  candidate predicted past the chip's usable HBM is pruned without
  compiling — compiles cost minutes through a tunneled backend, and the
  OOM ladder already showed which knobs drive the footprint.

The estimate is first-order, so pruning keeps a headroom margin and the
runner still quarantines the occasional surviving OOM (tpufw.tune.runner).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from tpufw.tools.estimate_memory import estimate_train


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in the search space — the knobs a winner carries.

    ``flash_bq``/``flash_bkv`` of None keep the kernel's size heuristic
    (tpufw.ops.flash._block_sizes); ``loss_chunk_size`` of None keeps
    full logits."""

    remat_policy: str = "dots"
    grad_accum: int = 1
    loss_chunk_size: Optional[int] = None
    flash_bq: Optional[int] = None
    flash_bkv: Optional[int] = None
    sync_every: int = 1
    # Pipeline schedule dimension (PipelineTrainer workloads only).
    # None = not searched / keep the trainer's own schedule — the
    # default old cache entries deserialize to, so pre-existing
    # winners stay valid. pipeline_vstages is the interleaved
    # schedule's v and meaningful only with
    # pipeline_schedule="interleaved".
    pipeline_schedule: Optional[str] = None
    pipeline_vstages: int = 1

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Candidate":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    """Axes of the Cartesian candidate space. The defaults cover the
    knob ranges the round-2/3 hardware sweeps actually explored; tests
    and budget-tight runs pass smaller spaces."""

    remat_policies: tuple = ("dots", "attn_out", "nothing")
    grad_accums: tuple = (1, 2)
    loss_chunk_sizes: tuple = (None, 512)
    # (bq, bkv) pairs; None = the kernel's divisor heuristic.
    flash_blocks: tuple = (None, (256, 256), (512, 512))
    sync_everys: tuple = (1, 4)
    # (schedule, vstages) pairs; the lone None default keeps the axis
    # inert for non-pipeline workloads. Pipeline searches pass e.g.
    # (None, ("1f1b", 1), ("interleaved", 2), ("zb1", 1)).
    pipeline_schedules: tuple = (None,)


DEFAULT_SPACE = SearchSpace()

# Headroom on the analytic estimate: XLA fusion/padding/temp buffers add
# real variance (estimate_memory docstring), so pruning at 100% of HBM
# would compile candidates that OOM anyway.
HBM_FRACTION = 0.9


def _pad128(n: int) -> int:
    return n + (-n) % 128


def candidate_order(c: Candidate) -> tuple:
    """Deterministic measurement order: baseline-ish candidates first so
    a tight wall-clock budget always measures something runnable before
    the exotic corners."""
    return (
        c.grad_accum,
        c.sync_every,
        c.flash_bq or 0,
        c.flash_bkv or 0,
        c.remat_policy,
        c.loss_chunk_size or 0,
        c.pipeline_schedule or "",
        c.pipeline_vstages,
    )


def enumerate_candidates(
    model_cfg,
    batch_size: int,
    seq_len: int,
    space: SearchSpace | None = None,
    dp_shards: int = 1,
    n_shards: int = 1,
    hbm_bytes: Optional[float] = None,
    hbm_fraction: float = HBM_FRACTION,
    pipe_stages: int = 0,
    pipe_microbatches: int = 0,
) -> tuple[list[Candidate], list[tuple[Candidate, str]]]:
    """The space, filtered. Returns (valid, pruned-with-reason).

    ``dp_shards`` is the data x fsdp product the batch rows shard over
    (the trainer's grad_accum divisibility check); ``n_shards`` the
    param sharding degree fed to the HBM estimate. ``hbm_bytes`` of
    None disables HBM pruning (pure-validity mode, used by tests and
    CPU runs where the static chip table is meaningless).
    ``pipe_stages``/``pipe_microbatches`` describe the pipeline
    workload shape (0 = not a pipeline trainer — every non-None
    ``pipeline_schedules`` entry then prunes); they gate the schedule
    axis with the same divisibility rules PipelineConfig.validate
    enforces, so invalid schedules never reach a compile.
    """
    space = space or DEFAULT_SPACE
    # The trainer feeds tokens[:, :-1] to the model, padded to 128
    # inside the kernel — flash blocks must divide THAT length.
    t_pad = _pad128(seq_len - 1)
    uses_flash = getattr(model_cfg, "attention_backend", "") == "flash"
    uses_remat = getattr(model_cfg, "remat", False)
    policies = space.remat_policies if uses_remat else (
        getattr(model_cfg, "remat_policy", "dots"),
    )
    blocks = space.flash_blocks if uses_flash else (None,)

    valid: list[Candidate] = []
    pruned: list[tuple[Candidate, str]] = []
    seen: set = set()
    n_layers = getattr(model_cfg, "n_layers", 0)
    for policy, accum, chunk, blk, sync, sched in itertools.product(
        policies, space.grad_accums, space.loss_chunk_sizes, blocks,
        space.sync_everys, space.pipeline_schedules,
    ):
        bq, bkv = blk if blk is not None else (None, None)
        ps, pv = sched if sched is not None else (None, 1)
        cand = Candidate(
            remat_policy=policy,
            grad_accum=accum,
            loss_chunk_size=chunk,
            flash_bq=bq,
            flash_bkv=bkv,
            sync_every=sync,
            pipeline_schedule=ps,
            pipeline_vstages=pv,
        )
        if cand in seen:
            continue
        seen.add(cand)
        if ps is not None:
            if pipe_stages < 2:
                pruned.append(
                    (cand, f"pipeline schedule {ps!r} needs a pipeline "
                     "trainer (pipe_stages >= 2)")
                )
                continue
            if ps == "interleaved":
                if pv < 2:
                    pruned.append(
                        (cand, "interleaved needs pipeline_vstages "
                         ">= 2")
                    )
                    continue
                if n_layers % (pv * pipe_stages):
                    pruned.append(
                        (cand, f"n_layers={n_layers} not divisible "
                         f"into {pv}x{pipe_stages} virtual chunks")
                    )
                    continue
                if pipe_microbatches % pipe_stages:
                    pruned.append(
                        (cand, f"microbatches {pipe_microbatches} not "
                         f"divisible by {pipe_stages} stages")
                    )
                    continue
            elif pv != 1:
                pruned.append(
                    (cand, f"pipeline_vstages={pv} only applies to "
                     "the interleaved schedule")
                )
                continue
        if accum < 1 or batch_size % accum:
            pruned.append(
                (cand, f"grad_accum {accum} does not divide batch "
                 f"{batch_size}")
            )
            continue
        if (batch_size // accum) % max(dp_shards, 1):
            pruned.append(
                (cand, f"microbatch rows {batch_size // accum} do not "
                 f"divide over data x fsdp = {dp_shards}")
            )
            continue
        if chunk is not None and chunk < 1:
            pruned.append((cand, f"loss_chunk_size {chunk} < 1"))
            continue
        bad_block = next(
            (
                b for b in (bq, bkv)
                if b is not None and (b % 128 or t_pad % b)
            ),
            None,
        )
        if bad_block is not None:
            pruned.append(
                (cand, f"flash block {bad_block} is not a 128-multiple "
                 f"divisor of padded seq {t_pad}")
            )
            continue
        if hbm_bytes:
            est = estimate_train(
                model_cfg,
                batch_size,
                seq_len,
                n_shards=max(n_shards, 1),
                remat_policy=policy,
                loss_chunk_size=chunk,
                grad_accum=accum,
            )
            if est.total() > hbm_bytes * hbm_fraction:
                pruned.append(
                    (cand, f"estimated {est.total() / 2**30:.2f} GiB > "
                     f"{hbm_fraction:.0%} of "
                     f"{hbm_bytes / 2**30:.2f} GiB HBM")
                )
                continue
        valid.append(cand)
    valid.sort(key=candidate_order)
    return valid, pruned
