"""MFU autotuner: compile-and-measure search over the train-step knobs.

- ``space``  — candidates, validity constraints, HBM pre-pruning
- ``runner`` — budgeted measurement loop + Trainer integration
- ``cache``  — per-(machine, model, batch/seq, mesh) persisted winners

Enable via ``TrainerConfig.autotune`` ("off" | "cached" | "search") or
``TPUFW_AUTOTUNE`` in the workloads. See docs/PERF.md "Autotuning".
"""

from tpufw.tune.space import (  # noqa: F401
    Candidate,
    SearchSpace,
    enumerate_candidates,
)
from tpufw.tune.runner import (  # noqa: F401
    TuneResult,
    Trial,
    apply_autotune,
    make_measure_fn,
    search,
)
from tpufw.tune import cache  # noqa: F401
