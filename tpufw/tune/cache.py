"""Persisted autotuner winners, keyed per machine + workload shape.

A search costs real wall-clock (each surviving candidate compiles and
runs a few steps), so winners are written to disk and subsequent runs
with the same (machine, model config, batch/seq, mesh) skip the search
entirely. One JSON file per key keeps entries independently writable
from concurrent hosts sharing a cache volume.

Layout: ``$TPUFW_TUNE_CACHE_DIR`` (default ``~/.cache/tpufw/tune``),
one ``<key>.json`` per entry.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pathlib
from typing import Optional

from tpufw.tune.space import Candidate
from tpufw.utils.profiling import machine_fingerprint

def cache_dir() -> pathlib.Path:
    from tpufw.workloads.env import env_opt_str

    d = env_opt_str("tune_cache_dir")
    if d:
        return pathlib.Path(d)
    return pathlib.Path.home() / ".cache" / "tpufw" / "tune"


def model_config_hash(model_cfg) -> str:
    """Stable hash of everything that changes the compiled step. Dtypes
    and other non-JSON leaves are stringified so two configs differing
    only in dtype get distinct keys."""
    if dataclasses.is_dataclass(model_cfg):
        d = dataclasses.asdict(model_cfg)
    else:
        d = dict(model_cfg)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def cache_key(
    model_cfg,
    batch_size: int,
    seq_len: int,
    mesh_shape: tuple,
    fingerprint: Optional[str] = None,
    extra: Optional[str] = None,
) -> str:
    """``extra`` extends the key with workload shape beyond the model/
    batch/mesh tuple — e.g. the pipeline trainer's ``ppSxM`` (stage and
    microbatch counts), which change the step being tuned without
    changing the model config."""
    fp = fingerprint or machine_fingerprint()
    mesh = "x".join(str(int(m)) for m in mesh_shape)
    return (
        f"{fp}-{model_config_hash(model_cfg)}"
        f"-b{batch_size}-s{seq_len}-m{mesh}"
        + (f"-{extra}" if extra else "")
    )


def load(key: str) -> Optional[dict]:
    """The cached entry for ``key``, or None. Corrupt files read as a
    miss — the search just re-runs and overwrites them."""
    path = cache_dir() / f"{key}.json"
    try:
        with open(path) as f:
            entry = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(entry, dict) or "candidate" not in entry:
        return None
    return entry


def store(
    key: str,
    candidate: Candidate,
    median_step_s: Optional[float] = None,
    tune_s: Optional[float] = None,
    meta: Optional[dict] = None,
) -> pathlib.Path:
    d = cache_dir()
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"{key}.json"
    entry = {
        "key": key,
        "candidate": candidate.as_dict(),
        "median_step_s": median_step_s,
        "tune_s": tune_s,
        **(meta or {}),
    }
    tmp = path.with_suffix(".json.tmp")
    with open(tmp, "w") as f:
        json.dump(entry, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_candidate(key: str) -> Optional[Candidate]:
    entry = load(key)
    if entry is None:
        return None
    try:
        return Candidate.from_dict(entry["candidate"])
    except (TypeError, KeyError):
        return None
