"""TPU010-TPU014 — cross-layer deployment rules.

These rules statically verify the ``kubectl apply`` path against the
python tree: the YAML a cluster operator applies encodes arithmetic
(topology products, chip counts, mesh factorizations), wiring (the env
vars ``tpufw.cluster.bootstrap`` keys its tier detection on), and
schema (``TrainerConfig`` field names, the docs/ENV.md knob catalog)
that nothing checks until a multi-hour reservation is already burning.
Every contract checked here is read from the live python tree via
``Project.parse_doc``/``read_doc`` — not duplicated into the linter —
so the rules drift with the code, and fire loudly (contract-drift
warnings) when a contract module stops looking like itself.

- TPU010 topology math: ``google.com/tpu`` limits x workers vs the
  ``gke-tpu-topology`` product vs the generation's chips-per-host
  ceiling (tpufw/utils/hardware.py), TPUFW_MESH_* products vs chip
  counts, and config-vs-manifest pairing drift.
- TPU011 bootstrap wiring: multi-host JobSets must supply exactly the
  inputs one of bootstrap.py's tiers needs (downward-API fields,
  TPUFW_WORKERS_PER_SLICE, a resolvable coordinator address).
- TPU012 env-knob validity: every literal TPUFW_* in manifests, the
  rendered chart, and the Dockerfile must exist in the docs/ENV.md
  catalog and type-check against its declared type.
- TPU013 config schema: deploy/configs fields vs the real dataclasses,
  plus an analytic HBM-fit pre-check (tpufw.tools.estimate_memory)
  when jax/numpy are importable.
- TPU014 chart/manifest parity: a template or manifest that fails to
  render/parse is itself a finding — and rendered chart docs flow
  through TPU010-012 like any manifest, so chart and raw manifests are
  held to the same rules.
"""
# tpulint: disable-file=TPU004 — like cluster/bootstrap.py, this module
# IS the contract checker: the TPUFW_* literals below are rule data
# (mesh-axis names, bootstrap markers, enum tables) quoted to verify
# manifests, not env reads, and the dict lookups TPU004's envish
# heuristic flags here operate on parsed YAML env blocks, not
# os.environ.

from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set

from tpufw.analysis import manifests as mf
from tpufw.analysis.core import Checker, Finding, Project
from tpufw.analysis.envreg import _edit_distance_1
from tpufw.utils.hardware import CHIP_SPECS

#: GKE accelerator nodeSelector value -> chip generation key.
ACCELERATOR_GENERATIONS = {
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5-lite-device": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v4-podslice": "v4",
    "tpu-v6e-slice": "v6e",
}

SELECTOR_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
SELECTOR_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
TPU_RESOURCE = "google.com/tpu"

#: Mesh-axis env names (tpufw/configs/loader.py _MESH_ENV) whose
#: product — x TPUFW_PIPE_STAGES — must equal the workload chip count.
MESH_ENV_NAMES = (
    "TPUFW_MESH_DATA",
    "TPUFW_MESH_PIPE",
    "TPUFW_MESH_FSDP",
    "TPUFW_MESH_EXPERT",
    "TPUFW_MESH_SEQUENCE",
    "TPUFW_MESH_TENSOR",
    "TPUFW_MESH_DCN_DATA",
)

BOOTSTRAP_MODULE = "tpufw/cluster/bootstrap.py"
LOADER_MODULE = "tpufw/configs/loader.py"

#: HBM-fit slack: estimate_train is a first-order model; the bench
#: config measures 46% MFU at an estimated 1.015x HBM, so only flag
#: configs whose estimate exceeds capacity by more than 10%.
HBM_SLACK = 1.1


def _topology_product(topo: Any) -> Optional[int]:
    """'4x4' / '2x2x8' -> product; None when not that shape."""
    if not isinstance(topo, str):
        return None
    parts = topo.lower().split("x")
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        return None
    if not dims or any(d < 1 for d in dims):
        return None
    out = 1
    for d in dims:
        out *= d
    return out


def _dfinding(
    checker: Checker,
    df: "mf.DeployFile",
    line: int,
    message: str,
    symbol: str,
    severity: Optional[str] = None,
) -> Finding:
    return Finding(
        rule=checker.rule,
        path=df.relpath,
        line=line,
        col=1,
        message=message,
        severity=severity or checker.severity,
        symbol=symbol,
    )


def _dedupe(findings: Iterator[Finding]) -> Iterator[Finding]:
    """Drop key-duplicates — the two chart render passes revisit the
    same template, and baseline keys must stay unique anyway."""
    seen: Set[str] = set()
    for f in findings:
        k = f.key()
        if k not in seen:
            seen.add(k)
            yield f


def _stem(relpath: str) -> str:
    base = relpath.rsplit("/", 1)[-1]
    return base.rsplit(".", 1)[0]


def _workload_files(project: Project) -> List["mf.DeployFile"]:
    return [
        df for df in project.deploy_files
        if df.kind in ("manifest", "rendered")
    ]


# ------------------------------------------------------------- TPU010

class TopologyMathChecker(Checker):
    """Chip arithmetic across manifests, chart, and configs."""

    rule = "TPU010"
    name = "topology-math"
    severity = "error"
    layer = "deploy"

    def check(self, project: Project) -> Iterator[Finding]:
        yield from _dedupe(self._check(project))

    def _check(self, project: Project) -> Iterator[Finding]:
        # (stem -> (chips, topology)) per side, for pairing drift.
        manifest_shapes: Dict[str, tuple] = {}
        config_shapes: Dict[str, tuple] = {}

        for df in _workload_files(project):
            for doc in df.docs:
                for w in mf.iter_workloads(doc):
                    yield from self._check_workload(df, w)
                    topo = w.node_selector().get(SELECTOR_TOPOLOGY)
                    chips = w.tpu_limit(TPU_RESOURCE) * w.workers
                    if chips and df.kind == "manifest":
                        manifest_shapes.setdefault(
                            _stem(df.relpath), (chips, topo, df, w.name)
                        )

        for df in project.deploy_matching(mf.CONFIG_DIR):
            doc = df.docs[0] if df.docs else None
            if isinstance(doc, dict):
                yield from self._check_config(df, doc)
                hw = doc.get("hardware") or {}
                if isinstance(hw, dict):
                    hosts = mf._as_int(hw.get("hosts", 1)) or 1
                    cph = mf._as_int(hw.get("chips_per_host", 1)) or 1
                    config_shapes[_stem(df.relpath)] = (
                        hosts * cph, hw.get("topology"), df
                    )

        yield from self._check_pairs(manifest_shapes, config_shapes)

    # ---- one pod workload (manifest or rendered chart doc)

    def _check_workload(
        self, df: "mf.DeployFile", w: "mf.PodWorkload"
    ) -> Iterator[Finding]:
        tpu = w.tpu_limit(TPU_RESOURCE)
        sel = w.node_selector()
        accel = sel.get(SELECTOR_ACCELERATOR)
        topo = sel.get(SELECTOR_TOPOLOGY)

        if tpu == 0 and topo is None:
            return  # not a TPU workload

        if (tpu > 1 or w.workers > 1) and (accel is None or topo is None):
            yield _dfinding(
                self, df, df.find_line(w.name),
                f"{w.kind} {w.name!r} requests {tpu} {TPU_RESOURCE} chip(s)"
                f" x {w.workers} worker(s) but its pod template lacks a "
                f"{SELECTOR_ACCELERATOR}/{SELECTOR_TOPOLOGY} nodeSelector "
                "— the scheduler cannot place it on a matching slice",
                symbol=f"selector:{w.name}",
            )

        gen = None
        if accel is not None:
            gen = ACCELERATOR_GENERATIONS.get(str(accel))
            if gen is None:
                yield _dfinding(
                    self, df, df.find_line(str(accel)),
                    f"unknown accelerator label {accel!r} on {w.name!r} — "
                    f"known: {sorted(ACCELERATOR_GENERATIONS)}",
                    symbol=f"accelerator:{w.name}",
                )
            else:
                spec = CHIP_SPECS[gen]
                if tpu > spec.chips_per_host:
                    yield _dfinding(
                        self, df, df.find_line(TPU_RESOURCE),
                        f"{w.kind} {w.name!r} requests {tpu} "
                        f"{TPU_RESOURCE} per pod but {gen} hosts top out "
                        f"at {spec.chips_per_host} chips — the pod can "
                        "never schedule",
                        symbol=f"chips-per-host:{w.name}",
                    )

        if topo is not None:
            prod = _topology_product(topo)
            if prod is None:
                yield _dfinding(
                    self, df, df.find_line(str(topo)),
                    f"unparseable {SELECTOR_TOPOLOGY} {topo!r} on "
                    f"{w.name!r} (want AxB or AxBxC)",
                    symbol=f"topology-syntax:{w.name}",
                )
            elif tpu and prod != tpu * max(1, w.parallelism):
                # Per-SLICE math: a replicatedJob's replicas are
                # independent gangs, each on its own slice of this
                # topology — only parallelism (pods per gang)
                # multiplies the chip count the selector describes.
                per_slice = tpu * max(1, w.parallelism)
                yield _dfinding(
                    self, df, df.find_line(str(topo)),
                    f"{w.kind} {w.name!r}: topology {topo} = {prod} chips"
                    f" but one gang covers {tpu} {TPU_RESOURCE} x "
                    f"{max(1, w.parallelism)} worker pod(s) = {per_slice}"
                    " — slice shape and chip math disagree",
                    symbol=f"topology:{w.name}",
                )

        if (
            w.kind == "JobSet"
            and w.completions is not None
            and w.completions != w.parallelism
        ):
            yield _dfinding(
                self, df, df.find_line("completions"),
                f"JobSet {w.name!r}: completions={w.completions} != "
                f"parallelism={w.parallelism} — a TPU slice job needs "
                "every worker pod, one per host",
                symbol=f"completions:{w.name}",
            )

        # Mesh axes, like topology, describe one gang's slice — not
        # the sum over replicas.
        yield from self._check_mesh_env(df, w, tpu * max(1, w.parallelism))

    def _check_mesh_env(
        self, df: "mf.DeployFile", w: "mf.PodWorkload", chips: int
    ) -> Iterator[Finding]:
        if not chips:
            return
        env = w.env_map()
        product = 1
        saw_any = False
        for name in MESH_ENV_NAMES:
            val = env.get(name)
            if not isinstance(val, str):
                continue
            iv = mf._as_int(val)
            if iv is None:
                continue  # TPU012's problem, not arithmetic
            if iv == -1:
                return  # a fill axis absorbs the remainder; no product
            saw_any = True
            product *= max(1, iv)
        stages = env.get("TPUFW_PIPE_STAGES")
        if isinstance(stages, str) and (mf._as_int(stages) or 0) > 1:
            saw_any = True
            product *= mf._as_int(stages)
        # Unset axes default to 1 except fsdp (-1, fill) — so an env
        # block that never pins fsdp can still absorb the remainder.
        if not saw_any or "TPUFW_MESH_FSDP" not in env:
            return
        if product != chips:
            yield _dfinding(
                self, df, df.find_line("TPUFW_MESH_FSDP"),
                f"{w.kind} {w.name!r}: TPUFW_MESH_* x pipe stages "
                f"factorize to {product} devices but the workload "
                f"provides {chips} chips — jax.make_mesh will raise at "
                "startup",
                symbol=f"mesh-product:{w.name}",
            )

    # ---- one run config (deploy/configs/*.yaml)

    def _check_config(
        self, df: "mf.DeployFile", doc: dict
    ) -> Iterator[Finding]:
        hw = doc.get("hardware")
        if not isinstance(hw, dict):
            return
        slice_name = str(hw.get("slice", ""))
        hosts = mf._as_int(hw.get("hosts", 1)) or 1
        cph = mf._as_int(hw.get("chips_per_host", 1)) or 1
        n_chips = hosts * cph
        stem = _stem(df.relpath)

        gen, _, suffix = slice_name.partition("-")
        spec = CHIP_SPECS.get(gen)
        if spec is None:
            yield _dfinding(
                self, df, df.find_line("slice"),
                f"hardware.slice {slice_name!r}: unknown generation "
                f"{gen!r} (known: {sorted(CHIP_SPECS)})",
                symbol=f"slice-generation:{stem}",
            )
        else:
            declared = mf._as_int(suffix)
            if declared is not None and declared != n_chips:
                yield _dfinding(
                    self, df, df.find_line("slice"),
                    f"hardware.slice {slice_name!r} names {declared} "
                    f"chips but hosts x chips_per_host = "
                    f"{hosts} x {cph} = {n_chips}",
                    symbol=f"slice-chips:{stem}",
                )
            if cph > spec.chips_per_host:
                yield _dfinding(
                    self, df, df.find_line("chips_per_host"),
                    f"hardware.chips_per_host={cph} exceeds the largest "
                    f"{gen} host ({spec.chips_per_host} chips)",
                    symbol=f"chips-per-host:{stem}",
                )

        topo = hw.get("topology")
        if topo is not None:
            prod = _topology_product(topo)
            if prod is not None and prod != n_chips:
                yield _dfinding(
                    self, df, df.find_line("topology"),
                    f"hardware.topology {topo} = {prod} chips but the "
                    f"slice has {n_chips}",
                    symbol=f"topology:{stem}",
                )

        mesh = doc.get("mesh")
        if isinstance(mesh, dict):
            vals = [mf._as_int(v) for v in mesh.values()]
            if all(v is not None for v in vals) and -1 not in vals:
                product = 1
                for v in vals:
                    product *= max(1, v)
                pipeline = doc.get("pipeline")
                if (
                    isinstance(pipeline, dict)
                    and "pipe" not in mesh
                    and (mf._as_int(pipeline.get("n_stages")) or 0) > 1
                ):
                    product *= mf._as_int(pipeline.get("n_stages"))
                if product != n_chips:
                    yield _dfinding(
                        self, df, df.find_line("mesh"),
                        f"mesh axes factorize to {product} devices but "
                        f"hardware declares {n_chips} chips "
                        f"({slice_name}) — the loader will reject this "
                        "at run start",
                        symbol=f"mesh-product:{stem}",
                    )

    # ---- config <-> manifest pairing (NN-name stems of record)

    def _check_pairs(
        self,
        manifest_shapes: Dict[str, tuple],
        config_shapes: Dict[str, tuple],
    ) -> Iterator[Finding]:
        for mstem, (mchips, mtopo, mdf, wname) in sorted(
            manifest_shapes.items()
        ):
            cstem = mstem[: -len("-jobset")] if mstem.endswith(
                "-jobset"
            ) else mstem
            got = config_shapes.get(cstem) or config_shapes.get(mstem)
            if got is None:
                continue
            cchips, ctopo, cdf = got
            if mchips != cchips:
                yield _dfinding(
                    self, mdf, mdf.find_line(TPU_RESOURCE),
                    f"manifest workload {wname!r} covers {mchips} chips "
                    f"but its config of record ({cdf.relpath}) declares "
                    f"{cchips} — the two halves of the recipe drifted",
                    symbol=f"pair-chips:{cstem}",
                )
            if (
                mtopo is not None
                and ctopo is not None
                and str(mtopo) != str(ctopo)
            ):
                yield _dfinding(
                    self, mdf, mdf.find_line(str(mtopo)),
                    f"manifest workload {wname!r} pins topology {mtopo} "
                    f"but its config of record ({cdf.relpath}) says "
                    f"{ctopo}",
                    symbol=f"pair-topology:{cstem}",
                )


# ------------------------------------------------------------- TPU011

#: Markers whose disappearance from bootstrap.py means the tier
#: contract this rule encodes has drifted — warn rather than guess.
BOOTSTRAP_MARKERS = (
    "TPUFW_COORDINATOR",
    "TPUFW_NUM_PROCESSES",
    "JOBSET_NAME",
    "JOB_COMPLETION_INDEX",
    "TPUFW_WORKERS_PER_SLICE",
    "TPUFW_COORDINATOR_SVC",
    "TPUFW_COORDINATOR_PORT",
    "REPLICATED_JOB_NAME",
)


class BootstrapWiringChecker(Checker):
    """Multi-host JobSets must feed one of bootstrap.py's tiers."""

    rule = "TPU011"
    name = "bootstrap-wiring"
    severity = "error"
    layer = "deploy"

    def check(self, project: Project) -> Iterator[Finding]:
        yield from _dedupe(self._check(project))

    def _check(self, project: Project) -> Iterator[Finding]:
        saw_multihost = False
        services = mf.service_names(project.deploy_files)
        for df in _workload_files(project):
            for doc in df.docs:
                for w in mf.iter_workloads(doc):
                    if w.kind != "JobSet" or not w.is_multihost:
                        continue
                    saw_multihost = True
                    yield from self._check_jobset(df, w, services)
        if saw_multihost:
            yield from self._check_contract(project)

    def _check_jobset(
        self,
        df: "mf.DeployFile",
        w: "mf.PodWorkload",
        services: Set[str],
    ) -> Iterator[Finding]:
        env = w.env_map()
        line = df.find_line(w.name)

        if "TPUFW_COORDINATOR" in env:
            # Explicit tier: address given, process count mandatory.
            if "TPUFW_NUM_PROCESSES" not in env:
                yield _dfinding(
                    self, df, line,
                    f"JobSet {w.name!r} sets TPUFW_COORDINATOR without "
                    "TPUFW_NUM_PROCESSES — bootstrap's explicit tier "
                    "raises ValueError on that combination",
                    symbol=f"explicit-num-processes:{w.name}",
                )
            return

        # JobSet tier: downward-API + per-slice worker count.
        if str(w.completion_mode) != "Indexed":
            yield _dfinding(
                self, df, line,
                f"JobSet {w.name!r} runs {w.workers} workers without "
                "completionMode: Indexed — JOB_COMPLETION_INDEX is only "
                "injected for indexed jobs, so process ids collapse",
                symbol=f"completion-mode:{w.name}",
            )

        for name, annotation in (
            ("JOBSET_NAME", "jobset-name"),
            ("JOB_COMPLETION_INDEX", "job-completion-index"),
        ):
            got = env.get(name)
            if got is None:
                yield _dfinding(
                    self, df, line,
                    f"JobSet {w.name!r} never injects {name} — "
                    "bootstrap's jobset tier cannot trigger and the "
                    "workers fall through to single-process",
                    symbol=f"missing-env:{w.name}:{name}",
                )
            elif isinstance(got, dict) and annotation not in str(got):
                yield _dfinding(
                    self, df, df.find_line(name),
                    f"JobSet {w.name!r}: {name} comes from a downward-"
                    f"API field that does not reference {annotation!r} "
                    "— wrong fieldPath",
                    symbol=f"fieldpath:{w.name}:{name}",
                    severity="warning",
                )

        wps = env.get("TPUFW_WORKERS_PER_SLICE")
        if wps is None:
            yield _dfinding(
                self, df, line,
                f"JobSet {w.name!r} omits TPUFW_WORKERS_PER_SLICE — "
                "bootstrap's jobset tier raises ValueError without it",
                symbol=f"missing-env:{w.name}:TPUFW_WORKERS_PER_SLICE",
            )
        elif isinstance(wps, str):
            ival = mf._as_int(wps)
            if ival is not None and ival != w.parallelism:
                yield _dfinding(
                    self, df, df.find_line("TPUFW_WORKERS_PER_SLICE"),
                    f"JobSet {w.name!r}: TPUFW_WORKERS_PER_SLICE={ival} "
                    f"but parallelism={w.parallelism} — process counts "
                    "will disagree with pod counts",
                    symbol=f"workers-per-slice:{w.name}",
                )

        if "REPLICATED_JOB_NAME" not in env:
            # bootstrap falls back to 'worker' when unset; only safe if
            # that is actually the replicated job's name.
            matches = w.replicated_job_name == "worker"
            yield _dfinding(
                self, df, line,
                f"JobSet {w.name!r} does not inject REPLICATED_JOB_NAME;"
                f" bootstrap assumes 'worker' but the replicated job is "
                f"named {w.replicated_job_name!r}"
                + (" (matches — informational)" if matches else
                   " — the coordinator DNS name will not resolve"),
                symbol=f"replicated-job-name:{w.name}",
                severity="warning" if matches else "error",
            )

        svc = env.get("TPUFW_COORDINATOR_SVC")
        if isinstance(svc, str) and svc:
            if services and svc not in services:
                yield _dfinding(
                    self, df, df.find_line("TPUFW_COORDINATOR_SVC"),
                    f"JobSet {w.name!r}: TPUFW_COORDINATOR_SVC={svc!r} "
                    "matches no Service in the deploy set",
                    symbol=f"coordinator-svc:{w.name}",
                )
        else:
            net = (w.jobset or {}).get("spec", {}).get("network") or {}
            if not net.get("enableDNSHostnames"):
                yield _dfinding(
                    self, df, line,
                    f"JobSet {w.name!r} relies on per-pod DNS for the "
                    "coordinator address but does not set "
                    "spec.network.enableDNSHostnames: true",
                    symbol=f"dns-hostnames:{w.name}",
                )

        port = 8476
        port_env = env.get("TPUFW_COORDINATOR_PORT")
        if isinstance(port_env, str) and mf._as_int(port_env) is not None:
            port = mf._as_int(port_env)
        ports = w.container_ports()
        if ports and port not in ports:
            yield _dfinding(
                self, df, df.find_line("containerPort"),
                f"JobSet {w.name!r}: coordinator port {port} is not "
                f"among the declared containerPorts {sorted(ports)}",
                symbol=f"coordinator-port:{w.name}",
                severity="warning",
            )

    def _check_contract(self, project: Project) -> Iterator[Finding]:
        text = project.read_doc(BOOTSTRAP_MODULE)
        if text is None:
            return  # fixture trees without the module: nothing to drift
        missing = [m for m in BOOTSTRAP_MARKERS if m not in text]
        for marker in missing:
            yield Finding(
                rule=self.rule,
                path=BOOTSTRAP_MODULE,
                line=1,
                col=1,
                message=(
                    f"{BOOTSTRAP_MODULE} no longer mentions {marker!r} "
                    "— the bootstrap tier contract TPU011 encodes has "
                    "drifted; update the rule or the module"
                ),
                severity="warning",
                symbol=f"contract-drift:{marker}",
            )


# ------------------------------------------------------------- TPU012

#: Knobs whose legal values are a closed set the type column cannot
#: express. Empty string = knob off where the reader treats it so.
ENV_ENUMS: Dict[str, Set[str]] = {
    "TPUFW_ATTENTION": {"flash", "ring", "reference", ""},
    "TPUFW_PIPE_SCHEDULE": {"gpipe", "1f1b", "interleaved", "zb1"},
    "TPUFW_PIPELINE_SCHEDULE": {"gpipe", "1f1b", "interleaved", "zb1"},
    "TPUFW_QUANTIZE": {"", "int8"},
    "TPUFW_SERVE_KV_QUANT": {"", "int8"},
    "TPUFW_SERVE_ROLE": {"", "prefill", "decode", "router"},
    "TPUFW_POOLING": {"mean", "last", "cls"},
}

_BOOL_WORDS = {"1", "true", "yes", "on", "0", "false", "no", "off", ""}


def _value_ok(type_str: str, value: str) -> bool:
    t = type_str.strip().lower()
    if t == "int":
        return mf._as_int(value) is not None
    if t == "float":
        try:
            float(value)
            return True
        except ValueError:
            return False
    if t == "bool":
        return value.lower() in _BOOL_WORDS
    if t == "bool/int":
        return (
            value.lower() in _BOOL_WORDS or mf._as_int(value) is not None
        )
    if t == "opt int":
        return value == "" or mf._as_int(value) is not None
    # str / opt str / anything exotic: any string is legal.
    return True


class EnvKnobValidityChecker(Checker):
    """Literal TPUFW_* env assignments must be real, typed knobs."""

    rule = "TPU012"
    name = "env-knob-validity"
    severity = "error"
    layer = "deploy"

    def check(self, project: Project) -> Iterator[Finding]:
        yield from _dedupe(self._check(project))

    def _check(self, project: Project) -> Iterator[Finding]:
        catalog = project.env_catalog()
        known = catalog.catalog_names | set(catalog.entries)
        if not known:
            return  # no catalog (fixture tree) — nothing to validate
        for df in project.deploy_files:
            if df.kind in ("manifest", "rendered"):
                for doc in df.docs:
                    for w in mf.iter_workloads(doc):
                        for e in w.env_entries():
                            name = e.get("name")
                            if not (
                                isinstance(name, str)
                                and name.startswith("TPUFW_")
                            ):
                                continue
                            if "value" not in e:
                                continue  # downward API: no literal
                            yield from self._check_one(
                                df, name, e["value"], catalog, known
                            )
            elif df.kind == "dockerfile":
                for name, value, line in mf.dockerfile_env(df):
                    if name.startswith("TPUFW_"):
                        yield from self._check_one(
                            df, name, value, catalog, known, line=line
                        )

    def _check_one(
        self,
        df: "mf.DeployFile",
        name: str,
        value: Any,
        catalog,
        known: Set[str],
        line: Optional[int] = None,
    ) -> Iterator[Finding]:
        line = line if line is not None else df.find_line(name)
        if name not in known:
            near = sorted(
                k for k in known if _edit_distance_1(name, k)
            )
            hint = f" — did you mean {near[0]}?" if near else ""
            yield _dfinding(
                self, df, line,
                f"{name} is not in the docs/ENV.md catalog; the reader "
                f"will silently ignore it{hint}",
                symbol=f"unknown:{name}",
            )
            return
        if not isinstance(value, str):
            yield _dfinding(
                self, df, line,
                f"{name}: env value {value!r} is a YAML "
                f"{type(value).__name__}, not a string — kubectl apply "
                "rejects non-string env values; quote it",
                symbol=f"unquoted:{name}",
            )
            value = str(value)
        knob = catalog.entries.get(name)
        if knob is not None and not _value_ok(knob.type, value):
            yield _dfinding(
                self, df, line,
                f"{name}={value!r} does not parse as the catalog type "
                f"{knob.type!r} — the typed env reader will raise at "
                "startup",
                symbol=f"type:{name}",
            )
            return
        allowed = ENV_ENUMS.get(name)
        if allowed is not None and isinstance(value, str):
            if value not in allowed:
                yield _dfinding(
                    self, df, line,
                    f"{name}={value!r} is not a legal value "
                    f"({sorted(v for v in allowed if v)})",
                    symbol=f"enum:{name}",
                )


# ------------------------------------------------------------- TPU013

#: Config section -> (module, dataclass) whose field names bound the
#: legal keys. Read from the live tree at check time via parse_doc.
SECTION_CONTRACTS = {
    "trainer": ("tpufw/train/trainer.py", "TrainerConfig"),
    "trainer/vision": ("tpufw/train/vision.py", "VisionTrainerConfig"),
    "mesh": ("tpufw/mesh/mesh.py", "MeshConfig"),
    "pipeline": ("tpufw/parallel/pipeline.py", "PipelineConfig"),
    "hardware": ("tpufw/configs/loader.py", "HardwareConfig"),
}

TOP_LEVEL_KEYS = {"name", "hardware", "model", "trainer", "mesh",
                  "pipeline"}
MODEL_KEYS = {"preset", "overrides"}


def _dataclass_fields(
    project: Project, relpath: str, classname: str
) -> Optional[Set[str]]:
    """Annotated field names of a (data)class, by ast — None when the
    module/class is absent (fixture trees: skip the check)."""
    tree = project.parse_doc(relpath)
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == classname:
            out: Set[str] = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    out.add(stmt.target.id)
            return out or None
    return None


class ConfigSchemaChecker(Checker):
    """deploy/configs fields vs the real dataclasses + HBM pre-check."""

    rule = "TPU013"
    name = "config-schema"
    severity = "error"
    layer = "deploy"

    def check(self, project: Project) -> Iterator[Finding]:
        yield from _dedupe(self._check(project))

    def _check(self, project: Project) -> Iterator[Finding]:
        for df in project.deploy_matching(mf.CONFIG_DIR):
            doc = df.docs[0] if df.docs else None
            if not isinstance(doc, dict):
                continue
            stem = _stem(df.relpath)
            yield from self._check_schema(project, df, doc, stem)
            yield from self._check_hbm(project, df, doc, stem)

    def _check_schema(
        self, project: Project, df: "mf.DeployFile", doc: dict, stem: str
    ) -> Iterator[Finding]:
        for key in sorted(set(doc) - TOP_LEVEL_KEYS):
            yield _dfinding(
                self, df, df.find_line(f"{key}:"),
                f"unknown top-level key {key!r} (allowed: "
                f"{sorted(TOP_LEVEL_KEYS)}) — load_run_config rejects "
                "the file",
                symbol=f"key:{key}",
            )
        model = doc.get("model")
        preset = ""
        if isinstance(model, dict):
            preset = str(model.get("preset", ""))
            for key in sorted(set(model) - MODEL_KEYS):
                yield _dfinding(
                    self, df, df.find_line(f"{key}:"),
                    f"unknown model key {key!r} (allowed: "
                    f"{sorted(MODEL_KEYS)})",
                    symbol=f"model-key:{key}",
                )
        for section in ("hardware", "mesh", "pipeline", "trainer"):
            given = doc.get(section)
            if not isinstance(given, dict):
                continue
            contract = section
            if section == "trainer" and preset == "resnet50":
                contract = "trainer/vision"
            relpath, classname = SECTION_CONTRACTS[contract]
            fields = _dataclass_fields(project, relpath, classname)
            if fields is None:
                continue  # contract module unavailable: skip silently
            for key in sorted(set(given) - fields):
                yield _dfinding(
                    self, df, df.find_line(f"{key}:"),
                    f"{section}.{key} is not a field of "
                    f"{classname} ({relpath}) — load_run_config "
                    "rejects the file",
                    symbol=f"{section}-key:{key}",
                )

    def _check_hbm(
        self, project: Project, df: "mf.DeployFile", doc: dict, stem: str
    ) -> Iterator[Finding]:
        """Analytic fit pre-check. Pipeline runs are skipped (the
        estimator has no stage model) and so is resnet50 (vision
        trainer, different activation shape) — documented limitation.
        Needs numpy/jax importable; degrades to nothing without them,
        so the deploy-lint CI job (pyyaml only) runs the schema half
        and a dev box runs both."""
        model = doc.get("model")
        if not isinstance(model, dict):
            return
        if str(model.get("preset", "")) == "resnet50":
            return
        if isinstance(doc.get("pipeline"), dict):
            return
        hw = doc.get("hardware")
        if not isinstance(hw, dict):
            return
        gen = str(hw.get("slice", "")).partition("-")[0]
        spec = CHIP_SPECS.get(gen)
        if spec is None:
            return
        try:
            import os as _os

            from tpufw.configs.loader import load_run_config
            from tpufw.tools.estimate_memory import estimate_train

            run = load_run_config(_os.path.join(project.root, df.relpath))
            n_chips = run.hardware.n_chips
            per_slice = max(1, n_chips // max(1, run.mesh.dcn_data))
            sizes = run.mesh.sizes(per_slice)
            # Shard degree = everything that is not pure data
            # parallelism (fsdp x expert x sequence x tensor): MoE
            # params shard over the expert axis too, so fsdp alone
            # wildly overstates the per-chip footprint.
            n_shards = max(1, per_slice // max(1, sizes.get("data", 1)))
            est = estimate_train(
                run.model_cfg,
                run.trainer.batch_size,
                run.trainer.seq_len,
                n_shards=n_shards,
                remat_policy=getattr(run.model_cfg, "remat_policy", None),
                loss_chunk_size=getattr(
                    run.trainer, "loss_chunk_size", None
                ),
                adam_mu_dtype=getattr(run.trainer, "adam_mu_dtype", None),
                grad_accum=getattr(run.trainer, "grad_accum", 1) or 1,
            )
            total = est.total()
        except Exception:
            return  # no jax/numpy (deploy-lint CI), or loader rejected
            # the file — the schema checks above own that failure.
        if total > HBM_SLACK * spec.hbm_bytes:
            gib = total / 2**30
            cap = spec.hbm_bytes / 2**30
            yield _dfinding(
                self, df, df.find_line("batch_size"),
                f"estimated training footprint {gib:.1f} GiB/chip "
                f"exceeds {gen} HBM {cap:.0f} GiB by more than "
                f"{HBM_SLACK:.0%} — this run OOMs at startup; shrink "
                "batch/seq, raise sharding, or set remat/loss-chunk "
                "knobs (see tpufw.tools.estimate_memory)",
                symbol=f"hbm:{stem}",
            )


# ------------------------------------------------------------- TPU014

class ChartParityChecker(Checker):
    """Render/parse failures are findings; parity with raw manifests
    comes from rendered docs flowing through TPU010-012."""

    rule = "TPU014"
    name = "chart-parity"
    severity = "error"
    layer = "deploy"

    def check(self, project: Project) -> Iterator[Finding]:
        yield from _dedupe(self._check(project))

    def _check(self, project: Project) -> Iterator[Finding]:
        for df in project.deploy_files:
            if not df.parse_error:
                continue
            kind = "render" if df.kind == "rendered" else "parse"
            yield _dfinding(
                self, df, 1,
                f"{df.relpath} failed to {kind}: {df.parse_error} — "
                "nothing downstream of this file was checked",
                symbol=f"{kind}:{df.relpath}",
            )
