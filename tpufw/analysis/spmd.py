"""Host-varying taint substrate for the protocol layer (TPU016).

Multi-host SPMD programs hang, not crash, when control flow diverges:
if host 0 takes a branch that issues a collective (``jax.lax.psum``,
``jax.distributed.initialize``, a jit dispatch that lowers to one) and
host 3 does not, every participant blocks forever waiting for the
missing peer. The values that diverge between hosts are boringly
predictable — ``jax.process_index()``, environment reads, wall-clock
time, host randomness, file/socket I/O — so the check is a taint
problem, not a semantics problem.

This module mirrors the shape of ``dataflow.VaryingEnv`` (PR 8): a
per-function forward propagation seeds names assigned from host-varying
sources and runs two passes so later-defined helpers still converge.
``jax.random`` is deliberately NOT a source: it is functional, and with
a replicated key every host draws the same numbers. Conversely a value
routed through ``multihost_utils.broadcast_one_to_all`` /
``process_allgather`` is uniform by construction and clears the taint.

Sinks come in three flavours:

- direct collectives / ``jax.distributed`` / multihost sync calls;
- calls to names bound from a tracer (``step = jax.jit(f); step(x)``);
- calls into project functions from which a collective is reachable
  (callgraph fixpoint — the classic "helper three frames down does the
  psum" hang).

``find_divergence`` flags If/While tests and For loop bounds that carry
taint AND whose body contains a sink — or that early-exit
(return/raise) past a sink later in the same function, which diverges
just as hard: the exiting hosts never reach the collective the rest
are blocked on.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from . import callgraph as cg
from .core import Project, SourceFile

FuncNode = cg.FuncNode

# ------------------------------------------------------- source kinds

# Names unambiguous enough to count even when imported bare.
_TIME_BARE = {"monotonic", "perf_counter", "process_time", "time_ns",
              "monotonic_ns", "perf_counter_ns"}
_TIME_QUALIFIED = {"time", "now", "utcnow", "today"}
_RANDOM_BARE = {"urandom", "uuid1", "uuid4", "token_hex", "token_bytes",
                "getrandbits", "randbytes", "randint", "randrange",
                "shuffle", "sample", "default_rng"}
_ENV_HELPERS = {"env_str", "env_int", "env_float", "env_bool",
                "env_opt_int", "env_opt_str"}
_IO_BARE = {"gethostname", "getpid"}

# Values made uniform across hosts on purpose; routing through one of
# these clears the taint (and calling one *inside a diverged branch*
# is itself a sink — see _MULTIHOST below).
_UNIFORMIZERS = {"broadcast_one_to_all", "process_allgather"}

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                "all_gather_invariant"}
_MULTIHOST = {"broadcast_one_to_all", "process_allgather",
              "sync_global_devices", "assert_equal"}


def source_kind(call: ast.Call) -> Optional[str]:
    """Classify a call as a host-varying source, or None."""
    chain = cg.attr_chain(call.func)
    if chain is None:
        if isinstance(call.func, ast.Name):
            chain = [call.func.id]
        else:
            return None
    last = chain[-1]
    if last in ("process_index", "host_id"):
        return "process_index"
    if "jax" in chain or "jnp" in chain:
        return None  # jax.random & friends are functional / replicated
    if last == "getenv" or last in _ENV_HELPERS:
        return "env"
    if last == "get" and "environ" in chain:
        return "env"
    if last in _TIME_BARE:
        return "time"
    if last in _TIME_QUALIFIED and len(chain) > 1 and chain[0] in (
        "time", "datetime", "date"
    ):
        return "time"
    if last in _RANDOM_BARE:
        return "random"
    if "random" in chain[:-1]:
        return "random"  # random.x / np.random.x
    if last == "open" and len(chain) == 1:
        return "io"
    if last in _IO_BARE or last in ("recv", "read_text", "read_bytes"):
        return "io"
    return None


def _is_uniformizer(call: ast.Call) -> bool:
    chain = cg.attr_chain(call.func)
    name = chain[-1] if chain else (
        call.func.id if isinstance(call.func, ast.Name) else None
    )
    return name in _UNIFORMIZERS


def _target_names(targets: Sequence[ast.AST]) -> Iterator[str]:
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                yield node.id


def walk_own(fn: FuncNode) -> Iterator[ast.AST]:
    """Every node in ``fn``'s body, not descending into nested
    function/class definitions (they execute later, if at all)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                continue
            stack.append(child)


class HostTaintEnv:
    """Which local names carry a host-varying value, and from what
    kind of source. Two forward passes, VaryingEnv-style."""

    def __init__(self, fn: FuncNode):
        self.fn = fn
        self.tainted: Dict[str, str] = {}
        for _ in range(2):
            for node in walk_own(fn):
                self._visit(node)

    def _visit(self, node: ast.AST) -> None:
        targets: Optional[Sequence[ast.AST]] = None
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.NamedExpr):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.withitem):
            if node.optional_vars is None:
                return
            targets, value = [node.optional_vars], node.context_expr
        if targets is None or value is None:
            return
        kind = self.expr_taint(value)
        if kind is not None:
            for name in _target_names(targets):
                self.tainted[name] = kind

    def expr_taint(self, expr: ast.AST) -> Optional[str]:
        """First host-varying source kind found in ``expr``, skipping
        subtrees routed through a uniformizer."""
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                if _is_uniformizer(node):
                    continue  # result is uniform; don't look inside
                kind = source_kind(node)
                if kind is not None:
                    return kind
            elif isinstance(node, ast.Name):
                if node.id in self.tainted:
                    return self.tainted[node.id]
            elif isinstance(node, ast.Subscript):
                chain = cg.attr_chain(node.value)
                if chain and chain[-1] == "environ":
                    return "env"
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return None


# --------------------------------------------------------------- sinks


def direct_sink(call: ast.Call) -> Optional[str]:
    chain = cg.attr_chain(call.func)
    if chain is None:
        if isinstance(call.func, ast.Name):
            chain = [call.func.id]
        else:
            return None
    last = chain[-1]
    if last in _COLLECTIVES:
        return f"collective {last}"
    if "distributed" in chain[:-1]:
        return f"jax.distributed.{last}"
    if last in _MULTIHOST:
        return f"multihost sync {last}"
    return None


class SinkIndex:
    """Project-wide: which calls dispatch into traced code or reach a
    collective through the call graph."""

    def __init__(self, project: Project):
        self.index = cg.ModuleIndex(project)
        roots = cg.find_traced_roots(self.index, project.files)
        self.traced_ids: Set[int] = {id(fi.node) for fi, _ in roots}
        # name/attr-chain handles bound from a tracer call, per file:
        # ``step = jax.jit(f)`` then ``step(x)`` is a dispatch.
        self.jit_handles: Dict[str, Set[str]] = {}
        for f in project.files:
            if f.tree is None:
                continue
            handles: Set[str] = set()
            for node in ast.walk(f.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if not isinstance(value, ast.Call):
                    continue
                nm = cg.call_name(value)
                if nm not in cg._TRACERS:
                    continue
                tgts = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in tgts:
                    chain = cg.attr_chain(t)
                    if chain:
                        handles.add(".".join(chain))
                    elif isinstance(t, ast.Name):
                        handles.add(t.id)
            self.jit_handles[f.relpath] = handles
        # Fixpoint: function-node ids from which a collective call is
        # reachable (including indirectly through project calls).
        contains: Set[int] = set()
        edges: Dict[int, Set[int]] = {}
        self._fn_of: Dict[int, cg.FunctionInfo] = {}
        for fi in self.index.functions:
            self._fn_of[id(fi.node)] = fi
            callees: Set[int] = set()
            for call in cg.iter_calls(fi.node):
                if direct_sink(call) is not None:
                    contains.add(id(fi.node))
                callee = self.index.resolve_call(
                    call, fi.module, within=fi.qname
                )
                if callee is not None:
                    callees.add(id(callee.node))
            edges[id(fi.node)] = callees
        self.reaches_collective: Set[int] = set(contains)
        changed = True
        while changed:
            changed = False
            for fid, callees in edges.items():
                if fid in self.reaches_collective:
                    continue
                if callees & self.reaches_collective:
                    self.reaches_collective.add(fid)
                    changed = True

    def call_sink(
        self, call: ast.Call, f: SourceFile, module: str, within: str
    ) -> Optional[str]:
        """Sink description for ``call``, or None."""
        d = direct_sink(call)
        if d is not None:
            return d
        chain = cg.attr_chain(call.func)
        handle = (
            ".".join(chain)
            if chain
            else (call.func.id if isinstance(call.func, ast.Name) else None)
        )
        if handle and handle in self.jit_handles.get(f.relpath, set()):
            return f"jit dispatch via {handle}"
        callee = self.index.resolve_call(call, module, within=within)
        if callee is not None:
            if id(callee.node) in self.traced_ids:
                return f"jit dispatch of {callee.name}"
            if id(callee.node) in self.reaches_collective:
                return f"call to {callee.name} (reaches a collective)"
        return None


def _stmts_sink(
    stmts: Sequence[ast.stmt],
    sinks: SinkIndex,
    f: SourceFile,
    module: str,
    within: str,
) -> Optional[Tuple[ast.AST, str]]:
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            desc = sinks.call_sink(node, f, module, within)
            if desc is not None:
                return node, desc
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.Lambda),
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return None


def _has_early_exit(stmts: Sequence[ast.stmt]) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Return, ast.Raise)):
                return True
    return False


class Divergence:
    def __init__(
        self,
        fi: cg.FunctionInfo,
        node: ast.AST,
        kind: str,
        sink: str,
        early_exit: bool,
    ):
        self.fi = fi
        self.node = node
        self.kind = kind
        self.sink = sink
        self.early_exit = early_exit


def find_divergence(project: Project) -> List[Divergence]:
    """Tainted branches/loop bounds dominating a collective sink."""
    sinks = SinkIndex(project)
    out: List[Divergence] = []
    for fi in sinks.index.functions:
        env = HostTaintEnv(fi.node)
        fn_sink = _stmts_sink(
            fi.node.body, sinks, fi.file, fi.module, fi.qname
        )
        for node in walk_own(fi.node):
            branch_body: Optional[List[ast.stmt]] = None
            test: Optional[ast.AST] = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
                branch_body = list(node.body) + list(
                    getattr(node, "orelse", [])
                )
            elif isinstance(node, ast.For):
                test = node.iter
                branch_body = list(node.body)
            if test is None or branch_body is None:
                continue
            kind = env.expr_taint(test)
            if kind is None:
                continue
            hit = _stmts_sink(
                branch_body, sinks, fi.file, fi.module, fi.qname
            )
            if hit is not None:
                out.append(Divergence(fi, node, kind, hit[1], False))
            elif (
                isinstance(node, ast.If)
                and _has_early_exit(branch_body)
                and fn_sink is not None
            ):
                out.append(
                    Divergence(fi, node, kind, fn_sink[1], True)
                )
    return out
