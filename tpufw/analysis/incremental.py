"""Incremental tpulint: replay cache (``--cache``) and change gating
(``--since <ref>``).

Every tpulint rule worth having here is cross-file (the callgraph, the
mesh axis registry, the obs catalog), so a per-file "only re-lint what
changed" scheme is unsound: editing ``tpufw/mesh/__init__.py`` can
create findings in files that did not change. The honest incremental
contract is therefore a *whole-scan replay cache*: the cache records a
signature of everything the analysis can observe — per-file content
hashes of the scan set, the analyzer's own sources, the rule
selection, and the out-of-scan context docs checkers read — and a hit
replays the previous findings without parsing or running a single
checker. Any drift in any input misses and the full scan runs (then
refreshes the cache). The common pre-commit / repeat-CI case (nothing
relevant changed) drops from seconds to milliseconds without ever
serving a stale finding.

``--since <ref>`` is orthogonal: the full tree is still *analyzed*
(cross-file rules need it), but only findings located in files changed
since ``ref`` (committed or not) gate the exit code. That is the
pre-commit contract: your diff must be clean; pre-existing findings
elsewhere are the baseline ratchet's job, not yours.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
from typing import List, Optional, Sequence, Set

from tpufw.analysis.core import Finding

CACHE_VERSION = 1
DEFAULT_CACHE = ".tpulint_cache.json"

# Out-of-scan documents checkers read via Project.read_doc; a change
# here changes findings, so they are part of the signature.
_CONTEXT_DOCS = (
    "docs/ENV.md",
    "docs/OBSERVABILITY.md",
    "docs/PERF.md",
    "docs/WORKFLOWS.md",
    "docs/PARITY.md",
    "README.md",
)


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:20]


def _file_sha(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as fh:
            return _sha(fh.read())
    except OSError:
        return None


def analyzer_signature() -> str:
    """One hash over every .py in tpufw/analysis — a rule edit must
    invalidate the cache even when no scanned file changed."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for name in sorted(os.listdir(pkg)):
        if not name.endswith(".py"):
            continue
        h.update(name.encode())
        digest = _file_sha(os.path.join(pkg, name))
        h.update((digest or "?").encode())
    return h.hexdigest()[:20]


def _deploy_hashes(root: str) -> dict:
    """Per-file hashes of everything under deploy/ — the deploy layer's
    scan set (manifests, configs, chart sources, Dockerfile). Hashing
    chart *sources* rather than rendered output keeps the signature
    cheap and still over-invalidates, never under."""
    out: dict = {}
    base = os.path.join(root, "deploy")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(
            d for d in dirnames if not d.startswith(".")
        )
        for fn in sorted(filenames):
            ap = os.path.join(dirpath, fn)
            rel = os.path.relpath(ap, root).replace(os.sep, "/")
            out[rel] = _file_sha(ap)
    return out


def scan_signature(
    root: str,
    py_files: Sequence[tuple],
    rules: Optional[Sequence[str]],
    layer: str = "all",
) -> dict:
    """Signature over everything the analysis observes. ``py_files``
    is :func:`core.iter_py_files` output — hashing raw bytes here is
    what lets a cache hit skip parsing entirely. When the deploy layer
    is in play the signature also covers every file under deploy/
    (TPU013 additionally reads contract modules, but those live in the
    python scan set / analysis package already hashed above)."""
    sig = {
        "version": CACHE_VERSION,
        "analyzer": analyzer_signature(),
        "rules": sorted(rules) if rules is not None else "all",
        "layer": layer,
        "docs": {
            d: _file_sha(os.path.join(root, d)) for d in _CONTEXT_DOCS
        },
        "files": {rel: _file_sha(ap) for ap, rel in py_files},
    }
    # ``layer`` may be a comma list (TPUFW_LINT_LAYERS). Only the
    # deploy layer reads manifests; the protocol layer's inputs
    # (serve/, obs/reqtrace.py, the wire markers) are .py files
    # already hashed under "files" above.
    if any(part in ("deploy", "all") for part in layer.split(",")):
        sig["deploy"] = _deploy_hashes(root)
    return sig


def load_cached(path: str, signature: dict) -> Optional[List[Finding]]:
    """Previous findings iff the cached signature matches exactly."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if data.get("signature") != signature:
        return None
    try:
        return [Finding(**f) for f in data.get("findings", [])]
    except TypeError:
        return None


def save_cache(
    path: str, signature: dict, findings: Sequence[Finding]
) -> None:
    data = {
        "comment": "tpulint replay cache — safe to delete, never commit",
        "signature": signature,
        "findings": [dataclasses.asdict(f) for f in findings],
    }
    try:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh)
            fh.write("\n")
    except OSError:
        pass  # a read-only tree just means no cache, not a failure


# ------------------------------------------------------------- --since

def changed_files(root: str, since: str) -> Optional[Set[str]]:
    """Repo-relative paths changed since ``since``: committed diff,
    staged, unstaged, and untracked. None when git can't answer (bad
    ref, not a checkout) — the caller falls back to a full gate."""
    out: Set[str] = set()
    cmds = (
        ["git", "diff", "--name-only", since, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    )
    for cmd in cmds:
        try:
            res = subprocess.run(
                cmd,
                cwd=root,
                capture_output=True,
                text=True,
                timeout=30,
                check=True,
            )
        except (OSError, subprocess.SubprocessError):
            return None
        out.update(
            line.strip().replace(os.sep, "/")
            for line in res.stdout.splitlines()
            if line.strip()
        )
    return out


def filter_since(
    findings: Sequence[Finding], changed: Set[str]
) -> List[Finding]:
    return [f for f in findings if f.path in changed]
