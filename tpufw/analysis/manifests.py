"""Deploy-layer scan substrate for tpulint v3 (TPU010-TPU014).

Parses everything the ``kubectl apply`` path consumes —
``deploy/manifests/*.yaml``, ``deploy/configs/*.yaml``, the Helm chart
``deploy/charts/tpu-stack`` (rendered through the same mini-renderer
the chart tests use, :mod:`tpufw.utils.helm`), and
``deploy/docker/Dockerfile`` — into :class:`DeployFile` objects the
deploy checkers walk. Suppression reuses the core ``# tpulint:``
comment grammar, which works as-is on YAML/Dockerfile comments.

pyyaml is the one non-stdlib dependency of the deploy layer; it is
imported lazily so the python layer keeps its zero-dependency
guarantee. :func:`yaml_available` gates callers.

The chart is rendered twice: once with default values, once with an
overlay that flips every boolean branch the templates carry
(``fakeDevices`` on, metrics/libtpu/validator off) so env vars inside
``{{- if }}`` blocks are still seen. Conditionals beyond that overlay
are a documented limitation (docs/ANALYSIS.md).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set

from tpufw.analysis.core import scan_suppression_lines

MANIFEST_DIR = "deploy/manifests"
CONFIG_DIR = "deploy/configs"
CHART_DIR = "deploy/charts/tpu-stack"
DOCKERFILE = "deploy/docker/Dockerfile"

#: The branch-flipping values overlay for the second chart render pass.
CHART_ALT_VALUES = {
    "fakeDevices": 2,
    "metrics": {"enabled": False},
    "libtpu": {"hostInstalled": False},
    "validator": {"enabled": False},
}

_ENV_NAME_RE = re.compile(r"TPUFW_[A-Z0-9_]+")
# Dockerfile ENV forms: `ENV A=1 B=2` and the legacy `ENV A 1`.
_DOCKER_ENV_RE = re.compile(r"^\s*ENV\s+(.*)$", re.I)
_DOCKER_PAIR_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)=(\"[^\"]*\"|\S+)")


def yaml_available() -> bool:
    try:
        import yaml  # noqa: F401

        return True
    except ImportError:
        return False


class DeployFile:
    """One parsed deploy artifact + its suppression table.

    ``kind`` is one of "manifest", "config", "rendered" (a chart
    template's rendered output), "dockerfile". ``variant`` tags the
    chart render pass ("default"/"alt"); both variants share the
    template's relpath so findings and suppressions anchor to the
    source file a human would edit.
    """

    def __init__(
        self,
        relpath: str,
        text: str,
        kind: str,
        variant: str = "",
        parse_error: Optional[str] = None,
        docs: Optional[List[Any]] = None,
    ):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.kind = kind
        self.variant = variant
        self.parse_error = parse_error
        self.docs: List[Any] = docs if docs is not None else []
        self.file_suppressed, self.line_suppressed = scan_suppression_lines(
            self.lines
        )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressed:
            return True
        return rule in self.line_suppressed.get(line, set())

    def find_line(self, *needles: str) -> int:
        """First 1-based line containing every needle — good-enough
        anchoring for findings over parsed YAML (which drops line
        info). Falls back to line 1."""
        for i, line in enumerate(self.lines, start=1):
            if all(n in line for n in needles):
                return i
        return 1

    def env_names(self) -> Set[str]:
        return set(_ENV_NAME_RE.findall(self.text))


def _load_yaml_file(
    root: str, relpath: str, kind: str
) -> Optional[DeployFile]:
    import yaml

    path = os.path.join(root, relpath)
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    try:
        docs = [d for d in yaml.safe_load_all(text) if d is not None]
        err = None
    except yaml.YAMLError as e:
        docs = []
        err = f"yaml parse error: {e}"
    return DeployFile(relpath, text, kind, parse_error=err, docs=docs)


def _load_dockerfile(root: str) -> Optional[DeployFile]:
    path = os.path.join(root, DOCKERFILE)
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    return DeployFile(DOCKERFILE, text, "dockerfile")


def dockerfile_env(df: DeployFile) -> Iterator[tuple[str, str, int]]:
    """(name, value, line) for every Dockerfile ENV assignment."""
    for i, line in enumerate(df.lines, start=1):
        m = _DOCKER_ENV_RE.match(line)
        if not m:
            continue
        rest = m.group(1)
        pairs = _DOCKER_PAIR_RE.findall(rest)
        if pairs:
            for name, value in pairs:
                yield name, value.strip('"'), i
        else:
            toks = rest.split(None, 1)
            if len(toks) == 2:
                yield toks[0], toks[1].strip(), i


def _render_chart(root: str) -> List[DeployFile]:
    """Both render passes of the chart, one DeployFile per template per
    pass; a render/parse failure becomes a DeployFile carrying
    ``parse_error`` (TPU014 reports it)."""
    import yaml

    chart_abs = os.path.join(root, CHART_DIR)
    if not os.path.isdir(os.path.join(chart_abs, "templates")):
        return []
    from tpufw.utils import helm

    out: List[DeployFile] = []
    for variant, overrides in (
        ("default", None),
        ("alt", CHART_ALT_VALUES),
    ):
        try:
            ctx = helm.Context(
                chart_abs, "tpu-stack", "tpu-system", overrides
            )
        except Exception as e:  # bad Chart.yaml/values.yaml
            out.append(
                DeployFile(
                    f"{CHART_DIR}/values.yaml", "", "rendered",
                    variant=variant,
                    parse_error=f"chart load failed: {e}",
                )
            )
            return out
        tdir = os.path.join(chart_abs, "templates")
        for fname in sorted(os.listdir(tdir)):
            if fname.startswith("_") or not fname.endswith(
                (".yaml", ".yml")
            ):
                continue
            rel = f"{CHART_DIR}/templates/{fname}"
            try:
                with open(
                    os.path.join(tdir, fname), encoding="utf-8"
                ) as fh:
                    template = fh.read()
            except OSError:
                continue
            try:
                rendered = helm.render_str(template, ctx, ctx.root)
                docs = [
                    d for d in yaml.safe_load_all(rendered)
                    if d is not None
                ]
                err = None
            except Exception as e:
                rendered = template  # anchor suppressions to something
                docs = []
                err = f"chart render failed ({variant} values): {e}"
            out.append(
                DeployFile(
                    rel, rendered, "rendered", variant=variant,
                    parse_error=err, docs=docs,
                )
            )
    return out


def load_manifest(path: str) -> Optional[DeployFile]:
    """Parse one manifest from an arbitrary path — the ``--manifest``
    CLI flag's loader, for artifacts outside the fixed deploy/ scan
    set (fleet scaling-recommendation YAML, generated files in temp
    dirs). Returns None when the file is unreadable."""
    import yaml

    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    try:
        docs = [d for d in yaml.safe_load_all(text) if d is not None]
        err = None
    except yaml.YAMLError as e:
        docs = []
        err = f"yaml parse error: {e}"
    return DeployFile(path, text, "manifest", parse_error=err, docs=docs)


def collect_deploy_files(root: str) -> List[DeployFile]:
    """Every deploy artifact under ``root``, parsed. Missing
    directories simply contribute nothing (fixture trees)."""
    out: List[DeployFile] = []
    for sub, kind in ((MANIFEST_DIR, "manifest"), (CONFIG_DIR, "config")):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for fn in sorted(os.listdir(base)):
            if not fn.endswith((".yaml", ".yml")):
                continue
            df = _load_yaml_file(root, f"{sub}/{fn}", kind)
            if df is not None:
                out.append(df)
    out.extend(_render_chart(root))
    dockerfile = _load_dockerfile(root)
    if dockerfile is not None:
        out.append(dockerfile)
    return out


# ------------------------------------------------- k8s object walking

def _as_int(val: Any) -> Optional[int]:
    try:
        return int(str(val))
    except (TypeError, ValueError):
        return None


class PodWorkload:
    """One pod template plus its controller context, flattened from a
    Pod / Job / JobSet document."""

    def __init__(
        self,
        doc: dict,
        pod_spec: dict,
        kind: str,
        name: str,
        parallelism: int = 1,
        completions: Optional[int] = None,
        replicas: int = 1,
        completion_mode: Optional[str] = None,
        jobset: Optional[dict] = None,
        replicated_job_name: Optional[str] = None,
    ):
        self.doc = doc
        self.pod_spec = pod_spec
        self.kind = kind
        self.name = name
        self.parallelism = parallelism
        self.completions = completions
        self.replicas = replicas
        self.completion_mode = completion_mode
        self.jobset = jobset  # the owning JobSet doc, if any
        self.replicated_job_name = replicated_job_name

    @property
    def workers(self) -> int:
        """Total pods across every gang (parallelism x replicas) —
        fleet-wide totals like chip counts."""
        return max(1, self.parallelism) * max(1, self.replicas)

    @property
    def is_multihost(self) -> bool:
        """Pods *within one gang* cooperate via jax.distributed; a
        replicatedJob's replicas are independent gangs, so only
        parallelism > 1 means multi-host bootstrap wiring is needed.
        (Scaling a serving pool to replicas: 3 must not start
        demanding JOBSET_NAME plumbing each single-pod replica never
        reads.)"""
        return max(1, self.parallelism) > 1

    def containers(self) -> List[dict]:
        out = []
        for key in ("initContainers", "containers"):
            got = self.pod_spec.get(key)
            if isinstance(got, list):
                out.extend(c for c in got if isinstance(c, dict))
        return out

    def tpu_limit(self, resource_name: str = "google.com/tpu") -> int:
        total = 0
        for c in self.containers():
            resources = c.get("resources") or {}
            for section in ("limits", "requests"):
                val = _as_int((resources.get(section) or {}).get(
                    resource_name
                ))
                if val:
                    total += val
                    break
        return total

    def node_selector(self) -> dict:
        sel = self.pod_spec.get("nodeSelector")
        return sel if isinstance(sel, dict) else {}

    def env_entries(self) -> List[dict]:
        out = []
        for c in self.containers():
            env = c.get("env")
            if isinstance(env, list):
                out.extend(e for e in env if isinstance(e, dict))
        return out

    def env_map(self) -> Dict[str, Any]:
        """name -> literal value (str) or the entry dict for valueFrom."""
        out: Dict[str, Any] = {}
        for e in self.env_entries():
            name = e.get("name")
            if not isinstance(name, str):
                continue
            if "value" in e:
                out.setdefault(name, e["value"])
            else:
                out.setdefault(name, e)
        return out

    def container_ports(self) -> Set[int]:
        out: Set[int] = set()
        for c in self.containers():
            for p in c.get("ports") or []:
                if isinstance(p, dict):
                    val = _as_int(p.get("containerPort"))
                    if val is not None:
                        out.add(val)
        return out


def iter_workloads(doc: Any) -> Iterator[PodWorkload]:
    """Flatten one parsed YAML document into pod workloads."""
    if not isinstance(doc, dict):
        return
    kind = doc.get("kind")
    meta = doc.get("metadata") or {}
    name = str(meta.get("name", "?"))
    spec = doc.get("spec") or {}
    if kind == "Pod":
        yield PodWorkload(doc, spec, "Pod", name)
    elif kind == "Job":
        pod_spec = ((spec.get("template") or {}).get("spec")) or {}
        yield PodWorkload(
            doc,
            pod_spec,
            "Job",
            name,
            parallelism=_as_int(spec.get("parallelism")) or 1,
            completions=_as_int(spec.get("completions")),
            completion_mode=spec.get("completionMode"),
        )
    elif kind in ("DaemonSet", "Deployment", "StatefulSet"):
        pod_spec = ((spec.get("template") or {}).get("spec")) or {}
        yield PodWorkload(
            doc,
            pod_spec,
            str(kind),
            name,
            replicas=_as_int(spec.get("replicas")) or 1,
        )
    elif kind == "JobSet":
        for rj in spec.get("replicatedJobs") or []:
            if not isinstance(rj, dict):
                continue
            job_spec = ((rj.get("template") or {}).get("spec")) or {}
            pod_spec = (
                (job_spec.get("template") or {}).get("spec")
            ) or {}
            yield PodWorkload(
                doc,
                pod_spec,
                "JobSet",
                name,
                parallelism=_as_int(job_spec.get("parallelism")) or 1,
                completions=_as_int(job_spec.get("completions")),
                replicas=_as_int(rj.get("replicas")) or 1,
                completion_mode=job_spec.get("completionMode"),
                jobset=doc,
                replicated_job_name=str(rj.get("name", "worker")),
            )


def service_names(files: Sequence[DeployFile]) -> Set[str]:
    """metadata.name of every Service across the deploy set — what a
    TPUFW_COORDINATOR_SVC value must resolve against."""
    out: Set[str] = set()
    for df in files:
        for doc in df.docs:
            if isinstance(doc, dict) and doc.get("kind") == "Service":
                name = (doc.get("metadata") or {}).get("name")
                if isinstance(name, str):
                    out.add(name)
    return out
