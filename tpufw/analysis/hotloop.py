"""TPU001 — hot-loop purity.

Two scopes, one rule: code that runs under ``jax.jit``/``shard_map``
tracing must never touch the host (``.item()``, ``np.asarray``,
``jax.device_get``, ``block_until_ready``, I/O) — on 0.4.x some of
these are trace-time errors, others silently insert a device->host
round trip per step; and the *host-side step loop* (any function
driving batches through a compiled step via ``timed_batches``) must
keep its per-step path free of the same sync primitives, because one
stray ``.item()`` serializes the async dispatch pipeline and the MFU
headline collapses ("Exploring the limits of Concurrency in ML
Training on Google TPUs", PAPERS.md).

Intentional sync points are allowlisted by receiver: the ``Meter``
(whose ``float(loss)`` IS the designed once-per-window barrier), the
``SkewMonitor`` (rides that same window), and telemetry/checkpoint
handles. Anything else needs a ``# tpulint: disable=TPU001`` with a
justification.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import Checker, Finding, Project

# Receiver base names whose method calls are designed sync points in
# the host loop (Meter.stop's float(loss) barrier, skew allgather,
# telemetry emit/span, checkpoint save/wait, profiler, preemption).
HOST_LOOP_ALLOWED_RECEIVERS: Set[str] = {
    "meter",
    "skew",
    "tel",
    "telemetry",
    "tracer",
    "events",
    "prof",
    "profiler",
    "ckpt",
    "shutdown",
}

_NP_ALIASES = {"np", "numpy", "onp"}

# Plain-call names that are host I/O wherever they appear in a hot path.
_IO_CALLS = {"print", "open", "input", "breakpoint"}


def _sync_reason(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(symbol, reason) when ``node`` is a host-sync primitive."""
    func = node.func
    if isinstance(func, ast.Attribute):
        attr = func.attr
        if attr == "item" and not node.args:
            return (".item()", ".item() forces a device->host sync")
        if attr == "block_until_ready":
            return (
                "block_until_ready",
                "block_until_ready blocks the host on the device",
            )
        if attr == "device_get":
            return (
                "device_get",
                "jax.device_get copies device buffers to host",
            )
        if attr in ("asarray", "array"):
            base = func.value
            if isinstance(base, ast.Name) and base.id in _NP_ALIASES:
                return (
                    f"np.{attr}",
                    f"np.{attr} materializes the array on host "
                    "(use jnp inside traced/step code)",
                )
        if attr == "sleep":
            base = func.value
            if isinstance(base, ast.Name) and base.id == "time":
                return ("time.sleep", "host sleep in a hot path")
    elif isinstance(func, ast.Name):
        if func.id in _IO_CALLS:
            return (func.id, f"host I/O call {func.id}()")
    return None


def _float_int_of_traced(
    node: ast.Call, params: Set[str]
) -> Optional[Tuple[str, str]]:
    """float()/int() applied to something that is an array in traced
    code: a subscript (``m[\"loss\"]``) or a function parameter. Both
    heuristics; plain float(literal) math is never flagged."""
    func = node.func
    if not (isinstance(func, ast.Name) and func.id in ("float", "int")):
        return None
    if len(node.args) != 1:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Subscript):
        return (
            f"{func.id}(subscript)",
            f"{func.id}() on a subscripted value forces a host sync",
        )
    if isinstance(arg, ast.Name) and arg.id in params:
        return (
            f"{func.id}({arg.id})",
            f"{func.id}() on parameter {arg.id!r} forces a host sync",
        )
    return None


def _float_int_host(node: ast.Call) -> Optional[Tuple[str, str]]:
    """float()/int() on a local name or subscript inside the step
    loop — the classic one-liner that serializes async dispatch
    (``loss_f = float(loss)``). Literal/expression args are skipped."""
    func = node.func
    if not (isinstance(func, ast.Name) and func.id in ("float", "int")):
        return None
    if len(node.args) != 1:
        return None
    arg = node.args[0]
    if isinstance(arg, (ast.Name, ast.Subscript)):
        what = arg.id if isinstance(arg, ast.Name) else "subscript"
        return (
            f"{func.id}({what})",
            f"{func.id}() on {what!r} forces a device->host sync",
        )
    return None


def _fn_params(fn: cg.FuncNode) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _receiver_base(node: ast.AST) -> Optional[str]:
    """meter.stop -> "meter"; self.telemetry.close -> "telemetry";
    tel.events.emit -> "tel"."""
    chain = cg.attr_chain(node)
    if not chain:
        return None
    if chain[0] == "self" and len(chain) > 2:
        return chain[1]
    return chain[0]


class HotLoopPurityChecker(Checker):
    rule = "TPU001"
    name = "hot-loop-purity"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        yield from self._check_traced(project, index)
        yield from self._check_host_loops(project, index)

    # -------------------------------------------------- traced scope

    def _check_traced(
        self, project: Project, index: cg.ModuleIndex
    ) -> Iterator[Finding]:
        roots = cg.find_traced_roots(index, project.files)
        reach = cg.reachable_functions(index, roots)
        for fi, how in reach.values():
            params = _fn_params(fi.node)
            for call in cg.iter_calls(fi.node):
                hit = _sync_reason(call) or _float_int_of_traced(
                    call, params
                )
                if hit is None:
                    continue
                symbol, reason = hit
                yield self.finding(
                    fi.file,
                    call,
                    f"{reason} inside traced function "
                    f"{fi.qname!r} (traced via {how})",
                    symbol=f"traced:{fi.qname}:{symbol}",
                )

    # ------------------------------------------------ host-loop scope

    def _check_host_loops(
        self, project: Project, index: cg.ModuleIndex
    ) -> Iterator[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            mod = cg.module_name(f.relpath)
            for fi in index.functions:
                if fi.file is not f:
                    continue
                if not self._is_step_loop_driver(fi.node):
                    continue
                for loop in self._loops(fi.node):
                    yield from self._scan_host_scope(
                        f, index, mod, fi, loop.body, hops=1
                    )

    @staticmethod
    def _is_step_loop_driver(fn: cg.FuncNode) -> bool:
        """A function that iterates ``timed_batches(...)`` — the one
        marked entrypoint all tpufw step loops share."""
        for call in cg.iter_calls(fn):
            if cg.call_name(call) == "timed_batches":
                return True
        return False

    @staticmethod
    def _loops(fn: cg.FuncNode) -> List[ast.stmt]:
        out = []
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.For, ast.While)):
                    out.append(node)
        return out

    def _scan_host_scope(
        self,
        f,
        index: cg.ModuleIndex,
        mod: str,
        owner: cg.FunctionInfo,
        body: List[ast.stmt],
        hops: int,
        _visited: Optional[Set[int]] = None,
    ) -> Iterator[Finding]:
        visited = _visited if _visited is not None else set()
        for stmt in body:
            stack: List[ast.AST] = [stmt]
            while stack:
                node = stack.pop()
                if isinstance(node, ast.Call):
                    base = _receiver_base(node.func)
                    if base in HOST_LOOP_ALLOWED_RECEIVERS:
                        # The whole call — arguments included — is the
                        # designed sync point (meter.stop(float(loss)),
                        # tel.events.emit(..., float(v), ...)).
                        continue
                stack.extend(ast.iter_child_nodes(node))
                if not isinstance(node, ast.Call):
                    continue
                hit = _sync_reason(node) or _float_int_host(node)
                if hit is not None:
                    symbol, reason = hit
                    yield self.finding(
                        f,
                        node,
                        f"{reason} in the step loop of "
                        f"{owner.qname!r} — each occurrence "
                        "serializes async dispatch; move it behind "
                        "the sync window or allowlist the receiver",
                        symbol=f"hotloop:{owner.qname}:{symbol}",
                        severity="warning",
                    )
                    continue
                # One hop into helpers defined in the same module
                # (nested closures like record_window).
                if hops > 0 and isinstance(node.func, ast.Name):
                    callee = index.resolve_call(
                        node, mod, within=owner.qname
                    )
                    if (
                        callee is not None
                        and callee.file is f
                        and id(callee.node) not in visited
                    ):
                        visited.add(id(callee.node))
                        cbody = callee.node.body
                        if not isinstance(cbody, list):
                            cbody = [cbody]
                        yield from self._scan_host_scope(
                            f, index, mod, callee, cbody,
                            hops - 1, visited,
                        )
