"""Shared AST infrastructure for the tpulint checkers: a project-wide
function index, best-effort name resolution (imports, module-level
string constants), jit/shard_map root discovery, and a conservative
reachability walk.

Resolution is deliberately heuristic — no type inference, no dynamic
dispatch. Calls resolve by (a) same-module definitions, (b) explicit
``from X import name`` / ``import X as y`` bindings, (c) ``self.m``
to a method named ``m`` in the same file. Anything else is skipped,
which biases the suite toward false negatives over false positives:
a lint that cries wolf gets suppressed wholesale and then catches
nothing.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple, Union

from tpufw.analysis.core import Project, SourceFile

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def module_name(relpath: str) -> str:
    """tpufw/train/trainer.py -> tpufw.train.trainer (best effort)."""
    p = relpath.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``jax.lax.psum`` -> ["jax", "lax", "psum"]; None if the chain
    bottoms out in anything but a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Last segment of the callee (``jax.jit`` -> "jit")."""
    chain = attr_chain(node.func)
    return chain[-1] if chain else None


class FunctionInfo:
    def __init__(self, qname: str, node: FuncNode, file: SourceFile):
        self.qname = qname
        self.node = node
        self.file = file
        self.module = module_name(file.relpath)

    @property
    def name(self) -> str:
        return self.qname.rsplit(".", 1)[-1]

    def __repr__(self) -> str:  # debugging aid only
        return f"<fn {self.module}:{self.qname}>"


class ModuleIndex:
    """Project-wide indexes: functions (incl. nested + methods),
    per-module import maps, and module-level string constants."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: List[FunctionInfo] = []
        self.by_module_qname: Dict[Tuple[str, str], FunctionInfo] = {}
        self.by_simple_name: Dict[str, List[FunctionInfo]] = {}
        # module -> local binding -> (source_module, original_name|None)
        self.imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        # module-level NAME = "literal" string constants
        self.constants: Dict[Tuple[str, str], str] = {}
        self.constants_by_name: Dict[str, Set[str]] = {}
        # (module, local name) -> `name = partial(f, ...)` call node,
        # module- or function-level; lets resolve_call see through the
        # `step = partial(train_step, ...); jax.jit(step)` idiom.
        self.partial_bindings: Dict[Tuple[str, str], ast.Call] = {}
        for f in project.files:
            if f.tree is None:
                continue
            self._index_file(f)

    def _index_file(self, f: SourceFile) -> None:
        mod = module_name(f.relpath)
        imps: Dict[str, Tuple[str, Optional[str]]] = {}
        self.imports[mod] = imps

        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    info = FunctionInfo(q, child, f)
                    self.functions.append(info)
                    self.by_module_qname[(mod, q)] = info
                    self.by_simple_name.setdefault(child.name, []).append(
                        info
                    )
                    walk(child, q)
                elif isinstance(child, ast.ClassDef):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    walk(child, q)
                else:
                    walk(child, prefix)

        walk(f.tree, "")
        for node in ast.walk(f.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and call_name(node.value) == "partial"
                and node.value.args
            ):
                self.partial_bindings.setdefault(
                    (mod, node.targets[0].id), node.value
                )
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    imps[local] = (alias.name, None)
            elif isinstance(node, ast.ImportFrom) and node.module:
                src = node.module
                if node.level:  # relative import: anchor at this package
                    pkg = mod.rsplit(".", node.level)[0]
                    src = f"{pkg}.{node.module}" if pkg else node.module
                for alias in node.names:
                    local = alias.asname or alias.name
                    imps[local] = (src, alias.name)
        for stmt in f.tree.body:
            targets: List[ast.expr] = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            if not (
                isinstance(value, ast.Constant)
                and isinstance(value.value, str)
            ):
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    self.constants[(mod, t.id)] = value.value
                    self.constants_by_name.setdefault(t.id, set()).add(
                        value.value
                    )

    # ------------------------------------------------------- resolution

    def resolve_str(
        self, node: ast.AST, module: Optional[str] = None
    ) -> Optional[str]:
        """Literal string, or a Name/Attribute resolving to a
        module-level string constant (same module first, then a
        project-wide unique name match)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        name: Optional[str] = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is None:
            return None
        if module is not None:
            v = self.constants.get((module, name))
            if v is not None:
                return v
            imp = self.imports.get(module, {}).get(name)
            if imp is not None and imp[1] is not None:
                v = self.constants.get((imp[0], imp[1]))
                if v is not None:
                    return v
        vals = self.constants_by_name.get(name, set())
        if len(vals) == 1:
            return next(iter(vals))
        return None

    def resolve_str_elements(
        self, node: ast.AST, module: Optional[str] = None
    ) -> List[Tuple[ast.AST, str]]:
        """Every string resolvable inside ``node`` (flattening tuples,
        lists, and ``+`` concatenations of tuples) with its AST node —
        dynamic elements are silently skipped."""
        out: List[Tuple[ast.AST, str]] = []
        s = self.resolve_str(node, module)
        if s is not None:
            out.append((node, s))
            return out
        if isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                out.extend(self.resolve_str_elements(el, module))
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            out.extend(self.resolve_str_elements(node.left, module))
            out.extend(self.resolve_str_elements(node.right, module))
        return out

    def resolve_call(
        self, call: ast.Call, module: str, within: Optional[str] = None
    ) -> Optional[FunctionInfo]:
        """Best-effort: the FunctionInfo a call lands in."""
        func = call.func
        if isinstance(func, ast.Name):
            return self._resolve_name(func.id, module, within)
        if isinstance(func, ast.Attribute):
            chain = attr_chain(func)
            if chain is None:
                return None
            if chain[0] == "self":
                # self.m() -> any method named m in the same file
                # (single-class files dominate; ambiguity -> skip).
                cands = [
                    fi
                    for fi in self.by_simple_name.get(chain[-1], [])
                    if fi.module == module and "." in fi.qname
                ]
                return cands[0] if len(cands) == 1 else None
            imp = self.imports.get(module, {}).get(chain[0])
            if imp is not None and imp[1] is None:
                # `import tpufw.ops.flash as fl; fl.attention(...)`
                return self.by_module_qname.get((imp[0], chain[-1]))
        return None

    def _resolve_name(
        self, name: str, module: str, within: Optional[str]
    ) -> Optional[FunctionInfo]:
        if within:
            # Nested defs: inner-most enclosing scope wins.
            parts = within.split(".")
            for i in range(len(parts), 0, -1):
                q = ".".join([*parts[:i], name])
                fi = self.by_module_qname.get((module, q))
                if fi is not None:
                    return fi
        fi = self.by_module_qname.get((module, name))
        if fi is not None:
            return fi
        imp = self.imports.get(module, {}).get(name)
        if imp is not None and imp[1] is not None:
            return self.by_module_qname.get((imp[0], imp[1]))
        return None

    def resolve_partial_binding(
        self, name: str, module: str
    ) -> Optional[FunctionInfo]:
        """The function behind ``name = partial(f, ...)``, if any.

        Deliberately NOT folded into resolve_call: the jit-boundary
        rules (TPU006-TPU008) need to see through ``jax.jit(step)``
        where ``step = partial(train_step, ...)``, but widening every
        rule's reachability the same way would re-litigate TPU001's
        calibration (partial-bound config scalars look like array
        params to the hot-loop sync heuristics)."""
        pc = self.partial_bindings.get((module, name))
        if pc is None or not pc.args:
            return None
        inner = pc.args[0]
        if isinstance(inner, ast.Name) and inner.id != name:
            return self._resolve_name(inner.id, module, None)
        if isinstance(inner, ast.Attribute):
            fake = ast.Call(func=inner, args=[], keywords=[])
            ast.copy_location(fake, inner)
            return self.resolve_call(fake, module)
        return None


# ------------------------------------------------------------ jit roots

# Callables that trace their function argument on TPU.
_TRACERS = {"jit", "pjit", "shard_map", "xmap", "checkpoint", "remat"}


def _first_traced_arg(call: ast.Call) -> Optional[ast.AST]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in ("fun", "f"):
            return kw.value
    return None


def _unwrap_partial(node: ast.AST) -> ast.AST:
    while (
        isinstance(node, ast.Call)
        and call_name(node) in ("partial", "wraps")
        and node.args
    ):
        node = node.args[0]
    return node


def find_traced_roots(
    index: ModuleIndex, files: Sequence[SourceFile]
) -> List[Tuple[FunctionInfo, str]]:
    """(function, how) pairs for every function handed to
    ``jax.jit``/``pjit``/``shard_map`` — via call or decorator —
    in the given files. Lambdas traced inline are returned as
    synthetic FunctionInfo objects."""
    roots: List[Tuple[FunctionInfo, str]] = []
    seen: Set[int] = set()

    def add(fi: Optional[FunctionInfo], how: str) -> None:
        if fi is not None and id(fi.node) not in seen:
            seen.add(id(fi.node))
            roots.append((fi, how))

    for f in files:
        if f.tree is None:
            continue
        mod = module_name(f.relpath)
        # Decorators.
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    target = _unwrap_partial(target)
                    if isinstance(dec, ast.Call) and call_name(dec) in (
                        "partial",
                    ):
                        # @partial(jax.jit, ...) — tracer is partial's
                        # first argument.
                        inner = dec.args[0] if dec.args else None
                        chain = attr_chain(inner) if inner else None
                        if chain and chain[-1] in _TRACERS:
                            add(_fi_for(index, mod, node, f), f"@{chain[-1]}")
                        continue
                    chain = attr_chain(target)
                    if chain and chain[-1] in _TRACERS:
                        add(_fi_for(index, mod, node, f), f"@{chain[-1]}")
            if isinstance(node, ast.Call):
                nm = call_name(node)
                if nm not in _TRACERS:
                    continue
                arg = _first_traced_arg(node)
                if arg is None:
                    continue
                arg = _unwrap_partial(arg)
                if isinstance(arg, ast.Lambda):
                    add(
                        FunctionInfo("<lambda>", arg, f),
                        f"{nm}(<lambda>)",
                    )
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    fake = ast.Call(func=arg, args=[], keywords=[])
                    ast.copy_location(fake, arg)
                    add(index.resolve_call(fake, mod), f"{nm}()")
    return roots


def _fi_for(
    index: ModuleIndex, mod: str, node: ast.AST, f: SourceFile
) -> Optional[FunctionInfo]:
    for fi in index.by_simple_name.get(getattr(node, "name", ""), []):
        if fi.node is node:
            return fi
    return None


def reachable_functions(
    index: ModuleIndex,
    roots: Sequence[Tuple[FunctionInfo, str]],
    max_depth: int = 8,
) -> Dict[int, Tuple[FunctionInfo, str]]:
    """BFS the call graph from the traced roots. Returns
    ``id(node) -> (FunctionInfo, chain-description)``. Expansion is
    bounded by the scan set: ``resolve_call`` only knows functions
    defined in scanned files, so jax/flax internals never enter."""
    out: Dict[int, Tuple[FunctionInfo, str]] = {}
    frontier: List[Tuple[FunctionInfo, str, int]] = [
        (fi, how, 0) for fi, how in roots
    ]
    while frontier:
        fi, how, depth = frontier.pop()
        if id(fi.node) in out:
            continue
        out[id(fi.node)] = (fi, how)
        if depth >= max_depth:
            continue
        for call in iter_calls(fi.node):
            callee = index.resolve_call(
                call, fi.module, within=fi.qname
            )
            if callee is None or id(callee.node) in out:
                continue
            frontier.append(
                (callee, f"{how} -> {callee.name}", depth + 1)
            )
    return out


def iter_calls(fn: FuncNode) -> Iterator[ast.Call]:
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                yield node
