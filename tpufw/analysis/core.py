"""tpulint core: checker plugin framework, file walker, suppression,
baseline ratchet, and output formatting.

The rules this suite enforces (hot-loop purity, mesh-axis consistency,
RNG discipline, env/obs registry hygiene) are invariants a generic
linter cannot see — they live in the relationship between *this*
repo's subsystems (the jitted step, ``tpufw/mesh``, ``workloads/env``,
``obs/events``), not in any one expression. Everything here is stdlib
``ast``: the suite must run in the bare training container and in CI
without installing anything.

Vocabulary
----------
- A :class:`Checker` owns one rule ID (``TPU001``..) and yields
  :class:`Finding` objects over a :class:`Project` (the parsed tree of
  every scanned file), so cross-file rules are first-class.
- Suppression is per-line: a trailing ``# tpulint: disable=TPU001``
  comment (or one alone on the preceding line) silences that rule on
  that line; ``# tpulint: disable-file=TPU004`` anywhere silences the
  whole file. Suppressions are expected to carry a justification after
  the rule list — they are reviewed as code.
- The baseline (``analysis_baseline.json``) ratchets pre-existing
  findings: runs fail only on findings whose stable key is *not* in
  the baseline, and the baseline may only shrink. Keys deliberately
  exclude line numbers so unrelated edits don't churn it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning", "info")

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
)

# Directories never worth parsing (caches, VCS, vendored assets).
_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache", "node_modules", ".venv"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one location.

    ``symbol`` is the stable anchor used for baseline identity (an
    env-var name, axis literal, function qname, ...): baselines keyed
    on ``rule:path:symbol`` survive line drift from unrelated edits.
    """

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    severity: str = "error"
    symbol: str = ""

    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol or self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} [{self.severity}] {self.message}"
        )


class SourceFile:
    """One parsed python file + its suppression table."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=relpath)
        except SyntaxError as e:
            self.parse_error = e
        self.file_suppressed: Set[str] = set()
        # line number -> rules suppressed on that line
        self.line_suppressed: Dict[int, Set[str]] = {}
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",")}
            if m.group(1) == "disable-file":
                self.file_suppressed |= rules
                continue
            self.line_suppressed.setdefault(i, set()).update(rules)
            # A comment alone on its line covers the rest of its
            # comment block (the justification) and the first code
            # line after it — for statements too long to carry a
            # trailing comment.
            if line.lstrip().startswith("#"):
                j = i + 1
                while j <= len(self.lines):
                    self.line_suppressed.setdefault(j, set()).update(rules)
                    stripped = self.lines[j - 1].lstrip()
                    if stripped and not stripped.startswith("#"):
                        break  # covered the first code line; stop
                    j += 1

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressed:
            return True
        return rule in self.line_suppressed.get(line, set())


class Project:
    """Every scanned file, plus the repo root for out-of-scan lookups
    (docs/, the env registry) that cross-file rules need."""

    def __init__(self, files: Sequence[SourceFile], root: str):
        self.files = list(files)
        self.root = root
        self._by_rel = {f.relpath: f for f in self.files}

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self._by_rel.get(relpath.replace(os.sep, "/"))

    def files_matching(self, prefix: str) -> List[SourceFile]:
        prefix = prefix.replace(os.sep, "/")
        return [f for f in self.files if f.relpath.startswith(prefix)]

    def read_doc(self, relpath: str) -> Optional[str]:
        """Text of a repo file outside the scan set (docs, README)."""
        p = os.path.join(self.root, relpath)
        try:
            with open(p, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None


class Checker:
    """Base class for one rule. Subclasses set ``rule``/``name`` and
    implement :meth:`check`; suppression and baseline filtering happen
    in the runner, so checkers yield every raw finding."""

    rule = "TPU000"
    name = "abstract"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self,
        file: SourceFile,
        node: ast.AST,
        message: str,
        symbol: str = "",
        severity: Optional[str] = None,
    ) -> Finding:
        return Finding(
            rule=self.rule,
            path=file.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            severity=severity or self.severity,
            symbol=symbol,
        )


def find_repo_root(start: str) -> str:
    """Nearest ancestor containing pyproject.toml (fallback: start)."""
    start = os.path.abspath(start)
    if os.path.isfile(start):
        start = os.path.dirname(start)
    d = start
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


def iter_py_files(
    paths: Sequence[str], root: str
) -> List[tuple[str, str]]:
    """(abspath, relpath) for every .py under ``paths``, deduped and
    sorted. Split from :func:`collect_files` so the incremental cache
    can hash contents without paying for a parse."""
    out: List[tuple[str, str]] = []
    seen: Set[str] = set()

    def add(path: str) -> None:
        ap = os.path.abspath(path)
        if ap in seen or not ap.endswith(".py"):
            return
        seen.add(ap)
        out.append((ap, os.path.relpath(ap, root)))

    for p in paths:
        if os.path.isfile(p):
            add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    add(os.path.join(dirpath, fn))
    out.sort(key=lambda pair: pair[1])
    return out


def collect_files(paths: Sequence[str], root: str) -> List[SourceFile]:
    out: List[SourceFile] = []
    for ap, rel in iter_py_files(paths, root):
        try:
            with open(ap, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        out.append(SourceFile(ap, rel, text))
    return out


def all_checkers() -> List[Checker]:
    """The shipped rule set, TPU001..TPU009 (import here, not at
    module top, so core stays importable from checker modules)."""
    from tpufw.analysis.donation import DonationChecker
    from tpufw.analysis.dtypes import DtypeDriftChecker
    from tpufw.analysis.envreg import EnvRegistryChecker
    from tpufw.analysis.hotloop import HotLoopPurityChecker
    from tpufw.analysis.locks import LockDisciplineChecker
    from tpufw.analysis.meshaxes import MeshAxisChecker
    from tpufw.analysis.obsnames import ObsNameChecker
    from tpufw.analysis.retrace import RetraceChurnChecker
    from tpufw.analysis.rng import RngDisciplineChecker

    return [
        HotLoopPurityChecker(),
        MeshAxisChecker(),
        RngDisciplineChecker(),
        EnvRegistryChecker(),
        ObsNameChecker(),
        DonationChecker(),
        RetraceChurnChecker(),
        DtypeDriftChecker(),
        LockDisciplineChecker(),
    ]


def run_analysis(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    checkers: Optional[Sequence[Checker]] = None,
) -> List[Finding]:
    """Parse ``paths``, run the (optionally filtered) checker set, and
    return suppression-filtered findings sorted by location. Parse
    failures surface as TPU000 errors rather than crashing the run."""
    root = root or find_repo_root(paths[0] if paths else ".")
    files = collect_files(paths, root)
    project = Project(files, root)
    checkers = list(checkers if checkers is not None else all_checkers())
    if rules is not None:
        want = set(rules)
        unknown = want - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.rule in want]
    findings: List[Finding] = []
    for f in files:
        if f.parse_error is not None:
            findings.append(
                Finding(
                    rule="TPU000",
                    path=f.relpath,
                    line=f.parse_error.lineno or 1,
                    col=(f.parse_error.offset or 0) + 1,
                    message=f"syntax error: {f.parse_error.msg}",
                    severity="error",
                    symbol="syntax-error",
                )
            )
    for checker in checkers:
        for finding in checker.check(project):
            src = project.file(finding.path)
            if src is not None and src.is_suppressed(
                finding.rule, finding.line
            ):
                continue
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------- baseline

BASELINE_VERSION = 1


def load_baseline(path: str) -> Set[str]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}"
        )
    return set(data.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    keys = sorted({f.key() for f in findings})
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    data = {
        "version": BASELINE_VERSION,
        "comment": (
            "tpulint ratchet: findings listed here predate the rule and "
            "are tolerated; new findings fail. This file may only "
            "shrink — fix or inline-suppress (with justification) "
            "instead of adding entries."
        ),
        "rule_counts": dict(sorted(counts.items())),
        "findings": keys,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=False)
        fh.write("\n")


def split_by_baseline(
    findings: Sequence[Finding], baseline: Set[str]
) -> tuple[List[Finding], List[Finding], Set[str]]:
    """(new, baselined, stale_keys): ``new`` fails the run, ``stale``
    are baseline entries no longer observed (the ratchet should
    shrink — rewrite the baseline to drop them)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen: Set[str] = set()
    for f in findings:
        k = f.key()
        if k in baseline:
            old.append(f)
            seen.add(k)
        else:
            new.append(f)
    return new, old, baseline - seen
