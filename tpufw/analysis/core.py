"""tpulint core: checker plugin framework, file walker, suppression,
baseline ratchet, and output formatting.

The rules this suite enforces (hot-loop purity, mesh-axis consistency,
RNG discipline, env/obs registry hygiene) are invariants a generic
linter cannot see — they live in the relationship between *this*
repo's subsystems (the jitted step, ``tpufw/mesh``, ``workloads/env``,
``obs/events``), not in any one expression. Everything here is stdlib
``ast``: the suite must run in the bare training container and in CI
without installing anything.

Vocabulary
----------
- A :class:`Checker` owns one rule ID (``TPU001``..) and yields
  :class:`Finding` objects over a :class:`Project` (the parsed tree of
  every scanned file), so cross-file rules are first-class.
- Suppression is per-line: a trailing ``# tpulint: disable=TPU001``
  comment (or one alone on the preceding line) silences that rule on
  that line; ``# tpulint: disable-file=TPU004`` anywhere silences the
  whole file. Suppressions are expected to carry a justification after
  the rule list — they are reviewed as code.
- The baseline (``analysis_baseline.json``) ratchets pre-existing
  findings: runs fail only on findings whose stable key is *not* in
  the baseline, and the baseline may only shrink. Keys deliberately
  exclude line numbers so unrelated edits don't churn it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning", "info")

LAYERS = ("python", "deploy", "protocol", "lifetime", "all")

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
)

# Directories never worth parsing (caches, VCS, vendored assets).
_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache", "node_modules", ".venv"}


def scan_suppression_lines(
    lines: Sequence[str],
) -> tuple[Set[str], Dict[int, Set[str]]]:
    """(file_suppressed, line -> rules) from ``# tpulint:`` comments.

    Works on any ``#``-comment syntax (python, YAML, Dockerfile), so
    the python scan set and the deploy layer share one suppression
    grammar: a trailing comment covers its line, a standalone comment
    covers its block plus the first non-comment line after it, and
    ``disable-file`` covers the whole file.
    """
    file_suppressed: Set[str] = set()
    line_suppressed: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",")}
        if m.group(1) == "disable-file":
            file_suppressed |= rules
            continue
        line_suppressed.setdefault(i, set()).update(rules)
        # A comment alone on its line covers the rest of its comment
        # block (the justification) and the first code line after it —
        # for statements too long to carry a trailing comment.
        if line.lstrip().startswith("#"):
            j = i + 1
            while j <= len(lines):
                line_suppressed.setdefault(j, set()).update(rules)
                stripped = lines[j - 1].lstrip()
                if stripped and not stripped.startswith("#"):
                    break  # covered the first code line; stop
                j += 1
    return file_suppressed, line_suppressed


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one location.

    ``symbol`` is the stable anchor used for baseline identity (an
    env-var name, axis literal, function qname, ...): baselines keyed
    on ``rule:path:symbol`` survive line drift from unrelated edits.
    """

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    severity: str = "error"
    symbol: str = ""

    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol or self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} [{self.severity}] {self.message}"
        )


class SourceFile:
    """One parsed python file + its suppression table."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=relpath)
        except SyntaxError as e:
            self.parse_error = e
        self.file_suppressed, self.line_suppressed = scan_suppression_lines(
            self.lines
        )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressed:
            return True
        return rule in self.line_suppressed.get(line, set())


class Project:
    """Every scanned file, plus the repo root for out-of-scan lookups
    (docs/, the env registry) that cross-file rules need. Since v3 it
    also carries the deploy layer: parsed manifests/configs/rendered
    chart templates (``deploy_files``, see
    :mod:`tpufw.analysis.manifests`)."""

    def __init__(
        self,
        files: Sequence[SourceFile],
        root: str,
        deploy_files: Sequence = (),
    ):
        self.files = list(files)
        self.root = root
        self.deploy_files = list(deploy_files)
        self._by_rel = {f.relpath: f for f in self.files}
        self._doc_trees: Dict[str, Optional[ast.Module]] = {}
        self._env_catalog: Optional["EnvCatalog"] = None

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self._by_rel.get(relpath.replace(os.sep, "/"))

    def files_matching(self, prefix: str) -> List[SourceFile]:
        prefix = prefix.replace(os.sep, "/")
        return [f for f in self.files if f.relpath.startswith(prefix)]

    def deploy_matching(self, prefix: str) -> List:
        prefix = prefix.replace(os.sep, "/")
        return [
            f for f in self.deploy_files if f.relpath.startswith(prefix)
        ]

    def read_doc(self, relpath: str) -> Optional[str]:
        """Text of a repo file outside the scan set (docs, README)."""
        p = os.path.join(self.root, relpath)
        try:
            with open(p, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    def parse_doc(self, relpath: str) -> Optional[ast.Module]:
        """AST of a python file resolved against the repo root even
        when it is outside the scan set — how deploy-layer rules read
        contract modules (``TrainerConfig`` fields, the bootstrap env
        names) under ``--layer deploy`` where no python is scanned."""
        relpath = relpath.replace(os.sep, "/")
        if relpath not in self._doc_trees:
            src = self.file(relpath)
            if src is not None:
                self._doc_trees[relpath] = src.tree
            else:
                text = self.read_doc(relpath)
                try:
                    tree = (
                        None if text is None
                        else ast.parse(text, filename=relpath)
                    )
                except SyntaxError:
                    tree = None
                self._doc_trees[relpath] = tree
        return self._doc_trees[relpath]

    def env_catalog(self) -> "EnvCatalog":
        if self._env_catalog is None:
            self._env_catalog = load_env_catalog(self)
        return self._env_catalog

    def is_suppressed(self, rule: str, path: str, line: int) -> bool:
        """Suppression lookup across both layers. Rendered chart
        variants share a relpath; a suppression in any variant wins
        (the comments come from the same template either way)."""
        src = self.file(path)
        if src is not None and src.is_suppressed(rule, line):
            return True
        path = path.replace(os.sep, "/")
        for df in self.deploy_files:
            if df.relpath == path and df.is_suppressed(rule, line):
                return True
        return False


# ----------------------------------------------------------- env catalog

#: Doc pages where a TPUFW_* mention counts as "documented"; the first
#: entry is the authoritative catalog with typed table rows.
ENV_CATALOG_DOC = "docs/ENV.md"
ENV_DOC_PAGES = (
    "docs/ENV.md",
    "docs/OBSERVABILITY.md",
    "docs/PERF.md",
    "docs/WORKFLOWS.md",
    "docs/PARITY.md",
    "README.md",
)

_ENV_NAME_RE = re.compile(r"TPUFW_[A-Z0-9_]+")
# A catalog table row: | `TPUFW_X` | type | default | meaning |
_ENV_ROW_RE = re.compile(
    r"^\|\s*`(TPUFW_[A-Z0-9_]+)`\s*\|\s*([^|]+?)\s*\|\s*([^|]*?)\s*\|"
)


@dataclasses.dataclass(frozen=True)
class EnvKnob:
    """One typed row of the docs/ENV.md catalog table."""

    name: str
    type: str  # "int" | "float" | "str" | "bool" | "opt int" | ...
    default: str


@dataclasses.dataclass(frozen=True)
class EnvCatalog:
    """Single-sourced docs/ENV.md parse shared by TPU004 and TPU012."""

    entries: Dict[str, EnvKnob]  # typed catalog table rows
    catalog_names: Set[str]  # every TPUFW_* mention in docs/ENV.md
    doc_names: Set[str]  # every TPUFW_* mention in any doc page


def load_env_catalog(project: Project) -> EnvCatalog:
    entries: Dict[str, EnvKnob] = {}
    catalog_names: Set[str] = set()
    doc_names: Set[str] = set()
    for page in ENV_DOC_PAGES:
        text = project.read_doc(page)
        if text is None:
            continue
        found = set(_ENV_NAME_RE.findall(text))
        doc_names |= found
        if page != ENV_CATALOG_DOC:
            continue
        catalog_names |= found
        for line in text.splitlines():
            m = _ENV_ROW_RE.match(line)
            if m:
                name, type_str, default = m.groups()
                entries.setdefault(
                    name, EnvKnob(name, type_str.strip(), default.strip())
                )
    return EnvCatalog(
        entries=entries, catalog_names=catalog_names, doc_names=doc_names
    )


def deploy_text_env_names(root: str) -> Set[str]:
    """Every TPUFW_* name textually present under ``deploy/`` — the
    raw-text (no yaml needed) mention source the stale-catalog check
    uses so chart-only knobs don't read as stale under
    ``--layer python``."""
    out: Set[str] = set()
    base = os.path.join(root, "deploy")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in _SKIP_DIRS and not d.startswith(".")
        )
        for fn in sorted(filenames):
            try:
                with open(
                    os.path.join(dirpath, fn), encoding="utf-8"
                ) as fh:
                    out |= set(_ENV_NAME_RE.findall(fh.read()))
            except (OSError, UnicodeDecodeError):
                continue
    return out


class Checker:
    """Base class for one rule. Subclasses set ``rule``/``name`` and
    implement :meth:`check`; suppression and baseline filtering happen
    in the runner, so checkers yield every raw finding."""

    rule = "TPU000"
    name = "abstract"
    severity = "error"
    # Which scan layer feeds the rule: "python" rules read the parsed
    # .py scan set, "deploy" rules read project.deploy_files (plus
    # contract modules via parse_doc). run_analysis(layer=...) filters
    # on this so CI's python-lint and deploy-lint jobs stay disjoint.
    layer = "python"

    def check(self, project: Project) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self,
        file: SourceFile,
        node: ast.AST,
        message: str,
        symbol: str = "",
        severity: Optional[str] = None,
    ) -> Finding:
        return Finding(
            rule=self.rule,
            path=file.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            severity=severity or self.severity,
            symbol=symbol,
        )


def find_repo_root(start: str) -> str:
    """Nearest ancestor containing pyproject.toml (fallback: start)."""
    start = os.path.abspath(start)
    if os.path.isfile(start):
        start = os.path.dirname(start)
    d = start
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


def iter_py_files(
    paths: Sequence[str], root: str
) -> List[tuple[str, str]]:
    """(abspath, relpath) for every .py under ``paths``, deduped and
    sorted. Split from :func:`collect_files` so the incremental cache
    can hash contents without paying for a parse."""
    out: List[tuple[str, str]] = []
    seen: Set[str] = set()

    def add(path: str) -> None:
        ap = os.path.abspath(path)
        if ap in seen or not ap.endswith(".py"):
            return
        seen.add(ap)
        out.append((ap, os.path.relpath(ap, root)))

    for p in paths:
        if os.path.isfile(p):
            add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    add(os.path.join(dirpath, fn))
    out.sort(key=lambda pair: pair[1])
    return out


def collect_files(paths: Sequence[str], root: str) -> List[SourceFile]:
    out: List[SourceFile] = []
    for ap, rel in iter_py_files(paths, root):
        try:
            with open(ap, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        out.append(SourceFile(ap, rel, text))
    return out


def all_checkers() -> List[Checker]:
    """The shipped rule set, TPU001..TPU022 (import here, not at
    module top, so core stays importable from checker modules)."""
    from tpufw.analysis.deploy import (
        BootstrapWiringChecker,
        ChartParityChecker,
        ConfigSchemaChecker,
        EnvKnobValidityChecker,
        TopologyMathChecker,
    )
    from tpufw.analysis.donation import DonationChecker
    from tpufw.analysis.dtypes import DtypeDriftChecker
    from tpufw.analysis.envreg import EnvRegistryChecker
    from tpufw.analysis.hotloop import HotLoopPurityChecker
    from tpufw.analysis.lifetime import (
        ConditionDisciplineChecker,
        CounterBalanceChecker,
        DonationWindowChecker,
        ResourceLifetimeChecker,
    )
    from tpufw.analysis.locks import LockDisciplineChecker
    from tpufw.analysis.meshaxes import MeshAxisChecker
    from tpufw.analysis.obsnames import ObsNameChecker
    from tpufw.analysis.protocol import (
        HttpSurfaceChecker,
        MetricLabelChecker,
        SpmdDivergenceChecker,
        WireContractChecker,
    )
    from tpufw.analysis.retrace import RetraceChurnChecker
    from tpufw.analysis.rng import RngDisciplineChecker

    return [
        HotLoopPurityChecker(),
        MeshAxisChecker(),
        RngDisciplineChecker(),
        EnvRegistryChecker(),
        ObsNameChecker(),
        DonationChecker(),
        RetraceChurnChecker(),
        DtypeDriftChecker(),
        LockDisciplineChecker(),
        TopologyMathChecker(),
        BootstrapWiringChecker(),
        EnvKnobValidityChecker(),
        ConfigSchemaChecker(),
        ChartParityChecker(),
        WireContractChecker(),
        SpmdDivergenceChecker(),
        HttpSurfaceChecker(),
        MetricLabelChecker(),
        ResourceLifetimeChecker(),
        ConditionDisciplineChecker(),
        CounterBalanceChecker(),
        DonationWindowChecker(),
    ]


def run_analysis(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    checkers: Optional[Sequence[Checker]] = None,
    layer: str = "all",
    extra_manifests: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Parse ``paths``, run the (optionally filtered) checker set, and
    return suppression-filtered findings sorted by location. Parse
    failures surface as TPU000 errors rather than crashing the run.

    ``layer`` selects the scan set: "python" parses ``paths`` and runs
    the single-process ast rules, "deploy" parses ``deploy/`` under
    the root and runs TPU010-014, "protocol" parses ``paths`` and runs
    the distributed-protocol rules TPU015-018 (same python scan set,
    no manifests), "lifetime" runs the resource-lifetime and
    concurrency-liveness rules TPU019-022 over the python scan set,
    "all" (default) does everything. The deploy layer
    degrades to nothing (with no error) when pyyaml is absent and
    layer="all"; requesting layer="deploy" without pyyaml raises
    ValueError.
    """
    if layer not in LAYERS:
        raise ValueError(f"unknown layer {layer!r}; choose from {LAYERS}")
    root = root or find_repo_root(paths[0] if paths else ".")
    files = collect_files(paths, root) if layer != "deploy" else []
    deploy_files: List = []
    if layer in ("deploy", "all"):
        from tpufw.analysis import manifests

        if manifests.yaml_available():
            deploy_files = manifests.collect_deploy_files(root)
            # Explicit extra manifests (--manifest): artifacts outside
            # the fixed deploy/ scan set, e.g. fleet scaling
            # recommendations, verified with the same rule set.
            for mpath in extra_manifests or ():
                df = manifests.load_manifest(mpath)
                if df is None:
                    raise ValueError(
                        f"--manifest {mpath}: unreadable"
                    )
                deploy_files.append(df)
        elif layer == "deploy":
            raise ValueError(
                "--layer deploy needs pyyaml to parse manifests "
                "(pip install pyyaml)"
            )
    project = Project(files, root, deploy_files=deploy_files)
    checkers = list(checkers if checkers is not None else all_checkers())
    if rules is not None:
        want = set(rules)
        unknown = want - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.rule in want]
    if layer != "all":
        checkers = [c for c in checkers if c.layer == layer]
    findings: List[Finding] = []
    for f in files:
        if f.parse_error is not None:
            findings.append(
                Finding(
                    rule="TPU000",
                    path=f.relpath,
                    line=f.parse_error.lineno or 1,
                    col=(f.parse_error.offset or 0) + 1,
                    message=f"syntax error: {f.parse_error.msg}",
                    severity="error",
                    symbol="syntax-error",
                )
            )
    for checker in checkers:
        for finding in checker.check(project):
            if project.is_suppressed(
                finding.rule, finding.path, finding.line
            ):
                continue
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------- baseline

BASELINE_VERSION = 1


def load_baseline(path: str) -> Set[str]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}"
        )
    return set(data.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    keys = sorted({f.key() for f in findings})
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    data = {
        "version": BASELINE_VERSION,
        "comment": (
            "tpulint ratchet: findings listed here predate the rule and "
            "are tolerated; new findings fail. This file may only "
            "shrink — fix or inline-suppress (with justification) "
            "instead of adding entries."
        ),
        "rule_counts": dict(sorted(counts.items())),
        "findings": keys,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=False)
        fh.write("\n")


def split_by_baseline(
    findings: Sequence[Finding], baseline: Set[str]
) -> tuple[List[Finding], List[Finding], Set[str]]:
    """(new, baselined, stale_keys): ``new`` fails the run, ``stale``
    are baseline entries no longer observed (the ratchet should
    shrink — rewrite the baseline to drop them)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen: Set[str] = set()
    for f in findings:
        k = f.key()
        if k in baseline:
            old.append(f)
            seen.add(k)
        else:
            new.append(f)
    return new, old, baseline - seen
