"""tpulint layer 4 — distributed-protocol rules (TPU015-TPU018).

PRs 11-12 made the repo a multi-process system: prefill, decode, and
router roles speak hand-rolled wire formats (TPFB page bundles, framed
JSON control frames, the ``X-TPUFW-Trace`` header, router HTTP JSON).
None of the single-process layers can see a producer writing
``"n_pages"`` while the consumer reads ``"num_pages"`` — the classic
cross-program drift MPMD decompositions die of. This layer checks the
contracts themselves:

TPU015  wire-contract drift. Producer/consumer functions declare the
        channel they speak with a structured comment::

            # wire: produces bundle-header via header
            # wire: consumes bundle-header via header

        (``via`` names the payload dict variable(s); producers without
        ``via`` contribute dict literals in return statements,
        ``json.dumps(...)`` arguments, and call arguments). A
        module-level dict constant tagged ``# wire: schema <channel>``
        (key -> (type, since-version, required)) becomes the channel's
        single source of truth. Flags: written-but-never-read,
        read-but-never-written, producer/consumer type mismatches, and
        unguarded reads of optional keys (no ``.get``/default and no
        enclosing if/try — version-gated reads are thereby exempt).

TPU016  SPMD divergence. Host-varying taint (process_index, env reads,
        time, randomness, file I/O — see spmd.py) steering a branch or
        loop bound whose body issues a collective, a
        ``jax.distributed`` call, or a jit dispatch: some hosts enter
        the collective, the rest never arrive, every participant
        blocks forever.

TPU017  HTTP surface drift. Endpoints, status codes, and headers the
        router actually serves (files tagged ``# http: serves``) vs.
        what the smoke harness claims (``# http: claims``) and what
        docs/OBSERVABILITY.md documents. A claimed-but-unserved
        surface is an error (the harness would fail against the real
        server); a served-but-unclaimed one is a warning (untested,
        undocumented surface).

TPU018  metric-label cardinality. Trace/span/request/session-id-shaped
        values used as metric label values explode Prometheus series
        cardinality; ``tenant`` is the one allowlisted id-ish label
        (bounded by the tenant set, and the SLO layer keys on it).

All extraction is syntactic (stdlib ast only). Dynamically-built keys
(``d[prefix + name]``), payloads forwarded through untagged helpers,
and cross-process framing are out of scope — see docs/ANALYSIS.md for
the limitation list.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from . import callgraph as cg
from . import spmd
from .core import Checker, Finding, Project, SourceFile

_WIRE_RE = re.compile(
    r"#\s*wire:\s*(produces|consumes)\s+([A-Za-z0-9_-]+)"
    r"(?:\s+via\s+([A-Za-z0-9_,\s]+?))?\s*$"
)
_SCHEMA_RE = re.compile(r"#\s*wire:\s*schema\s+([A-Za-z0-9_-]+)\s*$")
_HTTP_RE = re.compile(r"#\s*http:\s*(serves|claims)\s*$")

_JSON_TYPES = {"int", "str", "float", "bool", "list", "dict", "NoneType"}


# ------------------------------------------------------------ ast utils


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _ancestors(
    node: ast.AST, parents: Dict[int, ast.AST]
) -> Iterator[ast.AST]:
    cur = parents.get(id(node))
    while cur is not None:
        yield cur
        cur = parents.get(id(cur))


def _is_conditional(node: ast.AST, parents: Dict[int, ast.AST]) -> bool:
    """Write reached only on some executions: under an if/elif/else
    arm, a ternary, or an except handler. try: bodies and loop bodies
    count as unconditional — the happy path runs them."""
    return any(
        isinstance(a, (ast.If, ast.IfExp, ast.ExceptHandler))
        for a in _ancestors(node, parents)
    )


def _is_guarded(node: ast.AST, parents: Dict[int, ast.AST]) -> bool:
    """Read protected by SOME conditional context (if/ternary/try) —
    including version gates like ``if hdr["version"] >= 2:``."""
    return any(
        isinstance(a, (ast.If, ast.IfExp, ast.Try, ast.ExceptHandler))
        for a in _ancestors(node, parents)
    )


def _literal_type(node: ast.AST) -> Optional[str]:
    """Best-effort JSON-ish type of a written value."""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return "NoneType"
        if isinstance(node.value, bool):
            return "bool"
        if isinstance(node.value, int):
            return "int"
        if isinstance(node.value, float):
            return "float"
        if isinstance(node.value, str):
            return "str"
        return None
    if isinstance(node, ast.JoinedStr):
        return "str"
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, (ast.List, ast.ListComp, ast.Tuple)):
        return "list"
    if isinstance(node, ast.Call):
        nm = cg.call_name(node)
        if nm in ("int", "len", "ord"):
            return "int"
        if nm in ("str", "repr", "format"):
            return "str"
        if nm == "float":
            return "float"
        if nm == "bool":
            return "bool"
        if nm in ("list", "sorted", "tuple"):
            return "list"
        if nm == "dict":
            return "dict"
        if nm == "round":
            return "float" if len(node.args) > 1 else "int"
    return None


def _type_compatible(a: str, b: str) -> bool:
    if a == b:
        return True
    nums = {"int", "float"}
    return a in nums and b in nums and "bool" not in (a, b)


def _outer_dicts(expr: ast.AST) -> Iterator[ast.Dict]:
    """Outermost dict literals in ``expr`` (payload bodies); nested
    dicts are their own sub-payloads and stay out of the key set."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Dict):
            yield node
            continue
        stack.extend(ast.iter_child_nodes(node))


# ------------------------------------------------------------- markers


class _FnCtx:
    """One marker-bearing function: its node, location, and role."""

    def __init__(self, file: SourceFile, node: ast.AST, qname: str):
        self.file = file
        self.node = node
        self.qname = qname
        self.parents = _parent_map(node)


def _function_spans(
    f: SourceFile,
) -> List[Tuple[int, int, ast.AST, str]]:
    out: List[Tuple[int, int, ast.AST, str]] = []
    if f.tree is None:
        return out

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out.append(
                    (child.lineno, child.end_lineno or child.lineno,
                     child, q)
                )
                walk(child, q)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}.{child.name}" if prefix else
                     child.name)
            else:
                walk(child, prefix)

    walk(f.tree, "")
    return out


def _enclosing_fn(
    spans: Sequence[Tuple[int, int, ast.AST, str]], line: int
) -> Optional[Tuple[ast.AST, str]]:
    best: Optional[Tuple[int, int, ast.AST, str]] = None
    for lo, hi, node, q in spans:
        if lo <= line <= hi and (best is None or lo > best[0]):
            best = (lo, hi, node, q)
    return (best[2], best[3]) if best else None


class _Role:
    def __init__(self, ctx: _FnCtx, via: Optional[Set[str]]):
        self.ctx = ctx
        self.via = via  # None = unscoped


class _Schema:
    def __init__(
        self,
        file: SourceFile,
        node: ast.Dict,
        const_name: str,
        rows: Dict[str, Tuple[str, int, bool]],
    ):
        self.file = file
        self.node = node
        self.const_name = const_name
        self.rows = rows
        self.base_version = min(
            (since for _t, since, _r in rows.values()), default=1
        )

    def gated(self, key: str) -> bool:
        row = self.rows.get(key)
        return row is not None and row[1] > self.base_version


class _Channel:
    def __init__(self, name: str):
        self.name = name
        self.producers: List[_Role] = []
        self.consumers: List[_Role] = []
        self.schema: Optional[_Schema] = None


def _parse_schema(
    f: SourceFile, line: int, channel: str
) -> Optional[_Schema]:
    """The module-level dict constant the ``# wire: schema`` comment
    annotates (comment inside or up to 3 lines above the assign)."""
    if f.tree is None:
        return None
    for stmt in f.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            target, value = stmt.target, stmt.value
        else:
            continue
        if not (
            isinstance(target, ast.Name) and isinstance(value, ast.Dict)
        ):
            continue
        if not (stmt.lineno - 4 <= line <= (stmt.end_lineno or 0)):
            continue
        rows: Dict[str, Tuple[str, int, bool]] = {}
        for k, v in zip(value.keys, value.values):
            if not (
                isinstance(k, ast.Constant) and isinstance(k.value, str)
            ):
                continue
            tname, since, required = None, 1, True
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else []
            if elts:
                if isinstance(elts[0], ast.Name):
                    tname = elts[0].id
                elif isinstance(elts[0], ast.Constant) and isinstance(
                    elts[0].value, str
                ):
                    tname = elts[0].value
            if len(elts) > 1 and isinstance(elts[1], ast.Constant):
                since = int(elts[1].value)
            if len(elts) > 2 and isinstance(elts[2], ast.Constant):
                required = bool(elts[2].value)
            rows[k.value] = (tname or "?", since, required)
        if rows:
            return _Schema(f, value, target.id, rows)
    return None


def _collect_channels(
    project: Project, index: cg.ModuleIndex
) -> Dict[str, _Channel]:
    channels: Dict[str, _Channel] = {}

    def chan(name: str) -> _Channel:
        return channels.setdefault(name, _Channel(name))

    for f in project.files:
        if f.tree is None:
            continue
        spans = _function_spans(f)
        ctx_cache: Dict[int, _FnCtx] = {}
        for i, text in enumerate(f.lines, start=1):
            if "# wire:" not in text and "#wire:" not in text:
                continue
            m = _SCHEMA_RE.search(text)
            if m:
                schema = _parse_schema(f, i, m.group(1))
                if schema is not None:
                    chan(m.group(1)).schema = schema
                continue
            m = _WIRE_RE.search(text)
            if not m:
                continue
            hit = _enclosing_fn(spans, i)
            if hit is None:
                continue
            node, qname = hit
            ctx = ctx_cache.get(id(node))
            if ctx is None:
                ctx = _FnCtx(f, node, qname)
                ctx_cache[id(node)] = ctx
            via: Optional[Set[str]] = None
            if m.group(3):
                via = {
                    v.strip() for v in m.group(3).split(",") if v.strip()
                }
            role = _Role(ctx, via)
            if m.group(1) == "produces":
                chan(m.group(2)).producers.append(role)
            else:
                chan(m.group(2)).consumers.append(role)
    return channels


# --------------------------------------------------- producer extraction


class _Write:
    def __init__(
        self, key: str, node: ast.AST, conditional: bool,
        typename: Optional[str],
    ):
        self.key = key
        self.node = node
        self.conditional = conditional
        self.typename = typename


def _payload_names(ctx: _FnCtx, via: Optional[Set[str]]) -> Set[str]:
    if via is not None:
        return set(via)
    # Unscoped: names assigned a dict literal that are later returned
    # or handed to json.dumps as a bare name.
    assigned: Set[str] = set()
    used: Set[str] = set()
    for node in spmd.walk_own(ctx.node):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Dict
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigned.add(t.id)
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.value, ast.Dict)
            and isinstance(node.target, ast.Name)
        ):
            assigned.add(node.target.id)
        if isinstance(node, ast.Return) and node.value is not None:
            # Only the returned value itself (or tuple elements of
            # it): a name nested deeper — a dict VALUE like
            # ``{"stages": stages}`` — is a sub-payload, not this
            # channel's body.
            tops = (
                node.value.elts
                if isinstance(node.value, ast.Tuple)
                else [node.value]
            )
            for sub in tops:
                if isinstance(sub, ast.Name):
                    used.add(sub.id)
        if isinstance(node, ast.Call) and cg.call_name(node) in (
            "dumps", "dump"
        ):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    used.add(arg.id)
    return assigned & used


def _producer_writes(ctx: _FnCtx, via: Optional[Set[str]]) -> List[_Write]:
    writes: List[_Write] = []
    names = _payload_names(ctx, via)

    def dict_writes(d: ast.Dict) -> None:
        cond = _is_conditional(d, ctx.parents)
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                writes.append(
                    _Write(k.value, k, cond, _literal_type(v))
                )

    ret_maps: List[Dict[str, Tuple[ast.AST, Optional[str]]]] = []
    for node in spmd.walk_own(ctx.node):
        # dict literals assigned to a payload name
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Dict
        ):
            if any(
                isinstance(t, ast.Name) and t.id in names
                for t in node.targets
            ):
                dict_writes(node.value)
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.value, ast.Dict)
            and isinstance(node.target, ast.Name)
            and node.target.id in names
        ):
            dict_writes(node.value)
        # payload["k"] = v stores
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in names
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    writes.append(
                        _Write(
                            t.slice.value, t,
                            _is_conditional(t, ctx.parents),
                            _literal_type(node.value),
                        )
                    )
        # payload.setdefault("k", v) / payload.update({...})
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id in names:
                if (
                    node.func.attr == "setdefault"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    writes.append(
                        _Write(
                            node.args[0].value, node.args[0],
                            _is_conditional(node, ctx.parents),
                            _literal_type(node.args[1])
                            if len(node.args) > 1 else None,
                        )
                    )
                elif node.func.attr == "update" and node.args:
                    for d in _outer_dicts(node.args[0]):
                        dict_writes(d)
        # Anonymous dict literals in return statements are payload
        # bodies whether or not the marker scopes with `via` (bodies
        # like `return 200, {...}, headers` have no name to scope to).
        if isinstance(node, ast.Return) and node.value is not None:
            keys: Dict[str, Tuple[ast.AST, Optional[str]]] = {}
            for d in _outer_dicts(node.value):
                for k, v in zip(d.keys, d.values):
                    if isinstance(k, ast.Constant) and isinstance(
                        k.value, str
                    ):
                        keys[k.value] = (k, _literal_type(v))
            if keys:
                ret_maps.append(keys)
        elif via is None and isinstance(node, ast.Call) and not any(
            isinstance(a, ast.Return)
            for a in _ancestors(node, ctx.parents)
        ):
            # Unscoped only: dict literals handed straight to calls
            # (json.dumps({...}), _post(base, {...})); dicts inside
            # return expressions are the Return branch's, not ours.
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if isinstance(arg, ast.Dict):
                    dict_writes(arg)
    # Return-body conditionality is about exits, not nesting: a key
    # every dict-bearing return carries (e.g. the sole return inside a
    # retry loop's ``if done:``) is unconditionally produced; a key
    # only SOME returns carry (an error body vs the 200 body) is not.
    every = (
        set.intersection(*(set(m) for m in ret_maps))
        if ret_maps else set()
    )
    for m in ret_maps:
        for key, (knode, typ) in m.items():
            writes.append(_Write(key, knode, key not in every, typ))
    # schema-driven encode loop: `for key, spec in SCHEMA.items():`
    # marks every schema key written (handled by the caller, which
    # knows the schema const name).
    return writes


def _schema_loop_targets(
    ctx: _FnCtx, schema: _Schema
) -> List[Tuple[str, ast.For]]:
    """Loop variables bound to the schema's keys:
    ``for k in SCHEMA:`` / ``for k, spec in SCHEMA.items():``."""
    out: List[Tuple[str, ast.For]] = []
    for node in spmd.walk_own(ctx.node):
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        if isinstance(it, ast.Call) and isinstance(
            it.func, ast.Attribute
        ) and it.func.attr in ("items", "keys"):
            it = it.func.value
        if not (
            isinstance(it, ast.Name) and it.id == schema.const_name
        ):
            continue
        tgt = node.target
        if isinstance(tgt, ast.Tuple) and tgt.elts:
            tgt = tgt.elts[0]
        if isinstance(tgt, ast.Name):
            out.append((tgt.id, node))
    return out


# -------------------------------------------------- consumer extraction


class _Read:
    def __init__(self, key: str, node: ast.AST, guarded: bool):
        self.key = key
        self.node = node
        self.guarded = guarded


def _recv_matches(node: ast.AST, via: Optional[Set[str]]) -> bool:
    if via is None:
        return True
    if isinstance(node, ast.BoolOp):
        # ``(tmeta or {}).get(...)`` — the defaulting operand doesn't
        # change which payload is being read.
        return any(_recv_matches(v, via) for v in node.values)
    chain = cg.attr_chain(node)
    if chain:
        return chain[-1] in via or ".".join(chain) in via
    return False


def _file_str_tuples(f: SourceFile) -> Dict[str, Set[str]]:
    """Module-level ``NAME = ("a", "b", ...)`` all-string tuple/list
    constants — ModuleIndex only indexes scalar string constants, but
    key lists like router._SIGNAL_KEYS live in tuples."""
    out: Dict[str, Set[str]] = {}
    if f.tree is None:
        return out
    for stmt in f.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            tgt, value = stmt.target, stmt.value
        else:
            continue
        if not (
            isinstance(tgt, ast.Name)
            and isinstance(value, (ast.Tuple, ast.List))
            and value.elts
        ):
            continue
        vals = {
            el.value
            for el in value.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, str)
        }
        if len(vals) == len(value.elts):
            out[tgt.id] = vals
    return out


def _for_bindings(
    ctx: _FnCtx, index: cg.ModuleIndex, module: str,
    schema: Optional[_Schema],
) -> Dict[str, Tuple[Set[str], bool]]:
    """Loop-var name -> (possible string keys, is-schema-loop).

    Handles ``for k in _KEYS:`` over a resolvable constant tuple,
    positional unpacking over a literal tuple-of-tuples
    (``for src, dst in (("a", "b"), ...):``), and iteration over the
    channel's schema table."""
    out: Dict[str, Tuple[Set[str], bool]] = {}
    str_tuples = _file_str_tuples(ctx.file)
    for node in spmd.walk_own(ctx.node):
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        unwrapped = it
        if isinstance(it, ast.Call) and isinstance(
            it.func, ast.Attribute
        ) and it.func.attr in ("items", "keys"):
            unwrapped = it.func.value
        if (
            schema is not None
            and isinstance(unwrapped, ast.Name)
            and unwrapped.id == schema.const_name
        ):
            tgt = node.target
            if isinstance(tgt, ast.Tuple) and tgt.elts:
                tgt = tgt.elts[0]
            if isinstance(tgt, ast.Name):
                out[tgt.id] = (set(schema.rows), True)
            continue
        tgt = node.target
        if isinstance(tgt, ast.Name):
            vals = {
                s for _n, s in index.resolve_str_elements(it, module)
            }
            if not vals and isinstance(it, ast.Name):
                vals = str_tuples.get(it.id, set())
            if vals:
                out[tgt.id] = (vals, False)
        elif isinstance(tgt, ast.Tuple) and isinstance(
            it, (ast.Tuple, ast.List)
        ):
            # positional binding over a literal tuple-of-tuples
            for pos, name_node in enumerate(tgt.elts):
                if not isinstance(name_node, ast.Name):
                    continue
                vals = set()
                for row in it.elts:
                    if (
                        isinstance(row, (ast.Tuple, ast.List))
                        and pos < len(row.elts)
                        and isinstance(row.elts[pos], ast.Constant)
                        and isinstance(row.elts[pos].value, str)
                    ):
                        vals.add(row.elts[pos].value)
                if vals:
                    out[name_node.id] = (vals, False)
    return out


def _consumer_reads(
    ctx: _FnCtx, via: Optional[Set[str]], index: cg.ModuleIndex,
    module: str, schema: Optional[_Schema],
) -> List[_Read]:
    reads: List[_Read] = []
    bindings = _for_bindings(ctx, index, module, schema)
    for node in spmd.walk_own(ctx.node):
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            if not _recv_matches(node.value, via):
                continue
            guarded = _is_guarded(node, ctx.parents)
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(
                sl.value, str
            ):
                reads.append(_Read(sl.value, node, guarded))
            elif isinstance(sl, ast.Name) and sl.id in bindings:
                keys, is_schema = bindings[sl.id]
                for key in keys:
                    # The schema loop validates presence itself.
                    reads.append(
                        _Read(key, node, guarded or is_schema)
                    )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop")
            and node.args
        ):
            if not _recv_matches(node.func.value, via):
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(
                a0.value, str
            ):
                reads.append(_Read(a0.value, a0, True))
            elif isinstance(a0, ast.Name) and a0.id in bindings:
                for key in bindings[a0.id][0]:
                    reads.append(_Read(key, a0, True))
    return reads


# --------------------------------------------------------------- TPU015


class WireContractChecker(Checker):
    rule = "TPU015"
    name = "wire-contract-drift"
    severity = "error"
    layer = "protocol"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        channels = _collect_channels(project, index)
        for ch in channels.values():
            yield from self._check_channel(ch, index)

    def _check_channel(
        self, ch: _Channel, index: cg.ModuleIndex
    ) -> Iterator[Finding]:
        schema = ch.schema
        writes: Dict[str, List[Tuple[_Role, _Write]]] = {}
        schema_written_by: List[_Role] = []
        for role in ch.producers:
            fn_writes = _producer_writes(role.ctx, role.via)
            if schema is not None and _schema_loop_targets(
                role.ctx, schema
            ):
                schema_written_by.append(role)
            for w in fn_writes:
                writes.setdefault(w.key, []).append((role, w))
        reads: Dict[str, List[Tuple[_Role, _Read]]] = {}
        for role in ch.consumers:
            module = cg.module_name(role.ctx.file.relpath)
            for r in _consumer_reads(
                role.ctx, role.via, index, module, schema
            ):
                reads.setdefault(r.key, []).append((role, r))

        written_keys = set(writes)
        if schema is not None and schema_written_by:
            written_keys |= set(schema.rows)

        # -- schema membership + type agreement --------------------
        if schema is not None:
            for key, sites in writes.items():
                if key not in schema.rows:
                    role, w = sites[0]
                    yield self.finding(
                        role.ctx.file, w.node,
                        f"channel '{ch.name}': producer "
                        f"{role.ctx.qname} writes key '{key}' that is "
                        f"not in the {schema.const_name} schema",
                        symbol=f"{ch.name}:{key}:not-in-schema",
                    )
                    continue
                want = schema.rows[key][0]
                for role, w in sites:
                    if w.typename and want in _JSON_TYPES and not (
                        _type_compatible(w.typename, want)
                    ):
                        yield self.finding(
                            role.ctx.file, w.node,
                            f"channel '{ch.name}': key '{key}' is "
                            f"declared {want} in {schema.const_name} "
                            f"but written as {w.typename}",
                            symbol=f"{ch.name}:{key}:type-mismatch",
                        )
            for key, sites in reads.items():
                if key not in schema.rows:
                    role, r = sites[0]
                    yield self.finding(
                        role.ctx.file, r.node,
                        f"channel '{ch.name}': consumer "
                        f"{role.ctx.qname} reads key '{key}' that is "
                        f"not in the {schema.const_name} schema",
                        symbol=f"{ch.name}:{key}:not-in-schema",
                    )

        # -- producer-side type disagreement (schema-less) ----------
        if schema is None:
            for key, sites in writes.items():
                typed = [
                    (role, w) for role, w in sites if w.typename
                    and w.typename != "NoneType"
                ]
                for (r1, w1), (r2, w2) in zip(typed, typed[1:]):
                    if not _type_compatible(w1.typename, w2.typename):
                        yield self.finding(
                            r2.ctx.file, w2.node,
                            f"channel '{ch.name}': key '{key}' is "
                            f"written as {w1.typename} by "
                            f"{r1.ctx.qname} but as {w2.typename} by "
                            f"{r2.ctx.qname}",
                            symbol=f"{ch.name}:{key}:type-mismatch",
                        )

        # -- written-but-never-read ---------------------------------
        if ch.consumers:
            for key in sorted(set(writes) - set(reads)):
                role, w = writes[key][0]
                yield self.finding(
                    role.ctx.file, w.node,
                    f"channel '{ch.name}': key '{key}' is written by "
                    f"{role.ctx.qname} but never read by any declared "
                    f"consumer",
                    symbol=f"{ch.name}:{key}:written-never-read",
                )

        # -- read-but-never-written + optional-guard ----------------
        if not ch.producers and schema is None:
            return
        for key, sites in sorted(reads.items()):
            in_schema = schema is not None and key in schema.rows
            if key not in written_keys and not in_schema:
                for role, r in sites[:1]:
                    yield self.finding(
                        role.ctx.file, r.node,
                        f"channel '{ch.name}': key '{key}' is read by "
                        f"{role.ctx.qname} but no declared producer "
                        f"writes it",
                        symbol=f"{ch.name}:{key}:read-never-written",
                        severity="warning" if r.guarded else "error",
                    )
                continue
            optional = self._optional(ch, key, writes, schema)
            if not optional:
                continue
            for role, r in sites:
                if r.guarded:
                    continue
                why = (
                    f"optional in {schema.const_name}"
                    if in_schema and not schema.rows[key][2]
                    else f"gated on version > {schema.base_version}"
                    if in_schema and schema.gated(key)
                    else "not written by every producer on every path"
                )
                yield self.finding(
                    role.ctx.file, r.node,
                    f"channel '{ch.name}': key '{key}' is {why} but "
                    f"{role.ctx.qname} reads it without a "
                    f".get/default guard",
                    symbol=f"{ch.name}:{key}:unguarded-optional",
                )

    @staticmethod
    def _optional(
        ch: _Channel,
        field: str,
        writes: Dict[str, List[Tuple[_Role, _Write]]],
        schema: Optional[_Schema],
    ) -> bool:
        if schema is not None and field in schema.rows:
            _t, _since, required = schema.rows[field]
            return (not required) or schema.gated(field)
        sites = writes.get(field, [])
        if not sites:
            return False
        writers = {id(role.ctx.node) for role, _w in sites}
        all_producers = {
            id(role.ctx.node) for role in ch.producers
        }
        if writers != all_producers:
            return True  # some producer never sends this key
        return all(w.conditional for _role, w in sites)


# --------------------------------------------------------------- TPU016


class SpmdDivergenceChecker(Checker):
    rule = "TPU016"
    name = "spmd-divergence"
    severity = "error"
    layer = "protocol"

    def check(self, project: Project) -> Iterator[Finding]:
        seen: Set[Tuple[str, int]] = set()
        for div in spmd.find_divergence(project):
            f = div.fi.file
            key = (f.relpath, div.node.lineno)
            if key in seen:
                continue
            seen.add(key)
            shape = (
                "a loop bound" if isinstance(div.node, (ast.For,
                                                        ast.While))
                else "a branch"
            )
            tail = (
                f"early-exits past {div.sink} later in the function"
                if div.early_exit
                else f"dominates {div.sink}"
            )
            yield self.finding(
                f, div.node,
                f"host-varying value ({div.kind}) steers {shape} in "
                f"{div.fi.qname} that {tail}; hosts that skip it "
                f"never join the collective and every participant "
                f"hangs",
                symbol=f"divergence:{div.fi.qname}:{div.kind}",
            )


# --------------------------------------------------------------- TPU017

_ENDPOINT_RE = re.compile(r"^/[a-z][a-z0-9_]*$")
_DOC_ENDPOINT_RE = re.compile(r"`(/[a-z][a-z0-9_]*)`")
_DOC_CODE_RE = re.compile(r"\b([1-5]\d\d)\b")
_DOC_HEADER_RE = re.compile(r"`([A-Z][A-Za-z]*(?:-[A-Za-z]+)+)`")
_PATHISH = {"path", "url", "endpoint", "base", "route"}


class _Surface:
    def __init__(self) -> None:
        self.endpoints: Dict[str, Tuple[SourceFile, ast.AST]] = {}
        self.codes: Dict[int, Tuple[SourceFile, ast.AST]] = {}
        self.headers: Dict[str, Tuple[SourceFile, ast.AST]] = {}


class HttpSurfaceChecker(Checker):
    rule = "TPU017"
    name = "http-surface-drift"
    severity = "error"
    layer = "protocol"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        served, claimed = _Surface(), _Surface()
        any_server = any_client = False
        for f in project.files:
            if f.tree is None:
                continue
            mode = None
            for text in f.lines:
                m = _HTTP_RE.search(text)
                if m:
                    mode = m.group(1)
                    break
            if mode == "serves":
                any_server = True
                self._extract_served(f, index, served)
            elif mode == "claims":
                any_client = True
                self._extract_claimed(f, index, claimed)
        doc_claims = self._doc_claims(project)
        if not any_server:
            return
        # claimed but not served: the harness/doc describes a surface
        # the server does not have — hard drift.
        for path, (cf, node) in sorted(claimed.endpoints.items()):
            if path not in served.endpoints:
                yield self.finding(
                    cf, node,
                    f"endpoint {path} is claimed by {cf.relpath} but "
                    f"no tagged server serves it",
                    symbol=f"endpoint:{path}:unserved",
                )
        for code, (cf, node) in sorted(claimed.codes.items()):
            if code not in served.codes:
                yield self.finding(
                    cf, node,
                    f"status code {code} is asserted by {cf.relpath} "
                    f"but no tagged server sends it",
                    symbol=f"status:{code}:unserved",
                )
        for hdr, (cf, node) in sorted(claimed.headers.items()):
            if hdr.lower() not in {
                h.lower() for h in served.headers
            } and hdr.lower() not in ("content-type", "content-length"):
                yield self.finding(
                    cf, node,
                    f"header {hdr} is expected by {cf.relpath} but no "
                    f"tagged server sends it",
                    symbol=f"header:{hdr}:unserved",
                )
        # served but claimed nowhere (code or docs): untested,
        # undocumented surface. Warning — it works, nothing checks it.
        if not any_client and not doc_claims[0]:
            return
        all_claimed_eps = set(claimed.endpoints) | doc_claims[0]
        all_claimed_codes = set(claimed.codes) | doc_claims[1]
        for path, (sf, node) in sorted(served.endpoints.items()):
            if path not in all_claimed_eps:
                yield self.finding(
                    sf, node,
                    f"endpoint {path} is served but neither the smoke "
                    f"harness nor docs/OBSERVABILITY.md claims it",
                    symbol=f"endpoint:{path}:unclaimed",
                    severity="warning",
                )
        for code, (sf, node) in sorted(served.codes.items()):
            if code not in all_claimed_codes:
                yield self.finding(
                    sf, node,
                    f"status code {code} is served but neither the "
                    f"smoke harness nor docs/OBSERVABILITY.md claims "
                    f"it",
                    symbol=f"status:{code}:unclaimed",
                    severity="warning",
                )

    @staticmethod
    def _extract_served(
        f: SourceFile, index: cg.ModuleIndex, out: _Surface
    ) -> None:
        module = cg.module_name(f.relpath)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Compare):
                # self.path == "/healthz"
                sides = [node.left] + list(node.comparators)
                chains = [cg.attr_chain(s) for s in sides]
                if any(c and c[-1] in _PATHISH for c in chains):
                    for s in sides:
                        if isinstance(s, ast.Constant) and isinstance(
                            s.value, str
                        ) and _ENDPOINT_RE.match(s.value):
                            out.endpoints.setdefault(s.value, (f, s))
                        # path in ("/a", "/b") — membership routing
                        elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                            for elt in s.elts:
                                if isinstance(elt, ast.Constant) and \
                                        isinstance(elt.value, str) and \
                                        _ENDPOINT_RE.match(elt.value):
                                    out.endpoints.setdefault(
                                        elt.value, (f, elt)
                                    )
            if isinstance(node, ast.Call):
                nm = cg.call_name(node)
                if nm in ("_reply", "reply", "send_response") and \
                        node.args:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, int
                    ) and 100 <= a0.value <= 599:
                        out.codes.setdefault(a0.value, (f, a0))
                if nm == "send_header" and node.args:
                    h = index.resolve_str(node.args[0], module)
                    if h:
                        out.headers.setdefault(h, (f, node.args[0]))
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if isinstance(v, ast.Tuple) and v.elts:
                    first = v.elts[0]
                    if isinstance(first, ast.Constant) and isinstance(
                        first.value, int
                    ) and 100 <= first.value <= 599:
                        out.codes.setdefault(first.value, (f, first))
                    # header tuples riding in the same return
                    for sub in ast.walk(v):
                        if (
                            isinstance(sub, ast.Tuple)
                            and len(sub.elts) == 2
                        ):
                            h = index.resolve_str(sub.elts[0], module)
                            if h and _DOC_HEADER_RE.match(f"`{h}`"):
                                out.headers.setdefault(
                                    h, (f, sub.elts[0])
                                )

    @staticmethod
    def _extract_claimed(
        f: SourceFile, index: cg.ModuleIndex, out: _Surface
    ) -> None:
        for node in ast.walk(f.tree):
            # base + "/generate" — endpoint concatenated onto a host
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Add
            ):
                for side in (node.left, node.right):
                    if isinstance(side, ast.Constant) and isinstance(
                        side.value, str
                    ) and _ENDPOINT_RE.match(side.value):
                        out.endpoints.setdefault(side.value, (f, side))
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                statusish = False
                for s in sides:
                    chain = cg.attr_chain(s)
                    name = chain[-1] if chain else None
                    if name in ("status", "code", "status_code"):
                        statusish = True
                if statusish:
                    for s in sides:
                        if isinstance(s, ast.Constant) and isinstance(
                            s.value, int
                        ) and 100 <= s.value <= 599:
                            out.codes.setdefault(s.value, (f, s))
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
            ):
                chain = cg.attr_chain(node.func.value)
                if chain and "headers" in chain[-1]:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str
                    ):
                        out.headers.setdefault(a0.value, (f, a0))

    @staticmethod
    def _doc_claims(project: Project) -> Tuple[Set[str], Set[int]]:
        text = project.read_doc("docs/OBSERVABILITY.md") or ""
        endpoints = set(_DOC_ENDPOINT_RE.findall(text))
        codes: Set[int] = set()
        for line in text.splitlines():
            if "|" in line and _DOC_ENDPOINT_RE.search(line):
                codes |= {
                    int(c) for c in _DOC_CODE_RE.findall(line)
                }
        return endpoints, codes


# --------------------------------------------------------------- TPU018

_ID_SHAPED_RE = re.compile(
    r"(?:^|_)(?:trace|span|session|request|req|correlation|uuid|guid)"
    r"(?:_?id)?$|(?:^|_)id$",
    re.IGNORECASE,
)
_ID_MINTING = {"uuid1", "uuid4", "token_hex", "token_bytes", "urandom",
               "hex", "mint", "mint_id"}
_METRIC_METHODS = {"inc", "observe", "set", "labels"}


def _metric_receiver(chain: Sequence[str]) -> bool:
    for seg in chain[:-1]:
        s = seg.lstrip("_").lower()
        if s.startswith(("h_", "g_", "c_")) or "metric" in s:
            return True
    return False


def _id_shaped(node: ast.AST) -> Optional[str]:
    """Why the expression looks like an unbounded id, or None."""
    chain = cg.attr_chain(node)
    if chain and _ID_SHAPED_RE.search(chain[-1]):
        return f"'{'.'.join(chain)}' is id-shaped"
    if isinstance(node, ast.Call):
        nm = cg.call_name(node)
        if nm in _ID_MINTING:
            return f"{nm}() mints a fresh id per call"
    if isinstance(node, ast.JoinedStr):
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                why = _id_shaped(v.value)
                if why:
                    return why
    return None


class MetricLabelChecker(Checker):
    rule = "TPU018"
    name = "metric-label-cardinality"
    severity = "error"
    layer = "protocol"

    def check(self, project: Project) -> Iterator[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                ):
                    continue
                recv = node.func.value
                chain = cg.attr_chain(node.func) or []
                is_metric = _metric_receiver(chain)
                if not is_metric and isinstance(recv, ast.Call):
                    inner = cg.call_name(recv)
                    is_metric = inner in (
                        "counter", "gauge", "histogram", "summary"
                    )
                if not is_metric:
                    continue
                for kw in node.keywords:
                    if kw.arg is None or kw.arg == "tenant":
                        continue  # tenant is the allowlisted label
                    value_chain = cg.attr_chain(kw.value)
                    if value_chain and value_chain[-1] == "tenant":
                        continue
                    why = _id_shaped(kw.value)
                    if why is None and kw.arg is not None and \
                            _ID_SHAPED_RE.search(kw.arg):
                        why = f"label name '{kw.arg}' is id-shaped"
                    if why is None:
                        continue
                    yield self.finding(
                        f, kw.value,
                        f"metric label '{kw.arg}' gets an "
                        f"unbounded-cardinality value ({why}); each "
                        f"distinct value is a new Prometheus series — "
                        f"put ids in events/traces, not labels",
                        symbol=f"label:{kw.arg}",
                    )
