"""Intra-function control-flow graphs for the lifetime layer.

Every path-sensitive rule in :mod:`tpufw.analysis.lifetime` (acquire/
release pairing, counter balance, donation windows) asks the same
question: *can execution reach a function exit while still holding
something?* Answering it needs more than the lexical ancestor walks
the earlier layers get away with — it needs explicit edges for the
ways Python leaves a region early:

- ``return`` / ``raise`` / ``break`` / ``continue`` statements;
- the *implicit* exception edge out of any statement that can raise
  (a call, an ``assert``, an ``await``) into the innermost matching
  handler — or clean out of the function;
- ``finally`` blocks, which every in-``try`` exit must traverse.

The graph is statement-granular: one node per ``ast.stmt`` occurrence
(compound statements contribute a *header* node for their test /
items, then recurse). ``finally`` bodies are **duplicated per
continuation** (fall-through, return, exception, break, continue), the
textbook trick that keeps a return path from "leaking" into the
after-``try`` code of some other path. Rules attach meaning to nodes
(resource events) and run a worklist dataflow over the edges; this
module knows nothing about resources.

Deliberate imprecision, documented so the rules can document it:

- "may raise" is syntactic: a statement raises iff it contains a
  ``Call``, ``Await``, ``Raise``, or ``Assert``. Attribute access,
  subscripts, and arithmetic are treated as non-raising — flagging
  every ``KeyError``-shaped edge would drown the true positives.
- Every handler of a ``try`` is a possible target of every raising
  statement in its body (no type matching); the exception *escapes*
  the ``try`` too unless some handler is catch-all (bare ``except``,
  ``except BaseException``, or ``except Exception``).
- ``with`` blocks get no special exception semantics (a suppressing
  ``__exit__`` is invisible); the *lifetime* layer handles
  ``with``-managed acquisition at the event level instead.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# Edge kinds. "true"/"false" are the two arms of a test-bearing header
# (If/While — the lifetime layer refines obligations along them);
# "exc" carries an in-flight exception; everything else is "next".
EDGE_NEXT = "next"
EDGE_TRUE = "true"
EDGE_FALSE = "false"
EDGE_EXC = "exc"

# Node kinds (``Node.kind``).
N_ENTRY = "entry"
N_STMT = "stmt"
N_RETURN_EXIT = "return-exit"  # normal completion (return / fall-off)
N_EXC_EXIT = "exc-exit"  # exception escapes the function


@dataclasses.dataclass
class Node:
    id: int
    kind: str
    stmt: Optional[ast.stmt] = None  # None for entry/exit nodes

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0) if self.stmt else 0


class CFG:
    """One function's control-flow graph."""

    def __init__(self) -> None:
        self.nodes: List[Node] = []
        self.succs: Dict[int, List[Tuple[int, str]]] = {}
        self.entry = self._new(N_ENTRY)
        self.exit_return = self._new(N_RETURN_EXIT)
        self.exit_exc = self._new(N_EXC_EXIT)

    def _new(self, kind: str, stmt: Optional[ast.stmt] = None) -> int:
        n = Node(len(self.nodes), kind, stmt)
        self.nodes.append(n)
        self.succs[n.id] = []
        return n.id

    def edge(self, a: int, b: int, kind: str = EDGE_NEXT) -> None:
        if (b, kind) not in self.succs[a]:
            self.succs[a].append((b, kind))

    def node(self, i: int) -> Node:
        return self.nodes[i]

    def preds_of_exit(self, exit_id: int) -> List[Tuple[int, str]]:
        """(node, edge kind) pairs flowing into ``exit_id``."""
        out = []
        for a, succs in self.succs.items():
            for b, kind in succs:
                if b == exit_id:
                    out.append((a, kind))
        return out


# Builtins that raise only on type-confused arguments — treating
# them as raise sites would make every statement between an acquire
# and its release a phantom leak path, drowning the signal the
# lifetime layer exists for.
_NO_RAISE_BUILTINS = frozenset({
    "len", "int", "float", "bool", "str", "repr", "abs", "min", "max",
    "list", "tuple", "dict", "set", "frozenset", "sorted", "enumerate",
    "zip", "range", "isinstance", "issubclass", "id", "getattr",
    "hasattr", "callable", "print",
})


def may_raise(node: ast.AST) -> bool:
    """Syntactic may-raise: contains a call-shaped or raise-shaped
    expression (minus the benign-builtin whitelist above). Nested
    function/class bodies don't execute here and are excluded (their
    *decorators* still count via the header)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Await, ast.Raise, ast.Assert)):
            return True
        if isinstance(sub, ast.Call):
            f = sub.func
            if (
                isinstance(f, ast.Name)
                and f.id in _NO_RAISE_BUILTINS
            ):
                continue
            return True
    return False


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Attribute):
        names = [t.attr]
    elif isinstance(t, ast.Tuple):
        for el in t.elts:
            if isinstance(el, ast.Name):
                names.append(el.id)
            elif isinstance(el, ast.Attribute):
                names.append(el.attr)
    return any(n in ("BaseException", "Exception") for n in names)


class _Ctx:
    """Continuation targets visible to the statement being built.
    ``finally`` wrapping replaces each with its finally-copy."""

    __slots__ = ("ret_to", "exc_to", "break_to", "continue_to")

    def __init__(self, ret_to, exc_to, break_to=None, continue_to=None):
        self.ret_to = ret_to
        self.exc_to = exc_to
        self.break_to = break_to
        self.continue_to = continue_to

    def clone(self, **kw) -> "_Ctx":
        c = _Ctx(self.ret_to, self.exc_to, self.break_to,
                 self.continue_to)
        for k, v in kw.items():
            setattr(c, k, v)
        return c


class _Builder:
    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.cfg = CFG()

    def build(self) -> CFG:
        cfg = self.cfg
        ctx = _Ctx(ret_to=cfg.exit_return, exc_to=cfg.exit_exc)
        first = self._seq(self.fn.body, cfg.exit_return, ctx)
        cfg.edge(cfg.entry, first)
        return cfg

    # -- sequencing --------------------------------------------------

    def _seq(
        self, stmts: Sequence[ast.stmt], after: int, ctx: _Ctx
    ) -> int:
        """Wire ``stmts`` so the sequence falls through to ``after``;
        returns the entry node of the first statement."""
        entry = after
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, ctx)
        return entry

    def _stmt(self, stmt: ast.stmt, after: int, ctx: _Ctx) -> int:
        cfg = self.cfg
        if isinstance(stmt, ast.Return):
            n = cfg._new(N_STMT, stmt)
            cfg.edge(n, ctx.ret_to)
            if stmt.value is not None and may_raise(stmt.value):
                cfg.edge(n, ctx.exc_to, EDGE_EXC)
            return n
        if isinstance(stmt, ast.Raise):
            n = cfg._new(N_STMT, stmt)
            cfg.edge(n, ctx.exc_to, EDGE_EXC)
            return n
        if isinstance(stmt, ast.Break):
            n = cfg._new(N_STMT, stmt)
            cfg.edge(n, ctx.break_to if ctx.break_to is not None
                     else after)
            return n
        if isinstance(stmt, ast.Continue):
            n = cfg._new(N_STMT, stmt)
            cfg.edge(n, ctx.continue_to if ctx.continue_to is not None
                     else after)
            return n
        if isinstance(stmt, ast.If):
            n = cfg._new(N_STMT, stmt)
            body = self._seq(stmt.body, after, ctx)
            cfg.edge(n, body, EDGE_TRUE)
            if stmt.orelse:
                orelse = self._seq(stmt.orelse, after, ctx)
                cfg.edge(n, orelse, EDGE_FALSE)
            else:
                cfg.edge(n, after, EDGE_FALSE)
            if may_raise(stmt.test):
                cfg.edge(n, ctx.exc_to, EDGE_EXC)
            return n
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, after, ctx)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, after, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            n = cfg._new(N_STMT, stmt)
            body = self._seq(stmt.body, after, ctx)
            cfg.edge(n, body)
            if any(may_raise(item.context_expr) for item in stmt.items):
                cfg.edge(n, ctx.exc_to, EDGE_EXC)
            return n
        if isinstance(stmt, ast.Match):
            n = cfg._new(N_STMT, stmt)
            fell = False
            for case in stmt.cases:
                body = self._seq(case.body, after, ctx)
                cfg.edge(n, body, EDGE_TRUE)
                if (isinstance(case.pattern, ast.MatchAs)
                        and case.pattern.pattern is None
                        and case.guard is None):
                    fell = True  # wildcard arm: some case always runs
            if not fell:
                cfg.edge(n, after, EDGE_FALSE)
            if may_raise(stmt.subject):
                cfg.edge(n, ctx.exc_to, EDGE_EXC)
            return n
        # Simple statement (assign, expr, assert, import, ...).
        n = cfg._new(N_STMT, stmt)
        cfg.edge(n, after)
        if may_raise(stmt):
            cfg.edge(n, ctx.exc_to, EDGE_EXC)
        return n

    def _loop(self, stmt: ast.stmt, after: int, ctx: _Ctx) -> int:
        cfg = self.cfg
        header = cfg._new(N_STMT, stmt)
        loop_ctx = ctx.clone(break_to=after, continue_to=header)
        body = self._seq(stmt.body, header, loop_ctx)
        if isinstance(stmt, ast.While):
            cfg.edge(header, body, EDGE_TRUE)
            test = stmt.test
            infinite = (
                isinstance(test, ast.Constant) and bool(test.value)
            )
            if not infinite:
                exit_to = (
                    self._seq(stmt.orelse, after, ctx)
                    if stmt.orelse else after
                )
                cfg.edge(header, exit_to, EDGE_FALSE)
            if may_raise(test):
                cfg.edge(header, ctx.exc_to, EDGE_EXC)
        else:  # For / AsyncFor: iteration may end any time
            cfg.edge(header, body, EDGE_TRUE)
            exit_to = (
                self._seq(stmt.orelse, after, ctx)
                if stmt.orelse else after
            )
            cfg.edge(header, exit_to, EDGE_FALSE)
            if may_raise(stmt.iter):
                cfg.edge(header, ctx.exc_to, EDGE_EXC)
        return header

    def _try(self, stmt: ast.Try, after: int, ctx: _Ctx) -> int:
        cfg = self.cfg

        # finally duplication: each continuation target T reachable
        # from inside the try is replaced by a fresh copy of the
        # finally body whose tail falls through to T.
        if stmt.finalbody:
            copies: Dict[int, int] = {}

            def through_finally(target: int) -> int:
                if target not in copies:
                    copies[target] = self._seq(
                        stmt.finalbody, target, ctx
                    )
                return copies[target]
        else:
            def through_finally(target: int) -> int:
                return target

        after_f = through_finally(after)
        inner = ctx.clone(
            ret_to=through_finally(ctx.ret_to),
            exc_to=through_finally(ctx.exc_to),
        )
        if ctx.break_to is not None:
            inner.break_to = through_finally(ctx.break_to)
        if ctx.continue_to is not None:
            inner.continue_to = through_finally(ctx.continue_to)

        # Handlers run with the outer continuations (their own raises
        # propagate out through the finally).
        handler_entries: List[int] = []
        catch_all = False
        for h in stmt.handlers:
            handler_entries.append(self._seq(h.body, after_f, inner))
            catch_all = catch_all or _is_catch_all(h)

        # Exceptions in the body dispatch to every handler — and
        # escape too, unless some handler is catch-all.
        if stmt.handlers:
            dispatch = cfg._new(N_STMT, stmt)
            for he in handler_entries:
                cfg.edge(dispatch, he)
            if not catch_all:
                cfg.edge(dispatch, inner.exc_to, EDGE_EXC)
            body_exc = dispatch
        else:
            body_exc = inner.exc_to

        body_ctx = inner.clone(exc_to=body_exc)
        # else: runs after the body completes; its exceptions skip the
        # handlers.
        else_entry = (
            self._seq(stmt.orelse, after_f, inner)
            if stmt.orelse else after_f
        )
        return self._seq(stmt.body, else_entry, body_ctx)


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef."""
    return _Builder(fn).build()


def reachable_between(
    cfg: CFG,
    start: int,
    stop_nodes,
    include_exc: bool = True,
):
    """Node ids reachable from ``start`` (exclusive) without passing
    *through* any node in ``stop_nodes`` (stop nodes themselves are
    not expanded, but ARE yielded when first reached — the caller
    decides whether a stop node also counts as inside the window).
    Used by the donation-window rule."""
    seen = set()
    work = [
        b for b, kind in cfg.succs[start]
        if include_exc or kind != EDGE_EXC
    ]
    while work:
        n = work.pop()
        if n in seen:
            continue
        seen.add(n)
        if n in stop_nodes:
            continue
        for b, kind in cfg.succs[n]:
            if include_exc or kind != EDGE_EXC:
                work.append(b)
    return seen
