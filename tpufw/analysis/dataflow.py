"""jit-boundary dataflow substrate for tpulint's TPU006-TPU008 rules.

PR 3's ``callgraph`` answers "which functions run under trace". The
rules added here need more: for every ``jax.jit``/``pjit`` *site* —
decorator or call form — which signature slots are donated or static,
where the resulting compiled callable is invoked, and what dtypes flow
through the traced body. This module resolves all three, statically
and conservatively:

- :class:`JitSite`: one jit wrapping, with parsed
  ``donate_argnums``/``donate_argnames``/``static_argnums``/
  ``static_argnames`` (literal specs only; a dynamic spec sets the
  ``*_unparsed`` flag and downstream rules stay silent — false
  negatives over false positives, same bias as ``callgraph``).
- :func:`find_jit_sites` + :func:`find_call_sites`: sites and the call
  expressions that invoke them, found through the binding idioms this
  tree actually uses (``@jax.jit``, ``@partial(jax.jit, ...)``,
  ``step = jax.jit(f, ...)``, ``self._step = jax.jit(...)``).
- :class:`DtypeEnv`: a tiny abstract interpreter over one function
  body with the lattice ``bf16 / fp16 / fp32 / int / int8 / bool /
  weak-float / weak-int / unknown``. Only *strong* evidence (an
  ``astype``, a ``dtype=`` kwarg, a dtype-defaulting constructor)
  produces a non-unknown value; joins with ``unknown`` stay unknown,
  so the dtype rules only ever fire on locally-proven facts.

Everything is stdlib ``ast`` — the analysis package must keep running
in the bare container and in CI with no installs.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import SourceFile

# Parameter names that, by this repo's conventions, carry large device
# arrays: model/optimizer state, KV caches and their leaf tuples, page
# tables, gradient/moment trees. Shape information is not available
# statically, so names are the heuristic — matching callgraph's bias,
# a miss is a false negative, never a false positive.
LARGE_ARRAY_RE = re.compile(
    r"(^|_)(params?|state|opt_state|cache|kv|leaves|grads?|moments?"
    r"|pool|tables?|buffers?|weights?|carry)(_|$)|leaves$"
)

# Call names that pin a varying host value onto a bounded ladder of
# compiled programs (serve's ``_pow2_ceil`` chunk/cache ladders, batch
# bucketing). A value routed through one of these is not churn.
PIN_CALL_RE = re.compile(
    r"pow2|pow_?two|bucket|ladder|round_up|next_power|align|pad_to"
)

_JITTERS = {"jit", "pjit"}


def is_large_param(name: str) -> bool:
    return bool(LARGE_ARRAY_RE.search(name))


def _int_elements(node: ast.AST) -> Optional[Set[int]]:
    """Literal int / tuple-list of ints, else None (unparsable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for el in node.elts:
            if not (
                isinstance(el, ast.Constant) and isinstance(el.value, int)
            ):
                return None
            out.add(el.value)
        return out
    return None


def _str_elements(node: ast.AST) -> Optional[Set[str]]:
    """Literal str / tuple-list of strs, else None (unparsable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in node.elts:
            if not (
                isinstance(el, ast.Constant) and isinstance(el.value, str)
            ):
                return None
            out.add(el.value)
        return out
    return None


class JitSite:
    """One jax.jit/pjit wrapping and its parsed signature policy."""

    def __init__(
        self,
        file: SourceFile,
        node: ast.AST,
        fn: Optional[cg.FunctionInfo],
        how: str,
    ):
        self.file = file
        self.module = cg.module_name(file.relpath)
        self.node = node  # the jit call / decorator, for location
        self.fn = fn  # traced function, when resolvable
        self.lam: Optional[ast.Lambda] = None  # inline lambda form
        self.how = how  # "@jit" | "jit()" | "@partial(jit)"
        self.bound_name: Optional[str] = None  # step = jax.jit(f)
        self.bound_attr: Optional[str] = None  # self._step = jax.jit(f)
        self.donate_argnums: Set[int] = set()
        self.donate_argnames: Set[str] = set()
        self.static_argnums: Set[int] = set()
        self.static_argnames: Set[str] = set()
        self.donate_unparsed = False
        self.static_unparsed = False
        # jit(partial(f, *bound, kw=...)): params consumed by the
        # partial are not jit arguments — positional indices shift and
        # bound keywords can be neither donated nor churned.
        self.partial_nargs = 0
        self.partial_kwargs: Set[str] = set()

    # ------------------------------------------------------- keywords

    def absorb_keywords(self, keywords: Sequence[ast.keyword]) -> None:
        for kw in keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                vals = (
                    _int_elements(kw.value)
                    if kw.arg == "donate_argnums"
                    else _str_elements(kw.value)
                )
                if vals is None:
                    self.donate_unparsed = True
                elif kw.arg == "donate_argnums":
                    self.donate_argnums |= vals  # type: ignore[arg-type]
                else:
                    self.donate_argnames |= vals  # type: ignore[arg-type]
            elif kw.arg in ("static_argnums", "static_argnames"):
                vals = (
                    _int_elements(kw.value)
                    if kw.arg == "static_argnums"
                    else _str_elements(kw.value)
                )
                if vals is None:
                    self.static_unparsed = True
                elif kw.arg == "static_argnums":
                    self.static_argnums |= vals  # type: ignore[arg-type]
                else:
                    self.static_argnames |= vals  # type: ignore[arg-type]

    # ------------------------------------------------------ signature

    def positional_params(self) -> List[str]:
        """Names of the jit-visible positional parameters, in argnums
        order: the traced function's positional params minus anything
        consumed by a wrapping ``partial`` (kw-only params are
        addressable by name only)."""
        node = self.fn.node if self.fn is not None else self.lam
        if node is None:
            return []
        a = node.args
        out = [p.arg for p in a.posonlyargs + a.args]
        out = out[self.partial_nargs:]
        return [p for p in out if p not in self.partial_kwargs]

    def kwonly_params(self) -> List[str]:
        node = self.fn.node if self.fn is not None else self.lam
        if node is None:
            return []
        return [
            p.arg
            for p in node.args.kwonlyargs
            if p.arg not in self.partial_kwargs
        ]

    def is_donated(self, param: str) -> bool:
        if param in self.donate_argnames:
            return True
        pos = self.positional_params()
        return param in pos and pos.index(param) in self.donate_argnums

    def is_static(self, param: str) -> bool:
        if param in self.static_argnames:
            return True
        pos = self.positional_params()
        return param in pos and pos.index(param) in self.static_argnums

    def display_name(self) -> str:
        if self.fn is not None:
            return self.fn.qname
        if self.bound_attr is not None:
            return f"self.{self.bound_attr}"
        return self.bound_name or "<lambda>"

    def __repr__(self) -> str:  # debugging aid only
        return f"<JitSite {self.module}:{self.display_name()} {self.how}>"


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """``node`` as a jax.jit/pjit Call, unwrapping nothing."""
    if isinstance(node, ast.Call) and cg.call_name(node) in _JITTERS:
        return node
    return None


def _unwrap_partials(node: ast.AST) -> Tuple[int, Set[str]]:
    """(positional count, keyword names) consumed by nested
    ``partial(...)`` wrappers around a traced function."""
    nargs = 0
    kwargs: Set[str] = set()
    while (
        isinstance(node, ast.Call)
        and cg.call_name(node) == "partial"
        and node.args
    ):
        nargs += len(node.args) - 1
        kwargs |= {kw.arg for kw in node.keywords if kw.arg}
        node = node.args[0]
    return nargs, kwargs


def find_jit_sites(
    index: cg.ModuleIndex, files: Sequence[SourceFile]
) -> List[JitSite]:
    """Every jit/pjit wrapping in ``files``, with parsed policy and
    (for the call form) the name/attribute the callable is bound to."""
    sites: List[JitSite] = []
    seen: Set[int] = set()

    def add(site: JitSite) -> None:
        if id(site.node) not in seen:
            seen.add(id(site.node))
            sites.append(site)

    for f in files:
        if f.tree is None:
            continue
        mod = cg.module_name(f.relpath)
        for node in ast.walk(f.tree):
            # ---- decorator forms -------------------------------------
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = _fn_info(index, mod, node)
                for dec in node.decorator_list:
                    site: Optional[JitSite] = None
                    if isinstance(dec, (ast.Name, ast.Attribute)):
                        chain = cg.attr_chain(dec)
                        if chain and chain[-1] in _JITTERS:
                            site = JitSite(f, dec, fi, f"@{chain[-1]}")
                    elif isinstance(dec, ast.Call):
                        nm = cg.call_name(dec)
                        if nm in _JITTERS:
                            site = JitSite(f, dec, fi, f"@{nm}(...)")
                            site.absorb_keywords(dec.keywords)
                        elif nm == "partial" and dec.args:
                            chain = cg.attr_chain(dec.args[0])
                            if chain and chain[-1] in _JITTERS:
                                site = JitSite(
                                    f, dec, fi, f"@partial({chain[-1]})"
                                )
                                site.absorb_keywords(dec.keywords)
                    if site is not None:
                        site.bound_name = node.name
                        add(site)
            # ---- call form, possibly bound ---------------------------
            call = _jit_call(node)
            if call is None:
                continue
            arg = cg._first_traced_arg(call)
            if arg is None:
                continue
            partial_nargs, partial_kwargs = _unwrap_partials(arg)
            arg = cg._unwrap_partial(arg)
            fi = None
            lam = None
            if isinstance(arg, ast.Lambda):
                lam = arg
            elif isinstance(arg, (ast.Name, ast.Attribute)):
                if isinstance(arg, ast.Name):
                    # `step = partial(f, ...); jax.jit(step)`: the
                    # binding carries the partial's consumed params.
                    pc = index.partial_bindings.get((mod, arg.id))
                    if pc is not None:
                        n, kws = _unwrap_partials(pc)
                        partial_nargs += n
                        partial_kwargs |= kws
                fake = ast.Call(func=arg, args=[], keywords=[])
                ast.copy_location(fake, arg)
                fi = index.resolve_call(fake, mod)
                if fi is None and isinstance(arg, ast.Name):
                    fi = index.resolve_partial_binding(arg.id, mod)
            site = JitSite(f, call, fi, f"{cg.call_name(call)}()")
            site.lam = lam
            site.partial_nargs = partial_nargs
            site.partial_kwargs = partial_kwargs
            site.absorb_keywords(call.keywords)
            add(site)
        # Bindings: step = jax.jit(f, ...) / self._step = jax.jit(...).
        for stmt in ast.walk(f.tree):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            call = _jit_call(stmt.value)
            if call is None:
                continue
            target = stmt.targets[0]
            for site in sites:
                if site.node is call:
                    if isinstance(target, ast.Name):
                        site.bound_name = target.id
                    elif isinstance(target, ast.Attribute) and isinstance(
                        target.value, ast.Name
                    ):
                        site.bound_attr = target.attr
    return sites


def _fn_info(
    index: cg.ModuleIndex, mod: str, node: ast.AST
) -> Optional[cg.FunctionInfo]:
    for fi in index.by_simple_name.get(getattr(node, "name", ""), []):
        if fi.node is node:
            return fi
    return None


class CallSite:
    """One invocation of a jitted callable, with argument binding."""

    def __init__(
        self,
        site: JitSite,
        file: SourceFile,
        call: ast.Call,
        owner: Optional[cg.FunctionInfo],
    ):
        self.site = site
        self.file = file
        self.call = call
        self.owner = owner  # enclosing function, when known

    def bound_args(self) -> List[Tuple[str, ast.AST]]:
        """(param_name, arg_expr) pairs, positionally matched against
        the traced signature; keywords by name. Starred/dynamic forms
        are skipped."""
        pos = self.site.positional_params()
        out: List[Tuple[str, ast.AST]] = []
        for i, a in enumerate(self.call.args):
            if isinstance(a, ast.Starred):
                break
            if i < len(pos):
                out.append((pos[i], a))
        for kw in self.call.keywords:
            if kw.arg is not None:
                out.append((kw.arg, kw.value))
        return out


def find_call_sites(
    index: cg.ModuleIndex,
    files: Sequence[SourceFile],
    sites: Sequence[JitSite],
) -> Dict[int, List[CallSite]]:
    """id(site) -> invocations. Decorated functions are matched through
    ``resolve_call`` (cross-file, import-aware); ``name = jax.jit(f)``
    bindings by name within the defining file; ``self._x = jax.jit(f)``
    by ``self._x(...)`` / ``obj._x(...)`` attribute calls in the same
    file. The jit wrapping itself is never its own call site."""
    out: Dict[int, List[CallSite]] = {id(s): [] for s in sites}
    by_fn_node: Dict[int, JitSite] = {}
    for s in sites:
        if s.fn is not None and s.how.startswith("@"):
            by_fn_node[id(s.fn.node)] = s
    call_bound: Dict[Tuple[str, str], List[JitSite]] = {}
    attr_bound: Dict[Tuple[str, str], List[JitSite]] = {}
    for s in sites:
        if s.how.startswith("@"):
            continue
        if s.bound_name:
            call_bound.setdefault(
                (s.file.relpath, s.bound_name), []
            ).append(s)
        if s.bound_attr:
            attr_bound.setdefault(
                (s.file.relpath, s.bound_attr), []
            ).append(s)
    # Also: plain `@jit`-less functions called THROUGH a jit call form,
    # e.g. step = jax.jit(train_step); later step(...) — covered by
    # bound_name above. Direct calls to the decorated name:
    for f in files:
        if f.tree is None:
            continue
        mod = cg.module_name(f.relpath)
        owner_of = _owner_map(index, f)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            owner = owner_of.get(id(node))
            # Decorated functions, resolved cross-file.
            fi = index.resolve_call(
                node, mod, within=owner.qname if owner else None
            )
            if fi is not None and id(fi.node) in by_fn_node:
                s = by_fn_node[id(fi.node)]
                out[id(s)].append(CallSite(s, f, node, owner))
                continue
            # name(...) / self.attr(...) bindings (same file only).
            func = node.func
            if isinstance(func, ast.Name):
                for s in call_bound.get((f.relpath, func.id), []):
                    out[id(s)].append(CallSite(s, f, node, owner))
            elif isinstance(func, ast.Attribute):
                for s in attr_bound.get((f.relpath, func.attr), []):
                    out[id(s)].append(CallSite(s, f, node, owner))
    return out


def _owner_map(
    index: cg.ModuleIndex, f: SourceFile
) -> Dict[int, cg.FunctionInfo]:
    """id(call node) -> innermost enclosing FunctionInfo."""
    out: Dict[int, cg.FunctionInfo] = {}
    for fi in index.functions:
        if fi.file is not f:
            continue
        for call in cg.iter_calls(fi.node):
            out[id(call)] = fi  # later (inner) definitions overwrite
    return out


# ---------------------------------------------------------------- dtypes

BF16 = "bf16"
FP16 = "fp16"
FP32 = "fp32"
INT = "int"
INT8 = "int8"
BOOL = "bool"
WEAK_FLOAT = "weak-float"  # Python float literal: inherits neighbor dtype
WEAK_INT = "weak-int"
UNKNOWN = "unknown"

_DTYPE_NAMES = {
    "bfloat16": BF16,
    "bf16": BF16,
    "float16": FP16,
    "half": FP16,
    "float32": FP32,
    "float_": FP32,
    "float64": FP32,  # CPU-double; still a "wide float" for drift purposes
    "int8": INT8,
    "int16": INT,
    "int32": INT,
    "int64": INT,
    "uint8": INT8,
    "uint32": INT,
    "bool_": BOOL,
    "bool": BOOL,
}

_FLOAT_STRONG = {BF16, FP16, FP32}

# jnp constructors whose no-dtype default is fp32 (float family).
FLOAT_DEFAULT_CTORS = {"zeros", "ones", "empty", "full", "linspace"}
INT_DEFAULT_CTORS = {"arange"}
_LIKE_CTORS = {"zeros_like", "ones_like", "empty_like", "full_like"}

_JNP_ALIASES = {"jnp", "np", "numpy", "onp"}


def dtype_of_node(node: ast.AST) -> str:
    """Dtype named by an expression like ``jnp.bfloat16`` / the string
    literal "bfloat16" — UNKNOWN when it isn't a recognizable name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_NAMES.get(node.value, UNKNOWN)
    chain = cg.attr_chain(node)
    if chain:
        return _DTYPE_NAMES.get(chain[-1], UNKNOWN)
    return UNKNOWN


def _ctor_dtype_arg(call: ast.Call) -> Optional[ast.AST]:
    """The dtype expression of a jnp constructor call, positional or
    keyword, or None when the call leaves the dtype to the default."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    name = cg.call_name(call)
    # zeros(shape, dtype) / ones / empty; full(shape, fill, dtype);
    # arange(...,[dtype]) is keyword-only in practice here.
    if name in ("zeros", "ones", "empty") and len(call.args) >= 2:
        return call.args[1]
    if name == "full" and len(call.args) >= 3:
        return call.args[2]
    return None


def join(a: str, b: str) -> str:
    """Lattice join mirroring jax type promotion closely enough for
    drift detection: weak values inherit the strong side, mixed strong
    floats widen to the widest, anything touching UNKNOWN is UNKNOWN."""
    if a == b:
        return a
    if a == UNKNOWN or b == UNKNOWN:
        return UNKNOWN
    for weak, strongs in (
        (WEAK_FLOAT, _FLOAT_STRONG | {WEAK_INT}),
        (WEAK_INT, _FLOAT_STRONG | {INT, INT8, WEAK_FLOAT}),
    ):
        if a == weak and b in strongs:
            return b if b != WEAK_INT else WEAK_FLOAT
        if b == weak and a in strongs:
            return a if a != WEAK_INT else WEAK_FLOAT
    if a in _FLOAT_STRONG and b in _FLOAT_STRONG:
        return FP32 if FP32 in (a, b) else FP16
    if a in (INT, INT8) and b in (INT, INT8):
        return INT
    if a in _FLOAT_STRONG and b in (INT, INT8, BOOL):
        return a
    if b in _FLOAT_STRONG and a in (INT, INT8, BOOL):
        return b
    return UNKNOWN


class DtypeEnv:
    """One-pass abstract interpretation of a function body: a map from
    local names to lattice dtypes, built in statement order (loop
    bodies are visited once — enough for drift detection, which only
    acts on stable local evidence)."""

    # jnp reductions/elementwise that preserve their argument's dtype.
    _PRESERVING = {
        "sum", "mean", "max", "min", "abs", "exp", "log", "sqrt",
        "square", "tanh", "reshape", "transpose", "swapaxes",
        "broadcast_to", "where", "concatenate", "stack", "pad",
        "dynamic_update_slice", "dynamic_slice", "take_along_axis",
        "maximum", "minimum", "negative", "clip", "roll",
    }

    def __init__(self, fn: cg.FuncNode):
        self.env: Dict[str, str] = {}
        body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
        # Parameter annotations are the only pre-body evidence.
        for p in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            if p.annotation is not None:
                d = dtype_of_node(p.annotation)
                if d != UNKNOWN:
                    self.env[p.arg] = d
        for stmt in body:
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate scope; analyzed on its own
        if isinstance(stmt, ast.Assign):
            d = self.infer(stmt.value)
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    prev = self.env.get(t.id)
                    # A re-bind to a different proven dtype makes the
                    # name unstable — drop to UNKNOWN rather than pick.
                    if prev is not None and prev != d:
                        self.env[t.id] = UNKNOWN
                    else:
                        self.env[t.id] = d
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            d = dtype_of_node(stmt.annotation)
            if d == UNKNOWN and stmt.value is not None:
                d = self.infer(stmt.value)
            self.env[stmt.target.id] = d
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list):
                for s in sub:
                    if isinstance(s, ast.stmt):
                        self._visit_stmt(s)
        for h in getattr(stmt, "handlers", []) or []:
            for s in h.body:
                self._visit_stmt(s)

    # ---------------------------------------------------------- infer

    def infer(self, node: ast.AST) -> str:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return BOOL
            if isinstance(node.value, int):
                return WEAK_INT
            if isinstance(node.value, float):
                return WEAK_FLOAT
            return UNKNOWN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Subscript):
            return self.infer(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.infer(node.operand)
        if isinstance(node, ast.Compare):
            return BOOL
        if isinstance(node, ast.BinOp):
            ld, rd = self.infer(node.left), self.infer(node.right)
            if isinstance(node.op, ast.Div) and ld in (
                INT, WEAK_INT
            ) and rd in (INT, WEAK_INT):
                return FP32  # true division of ints promotes to f32
            return join(ld, rd)
        if isinstance(node, ast.IfExp):
            return join(self.infer(node.body), self.infer(node.orelse))
        if isinstance(node, ast.Call):
            return self._infer_call(node)
        if isinstance(node, ast.Attribute):
            # x.T / x.real keep dtype; a bare dtype name IS a dtype.
            d = dtype_of_node(node)
            if d != UNKNOWN:
                return d
            if node.attr in ("T", "mT", "real"):
                return self.infer(node.value)
            return UNKNOWN
        return UNKNOWN

    def _infer_call(self, call: ast.Call) -> str:
        name = cg.call_name(call)
        chain = cg.attr_chain(call.func)
        if name == "astype" and call.args:
            return dtype_of_node(call.args[0])
        if name is None:
            return UNKNOWN
        # jnp.float32(x)-style casts and dtype constructors.
        if name in _DTYPE_NAMES:
            return _DTYPE_NAMES[name]
        is_jnp = bool(chain) and len(chain) >= 2 and chain[0] in _JNP_ALIASES
        if is_jnp or len(chain or []) == 1:
            if name in FLOAT_DEFAULT_CTORS:
                dt = _ctor_dtype_arg(call)
                if dt is None:
                    if name == "full" and len(call.args) >= 2:
                        return self.infer(call.args[1])
                    return FP32
                return dtype_of_node(dt)
            if name in INT_DEFAULT_CTORS:
                dt = _ctor_dtype_arg(call)
                return INT if dt is None else dtype_of_node(dt)
            if name in _LIKE_CTORS:
                dt = _ctor_dtype_arg(call)
                if dt is not None:
                    return dtype_of_node(dt)
                return self.infer(call.args[0]) if call.args else UNKNOWN
        if name in self._PRESERVING:
            # where(c, a, b): dtype joins the branches, not the mask.
            args = call.args[1:] if name == "where" else call.args
            d = UNKNOWN
            for i, a in enumerate(args):
                ad = self.infer(a)
                d = ad if i == 0 else join(d, ad)
            # Attribute form x.sum(): dtype of the receiver.
            if not args and isinstance(call.func, ast.Attribute):
                return self.infer(call.func.value)
            return d
        if name in ("einsum", "dot", "matmul", "dot_general", "tensordot"):
            for kw in call.keywords:
                if kw.arg == "preferred_element_type":
                    return dtype_of_node(kw.value)
            d = UNKNOWN
            operands = [
                a for a in call.args
                if not (
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                )
            ]
            for i, a in enumerate(operands):
                ad = self.infer(a)
                d = ad if i == 0 else join(d, ad)
            return d
        return UNKNOWN


def iter_binops(fn: cg.FuncNode) -> Iterator[ast.BinOp]:
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.BinOp):
                yield node


# ------------------------------------------------------- varying values

class VaryingEnv:
    """Host-side per-function classification of names whose VALUE or
    whose SHAPE varies across iterations/calls — the trace-cache keys
    TPU007 cares about. A name is value-varying when it is a loop
    target or assigned from ``len(...)``/another varying name;
    shape-varying when assigned from a size-constructing call or a
    slice whose bounds are value-varying. Routing through a
    ``PIN_CALL_RE`` call (pow2 ladders, bucketing) clears both."""

    _SIZED_CTORS = {
        "zeros", "ones", "full", "empty", "arange", "tile", "repeat",
        "split",
    }

    def __init__(self, fn: cg.FuncNode):
        self.value_varying: Set[str] = set()
        self.shape_varying: Set[str] = set()
        body = fn.body if isinstance(fn.body, list) else []
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.For):
                    self.value_varying |= _target_names(node.target)
                elif isinstance(node, ast.While):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.AugAssign) and isinstance(
                            sub.target, ast.Name
                        ):
                            self.value_varying.add(sub.target.id)
        # Forward propagation, two passes to catch simple chains.
        for _ in range(2):
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Assign):
                        continue
                    if self.expr_value_varying(node.value):
                        for t in node.targets:
                            self.value_varying |= _target_names(t)
                    if self.expr_shape_varying(node.value):
                        for t in node.targets:
                            self.shape_varying |= _target_names(t)

    def expr_value_varying(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                nm = cg.call_name(sub)
                if nm and PIN_CALL_RE.search(nm):
                    return False  # pinned — stop looking deeper
            if isinstance(sub, ast.Name) and sub.id in self.value_varying:
                return True
        return False

    def expr_shape_varying(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.shape_varying
        if isinstance(node, ast.Subscript):
            sl = node.slice
            slices = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
            for s in slices:
                if isinstance(s, ast.Slice):
                    for bound in (s.lower, s.upper):
                        if bound is not None and self.expr_value_varying(
                            bound
                        ):
                            return True
            return self.expr_shape_varying(node.value)
        if isinstance(node, ast.Call):
            nm = cg.call_name(node)
            if nm and PIN_CALL_RE.search(nm):
                return False
            if nm in self._SIZED_CTORS:
                for a in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if self.expr_value_varying(a):
                        return True
            # asarray(x)/astype(x)-style wrappers keep x's shape.
            if nm in ("asarray", "array", "astype") and node.args:
                return self.expr_shape_varying(node.args[0])
        if isinstance(node, ast.BinOp):
            return self.expr_shape_varying(
                node.left
            ) or self.expr_shape_varying(node.right)
        return False


def _target_names(t: ast.AST) -> Set[str]:
    names: Set[str] = set()
    if isinstance(t, ast.Name):
        names.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            names |= _target_names(e)
    elif isinstance(t, ast.Starred):
        names |= _target_names(t.value)
    return names
