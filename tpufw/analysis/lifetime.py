"""tpulint layer 5 — resource-lifetime & concurrency-liveness rules
(TPU019-TPU022).

The most expensive bugs this repo has shipped were lifetime bugs found
only in review: the decode slot leaked for submit-time-done bundles,
and the router inflight credit leaked when a queue wait timed out.
Every serving subsystem re-implements the same acquire/release
protocol (allocator pages, pool slots, inflight credits, chunked-
prefill tickets, series-store file handles); this layer lets the code
*declare* the protocol once and then proves, over a real control-flow
graph (:mod:`tpufw.analysis.cfg`), that no path — raise, early
return, ``except``-swallowed — exits still holding something.

Marker grammar (``# resource:`` comments)::

    # resource: acquires <kind>      # trailing -> this statement acquires
    # resource: releases <kind>      # trailing -> this statement releases
    # resource: transfers <kind>     # trailing -> ownership handed off here
    # resource: counter <kind>       # trailing on a gauge's init assignment
    # resource: donates <name>[, ..] # trailing on a donated jit dispatch

A marker *alone on its line inside a function* is that function's
**contract** instead of a statement event: callers of a function whose
contract says ``acquires pages`` pick up a pages obligation at the
call site, ``releases``/``transfers`` contracts discharge one — the
one-hop callgraph follow that lets ``export_slot -> wire ->
splice_slot`` check end to end without whole-program analysis.
Contract calls are resolved by the callee's *simple name* (the
terminal attribute), so ``self.pool.allocator.release(ids)`` matches
``PageAllocator.release``; for ``__init__`` contracts the class name
is registered too (``SeriesStore(path)`` acquires the file handle).

TPU019  acquire/release pairing. Path-sensitive obligation dataflow:
        an acquire adds an obligation (on the *normal* out-edge only —
        a raising acquire acquired nothing), releases discharge on
        every edge, statement-level transfers discharge on every edge,
        contract transfers only on the normal edge (a raising callee
        transferred nothing). ``with``-managed acquisitions are
        auto-discharged; ``try/finally`` releases cover every exit by
        CFG construction. An obligation bound to an assignment target
        is value-filtered at ``if x is None`` / ``if not x`` branches
        (the alloc-returns-None idiom), and a function whose own
        contract acquires a kind may *return* holding it (that IS the
        handoff to the caller) — but may not leak it on a raise.

TPU020  condition-variable discipline, on classes owning a
        ``threading.Condition``: a ``cv.wait()`` with no enclosing
        ``while`` (spurious wakeups / missed re-checks), a
        ``notify``/``notify_all`` outside ``with cv`` (or the lock the
        Condition wraps; ``*_locked``-suffixed methods are exempt by
        house convention — their callers hold the monitor), and a
        method that writes a predicate attribute (one read by a
        wait-loop's test) under the lock with no reachable notify.

TPU021  counter balance, for gauges marked ``# resource: counter``:
        a method containing both an increment and a decrement must
        have the decrement post-dominate the increment (every path
        from ``+=`` to exit passes ``-=``, exception edges included —
        the try/finally shape); a counter with increments but no
        decrement anywhere in its class can only saturate.

TPU022  single-flight donation windows: after a statement marked
        ``# resource: donates a, b`` dispatches a jit that donates
        those buffers, reading ``a`` or ``b`` before a
        ``block_until_ready`` or a rebinding of the name is a read of
        memory the accelerator may already have overwritten.

Known limits (see docs/ANALYSIS.md): contract resolution is by simple
name (rename or suppress on collision); may-raise is syntactic
(calls/asserts, not subscripts); obligations are per-kind sets, not
counts; rebinding an obligated name is not itself a leak.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from . import cfg as cfgmod
from .core import Checker, Finding, Project, SourceFile

_RES_RE = re.compile(
    r"#\s*resource:\s*(acquires|releases|transfers|counter|donates)"
    r"\s+([A-Za-z0-9_.,\- ]+?)\s*(?:—.*)?$"
)

_SITE_VERBS = ("acquires", "releases", "transfers")


# ------------------------------------------------------------ parsing


class _Marker:
    __slots__ = ("line", "verb", "arg", "standalone")

    def __init__(self, line: int, verb: str, arg: str, standalone: bool):
        self.line = line
        self.verb = verb
        self.arg = arg
        self.standalone = standalone


def _scan_markers(f: SourceFile) -> List[_Marker]:
    out: List[_Marker] = []
    for i, text in enumerate(f.lines, start=1):
        m = _RES_RE.search(text)
        if not m:
            continue
        before = text[: m.start()].strip()
        standalone = before == "" or before.endswith("#")
        # ``x = 1  # noqa  # resource: ...`` is trailing; a pure
        # comment line (possibly after other comments) is standalone.
        if before.startswith("#"):
            standalone = True
        out.append(
            _Marker(i, m.group(1), m.group(2).strip(), standalone)
        )
    return out


class _FnInfo:
    """One function: node, qualified name, class context, span."""

    __slots__ = ("node", "qname", "cls", "name")

    def __init__(self, node, qname, cls):
        self.node = node
        self.qname = qname
        self.cls = cls  # ClassDef or None (immediate owner only)
        self.name = node.name


def _walk_functions(f: SourceFile) -> List[_FnInfo]:
    out: List[_FnInfo] = []
    if f.tree is None:
        return out

    def walk(node: ast.AST, prefix: str, cls) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out.append(_FnInfo(child, q, cls))
                walk(child, q, None)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                walk(child, q, child)
            else:
                walk(child, prefix, cls)

    walk(f.tree, "", None)
    return out


def _enclosing(fns: Sequence[_FnInfo], line: int) -> Optional[_FnInfo]:
    best = None
    for fi in fns:
        lo = fi.node.lineno
        hi = fi.node.end_lineno or lo
        if lo <= line <= hi and (best is None or lo > best.node.lineno):
            best = fi
    return best


def _innermost_stmt(fn: ast.AST, line: int) -> Optional[ast.stmt]:
    best: Optional[ast.stmt] = None
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.stmt) or sub is fn:
            continue
        lo = getattr(sub, "lineno", None)
        hi = getattr(sub, "end_lineno", None)
        if lo is None or hi is None or not (lo <= line <= hi):
            continue
        if best is None or lo > best.lineno or (
            lo == best.lineno and hi <= (best.end_lineno or hi)
        ):
            best = sub
    return best


def _terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for an exact ``self.x`` attribute access."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


_COMPOUND = (
    ast.If, ast.While, ast.For, ast.AsyncFor, ast.With, ast.AsyncWith,
    ast.Try, ast.Match,
)


def _header_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions a compound statement's *header* evaluates (the
    part its CFG node represents — body calls belong to body nodes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    return []


def _calls_in_stmt(stmt: ast.stmt) -> List[ast.Call]:
    """Calls this statement's CFG node evaluates."""
    roots: List[ast.AST]
    if isinstance(stmt, _COMPOUND):
        roots = _header_exprs(stmt)
    else:
        roots = [stmt]
    out = []
    for r in roots:
        for sub in ast.walk(r):
            if isinstance(sub, ast.Call):
                out.append(sub)
    return out


def _assign_binder(stmt: ast.stmt) -> Optional[str]:
    """Single-Name assignment target, for value-filtered obligations."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        t = stmt.targets[0]
        if isinstance(t, ast.Name):
            return t.id
    if isinstance(stmt, ast.AnnAssign) and isinstance(
        stmt.target, ast.Name
    ):
        return stmt.target.id
    return None


def _branch_filter(
    test: ast.AST, binder: str
) -> Tuple[bool, bool]:
    """(keep_on_true, keep_on_false) for an obligation bound to
    ``binder`` at a branch on ``test``. Conservative default: keep."""

    def is_binder(n: ast.AST) -> bool:
        return isinstance(n, ast.Name) and n.id == binder

    def none_test(n: ast.AST) -> Optional[bool]:
        """True => 'binder is None' shape, False => 'is not None'."""
        if (
            isinstance(n, ast.Compare)
            and len(n.ops) == 1
            and is_binder(n.left)
            and isinstance(n.comparators[0], ast.Constant)
            and n.comparators[0].value is None
        ):
            if isinstance(n.ops[0], ast.Is):
                return True
            if isinstance(n.ops[0], ast.IsNot):
                return False
        return None

    if is_binder(test):
        return True, False  # truthy -> held; falsy -> never acquired
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        if is_binder(test.operand):
            return False, True
        nt = none_test(test.operand)
        if nt is True:
            return True, False
    nt = none_test(test)
    if nt is True:
        return False, True
    if nt is False:
        return True, False
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        # 'if b is None and <...>': on the true edge every conjunct
        # holds, so the binder IS None there. The false edge learns
        # nothing (any conjunct may have failed).
        for v in test.values:
            if none_test(v) is True or (
                isinstance(v, ast.UnaryOp)
                and isinstance(v.op, ast.Not)
                and is_binder(v.operand)
            ):
                return False, True
    return True, True


# -------------------------------------------------------- event model


class _Events:
    """Resource events one CFG node performs."""

    __slots__ = (
        "acquires",  # [(kind, binder)]
        "releases",  # {kind} — discharge on every out-edge
        "transfers_all",  # {kind} — statement-level: every edge
        "transfers_ok",  # {kind} — contract call: normal edge only
        "test_acquires",  # [(kind, on_true: bool)] — If-test acquire
    )

    def __init__(self):
        self.acquires = []
        self.releases = set()
        self.transfers_all = set()
        self.transfers_ok = set()
        self.test_acquires = []

    def empty(self) -> bool:
        return not (
            self.acquires or self.releases or self.transfers_all
            or self.transfers_ok or self.test_acquires
        )


def _call_in(tree: ast.AST, call: ast.Call) -> bool:
    return any(sub is call for sub in ast.walk(tree))


def _collect_events(
    fn: _FnInfo,
    site_by_line: Dict[int, List[Tuple[str, str]]],
    contracts: Dict[str, Set[Tuple[str, str]]],
    by_class: Optional[Dict[Tuple[str, str], Set[Tuple[str, str]]]] = None,
    class_methods: Optional[Dict[str, Set[str]]] = None,
) -> Dict[int, _Events]:
    """line-of-stmt -> events, keyed by the statement's lineno (CFG
    nodes for the same stmt share events; finally copies inherit)."""
    out: Dict[int, _Events] = {}

    def ev(stmt: ast.stmt) -> _Events:
        key = stmt.lineno
        if key not in out:
            out[key] = _Events()
        return out[key]

    # Site markers -> innermost enclosing statement.
    for line, pairs in site_by_line.items():
        stmt = _innermost_stmt(fn.node, line)
        if stmt is None:
            continue
        e = ev(stmt)
        for verb, kind in pairs:
            if verb == "acquires":
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    continue  # with-managed: discharged at exit
                e.acquires.append((kind, _assign_binder(stmt)))
            elif verb == "releases":
                e.releases.add(kind)
            elif verb == "transfers":
                e.transfers_all.add(kind)

    # Contract calls.
    for sub in ast.walk(fn.node):
        if not isinstance(sub, ast.stmt) or sub is fn.node:
            continue
        # Skip statements of nested function definitions: they run on
        # the inner function's activation, not this one's.
        calls = _calls_in_stmt(sub)
        if not calls:
            continue
        for call in calls:
            t = _terminal_name(call.func)
            if t is None or t == fn.name:
                continue
            # ``self.X(...)`` where the enclosing class defines X:
            # resolve against THAT method's contract only (possibly
            # none), never a same-named method of another class.
            entry = contracts.get(t, ())
            if (
                class_methods is not None
                and fn.cls is not None
                and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id == "self"
                and t in class_methods.get(fn.cls.name, ())
            ):
                entry = (by_class or {}).get((fn.cls.name, t), set())
            for verb, kind in entry:
                e = ev(sub)
                if verb == "acquires":
                    if isinstance(sub, (ast.With, ast.AsyncWith)):
                        continue  # with-managed acquisition
                    if isinstance(sub, ast.If) and _call_in(
                        sub.test, call
                    ):
                        on_true = not (
                            isinstance(sub.test, ast.UnaryOp)
                            and isinstance(sub.test.op, ast.Not)
                        )
                        e.test_acquires.append((kind, on_true))
                    else:
                        e.acquires.append(
                            (kind, _assign_binder(sub))
                        )
                elif verb == "releases":
                    e.releases.add(kind)
                elif verb == "transfers":
                    e.transfers_ok.add(kind)
    # Nested defs: drop events attached to their statements — walk
    # found them, but they don't execute in this frame.
    nested: List[Tuple[int, int]] = []
    for sub in ast.walk(fn.node):
        if sub is not fn.node and isinstance(
            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            nested.append((sub.lineno, sub.end_lineno or sub.lineno))
    if nested:
        for line in list(out):
            if any(lo < line <= hi for lo, hi in nested):
                del out[line]
    return out


# ------------------------------------------------------------- TPU019


class ResourceLifetimeChecker(Checker):
    rule = "TPU019"
    name = "resource-lifetime"
    severity = "error"
    layer = "lifetime"

    def check(self, project: Project) -> Iterator[Finding]:
        # Pass 1: contracts from standalone markers, tree-wide.  Two
        # registries: a global one keyed by terminal name, and a
        # class-scoped one so ``self.X(...)`` resolves against the
        # enclosing class's own method before any same-named method
        # elsewhere in the tree (a scheduler's ``_admit`` must not
        # inherit the router's ``_admit`` contract).
        contracts: Dict[str, Set[Tuple[str, str]]] = {}
        by_class: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        class_methods: Dict[str, Set[str]] = {}
        per_file: Dict[str, Tuple[List[_FnInfo], List[_Marker]]] = {}
        for f in project.files:
            if f.tree is None:
                continue
            fns = _walk_functions(f)
            markers = _scan_markers(f)
            per_file[f.relpath] = (fns, markers)
            for fi in fns:
                if fi.cls is not None:
                    class_methods.setdefault(fi.cls.name, set()).add(
                        fi.name
                    )
            for m in markers:
                if not m.standalone or m.verb not in _SITE_VERBS:
                    continue
                fi = _enclosing(fns, m.line)
                if fi is None:
                    continue
                names = {fi.name}
                if fi.name == "__init__" and fi.cls is not None:
                    names.add(fi.cls.name)
                for n in names:
                    contracts.setdefault(n, set()).add(
                        (m.verb, m.arg)
                    )
                if fi.cls is not None:
                    by_class.setdefault(
                        (fi.cls.name, fi.name), set()
                    ).add((m.verb, m.arg))

        # Pass 2: per-function obligation dataflow.
        for f in project.files:
            if f.relpath not in per_file:
                continue
            fns, markers = per_file[f.relpath]
            site: Dict[_FnInfo, Dict[int, List[Tuple[str, str]]]] = {}
            own: Dict[_FnInfo, Set[str]] = {}
            for m in markers:
                if m.verb not in _SITE_VERBS:
                    continue
                fi = _enclosing(fns, m.line)
                if fi is None:
                    continue
                if m.standalone:
                    if m.verb == "acquires":
                        own.setdefault(fi, set()).add(m.arg)
                    continue
                site.setdefault(fi, {}).setdefault(m.line, []).append(
                    (m.verb, m.arg)
                )
            for fi in fns:
                yield from self._check_fn(
                    f, fi, site.get(fi, {}), contracts,
                    own.get(fi, set()), by_class, class_methods,
                )

    def _check_fn(
        self,
        f: SourceFile,
        fi: _FnInfo,
        site_by_line: Dict[int, List[Tuple[str, str]]],
        contracts: Dict[str, Set[Tuple[str, str]]],
        own_acquires: Set[str],
        by_class: Dict[Tuple[str, str], Set[Tuple[str, str]]],
        class_methods: Dict[str, Set[str]],
    ) -> Iterator[Finding]:
        events = _collect_events(
            fi, site_by_line, contracts, by_class, class_methods
        )
        if not any(
            e.acquires or e.test_acquires for e in events.values()
        ):
            return
        graph = cfgmod.build_cfg(fi.node)
        # Worklist may-analysis: node -> set of (kind, binder, line).
        state: Dict[int, Set[Tuple[str, Optional[str], int]]] = {
            graph.entry: set()
        }
        work = [graph.entry]
        leaks: Dict[
            Tuple[str, str], Tuple[int, int]
        ] = {}  # (kind, exit-kind) -> (acquire line, exit line)
        while work:
            n = work.pop()
            node = graph.node(n)
            s_in = state.get(n, set())
            e = events.get(node.line) if node.stmt is not None else None
            for succ, ekind in graph.succs[n]:
                s = set(s_in)
                if e is not None:
                    if e.releases or e.transfers_all:
                        gone = e.releases | e.transfers_all
                        s = {o for o in s if o[0] not in gone}
                    if ekind != cfgmod.EDGE_EXC and e.transfers_ok:
                        s = {
                            o for o in s
                            if o[0] not in e.transfers_ok
                        }
                    if ekind != cfgmod.EDGE_EXC:
                        for kind, binder in e.acquires:
                            s.add((kind, binder, node.line))
                        for kind, on_true in e.test_acquires:
                            if (ekind == cfgmod.EDGE_TRUE) == on_true:
                                s.add((kind, None, node.line))
                if (
                    node.stmt is not None
                    and isinstance(node.stmt, (ast.If, ast.While))
                    and ekind in (cfgmod.EDGE_TRUE, cfgmod.EDGE_FALSE)
                ):
                    kept = set()
                    for kind, binder, line in s:
                        if binder is None:
                            kept.add((kind, binder, line))
                            continue
                        kt, kf = _branch_filter(node.stmt.test, binder)
                        if (kt if ekind == cfgmod.EDGE_TRUE else kf):
                            kept.add((kind, binder, line))
                    s = kept
                target = graph.node(succ)
                if target.kind in (
                    cfgmod.N_RETURN_EXIT, cfgmod.N_EXC_EXIT
                ):
                    for kind, binder, line in s:
                        if (
                            target.kind == cfgmod.N_RETURN_EXIT
                            and kind in own_acquires
                        ):
                            continue  # declared handoff to the caller
                        key = (kind, target.kind)
                        exit_line = node.line or line
                        if key not in leaks or leaks[key][1] > exit_line:
                            leaks[key] = (line, exit_line)
                    continue
                if succ not in state:
                    state[succ] = set(s)
                    work.append(succ)
                elif not s <= state[succ]:
                    state[succ] |= s
                    work.append(succ)
        for (kind, exit_kind), (acq_line, exit_line) in sorted(
            leaks.items(), key=lambda kv: kv[1]
        ):
            how = (
                "an exception path"
                if exit_kind == cfgmod.N_EXC_EXIT
                else "a return path"
            )
            anchor = ast.Name(
                id="x", lineno=acq_line, col_offset=0
            )
            yield self.finding(
                f,
                anchor,
                f"{fi.qname}: {kind!r} acquired here can reach "
                f"function exit via {how} (around line {exit_line}) "
                "without a release or ownership transfer — wrap in "
                "try/finally, release in the handler, or mark the "
                "handoff with '# resource: transfers'",
                symbol=f"leak:{fi.qname}:{kind}:{exit_kind}",
            )


# ------------------------------------------------------------- TPU020


_CV_CTORS = {"Condition"}


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _ancestors(node, parents) -> Iterator[ast.AST]:
    cur = parents.get(id(node))
    while cur is not None:
        yield cur
        cur = parents.get(id(cur))


class ConditionDisciplineChecker(Checker):
    rule = "TPU020"
    name = "cv-discipline"
    severity = "error"
    layer = "lifetime"

    def check(self, project: Project) -> Iterator[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(f, node)

    def _check_class(
        self, f: SourceFile, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # Condition attrs + the explicit lock each one wraps (if any).
        cvs: Dict[str, Optional[str]] = {}
        for m in methods:
            for sub in ast.walk(m):
                if not isinstance(sub, ast.Assign):
                    continue
                v = sub.value
                if not (
                    isinstance(v, ast.Call)
                    and _terminal_name(v.func) in _CV_CTORS
                ):
                    continue
                for t in sub.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    lock = (
                        _self_attr(v.args[0]) if v.args else None
                    )
                    cvs[attr] = lock
        if not cvs:
            return

        # Which methods notify which cv (for the one-hop reach check).
        notify_methods: Dict[str, Set[str]] = {}  # cv -> {method}
        calls_of: Dict[str, Set[str]] = {}  # method -> self-calls
        for m in methods:
            for sub in ast.walk(m):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if isinstance(fn, ast.Attribute):
                    recv = _self_attr(fn.value)
                    if recv in cvs and fn.attr in (
                        "notify", "notify_all"
                    ):
                        notify_methods.setdefault(recv, set()).add(
                            m.name
                        )
                    if (
                        isinstance(fn.value, ast.Name)
                        and fn.value.id == "self"
                    ):
                        calls_of.setdefault(m.name, set()).add(fn.attr)

        def reaches_notify(method: str, cv: str) -> bool:
            if method in notify_methods.get(cv, ()):
                return True
            return any(
                callee in notify_methods.get(cv, ())
                for callee in calls_of.get(method, ())
            )

        def holds(node, parents, cv: str) -> bool:
            lock = cvs.get(cv)
            for a in _ancestors(node, parents):
                if isinstance(a, (ast.With, ast.AsyncWith)):
                    for item in a.items:
                        attr = _self_attr(item.context_expr)
                        if attr == cv or (lock and attr == lock):
                            return True
            return False

        predicate_attrs: Dict[str, Set[str]] = {}  # cv -> attrs
        wait_sites = []  # (method, call node, cv, parents)
        for m in methods:
            parents = _parent_map(m)
            for sub in ast.walk(m):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if not isinstance(fn, ast.Attribute):
                    continue
                recv = _self_attr(fn.value)
                if recv not in cvs:
                    continue
                if fn.attr == "wait":
                    wait_sites.append((m, sub, recv, parents))
                    # Predicate attrs: the wait loop's test plus any
                    # if-guards between the loop and the wait.
                    loop = None
                    for a in _ancestors(sub, parents):
                        if isinstance(a, ast.While):
                            loop = a
                            break
                    if loop is not None:
                        pool = [loop.test] + [
                            a.test
                            for a in _ancestors(sub, parents)
                            if isinstance(a, ast.If)
                            and a.lineno >= loop.lineno
                        ]
                        attrs = predicate_attrs.setdefault(
                            recv, set()
                        )
                        for t in pool:
                            for n2 in ast.walk(t):
                                a2 = _self_attr(n2)
                                if a2:
                                    attrs.add(a2)
                elif fn.attr in ("notify", "notify_all"):
                    if m.name.endswith("_locked"):
                        continue  # caller holds the monitor (house
                        # convention, same as TPU009's helper rule)
                    if not holds(sub, parents, recv):
                        yield self.finding(
                            f,
                            sub,
                            f"{cls.name}.{m.name}: notify on "
                            f"self.{recv} outside 'with "
                            f"self.{recv}' — a waiter can miss the "
                            "wakeup between its predicate check and "
                            "its wait",
                            symbol=(
                                f"notify-unlocked:{cls.name}."
                                f"{m.name}:{recv}"
                            ),
                        )

        for m, call, cv, parents in wait_sites:
            in_while = any(
                isinstance(a, ast.While)
                for a in _ancestors(call, parents)
            )
            if not in_while:
                yield self.finding(
                    f,
                    call,
                    f"{cls.name}.{m.name}: self.{cv}.wait() outside "
                    "a while-predicate loop — spurious wakeups and "
                    "missed notifies make a bare wait return without "
                    "its condition holding",
                    symbol=f"wait-no-while:{cls.name}.{m.name}:{cv}",
                )

        # Predicate-state writes with no reachable notify.
        for m in methods:
            if m.name.endswith("_locked"):
                continue
            parents = _parent_map(m)
            for sub in ast.walk(m):
                target = None
                if isinstance(sub, ast.AugAssign):
                    target = _self_attr(sub.target)
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        target = target or _self_attr(t)
                if target is None:
                    continue
                for cv, attrs in predicate_attrs.items():
                    if target not in attrs:
                        continue
                    if not holds(sub, parents, cv):
                        continue  # unlocked writes are TPU009's beat
                    if reaches_notify(m.name, cv):
                        continue
                    yield self.finding(
                        f,
                        sub,
                        f"{cls.name}.{m.name}: writes predicate "
                        f"state self.{target} under self.{cv} but "
                        "no notify is reachable — sleepers re-check "
                        "only on timeout (or never)",
                        symbol=(
                            f"predicate-no-notify:{cls.name}."
                            f"{m.name}:{target}"
                        ),
                        severity="warning",
                    )


# ------------------------------------------------------------- TPU021


class CounterBalanceChecker(Checker):
    rule = "TPU021"
    name = "counter-balance"
    severity = "error"
    layer = "lifetime"

    def check(self, project: Project) -> Iterator[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            markers = [
                m for m in _scan_markers(f)
                if m.verb == "counter" and not m.standalone
            ]
            if not markers:
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(f, node, markers)

    def _check_class(
        self, f: SourceFile, cls: ast.ClassDef, markers
    ) -> Iterator[Finding]:
        lo, hi = cls.lineno, cls.end_lineno or cls.lineno
        counters: Dict[str, str] = {}  # attr -> kind
        for m in markers:
            if not (lo <= m.line <= hi):
                continue
            stmt = _innermost_stmt(cls, m.line)
            attr = None
            if isinstance(stmt, ast.Assign) and stmt.targets:
                attr = _self_attr(stmt.targets[0])
                if attr is None and isinstance(
                    stmt.targets[0], ast.Name
                ):
                    attr = stmt.targets[0].id
            elif isinstance(stmt, ast.AnnAssign):
                attr = _self_attr(stmt.target)
                if attr is None and isinstance(
                    stmt.target, ast.Name
                ):
                    attr = stmt.target.id
            if attr:
                counters[attr] = m.arg
        if not counters:
            return
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

        def delta(stmt: ast.stmt, attr: str) -> Optional[str]:
            """'inc' / 'dec' when ``stmt`` adjusts ``self.attr``."""
            if isinstance(stmt, ast.AugAssign):
                if _self_attr(stmt.target) != attr:
                    return None
                if isinstance(stmt.op, ast.Add):
                    return "inc"
                if isinstance(stmt.op, ast.Sub):
                    return "dec"
                return None
            if isinstance(stmt, ast.Assign):
                if not any(
                    _self_attr(t) == attr for t in stmt.targets
                ):
                    return None
                for sub in ast.walk(stmt.value):
                    if isinstance(sub, ast.BinOp) and any(
                        _self_attr(s) == attr
                        for s in (sub.left, sub.right)
                    ):
                        if isinstance(sub.op, ast.Add):
                            return "inc"
                        if isinstance(sub.op, ast.Sub):
                            return "dec"
            return None

        sites: Dict[str, Dict[str, List[Tuple[ast.stmt, str]]]] = {}
        for attr in counters:
            sites[attr] = {}
            for m in methods:
                hits = []
                for sub in ast.walk(m):
                    if isinstance(sub, ast.stmt):
                        d = delta(sub, attr)
                        if d:
                            hits.append((sub, d))
                if hits:
                    sites[attr][m.name] = hits

        by_name = {m.name: m for m in methods}
        for attr, kind in counters.items():
            per_method = sites[attr]
            incs = [
                (mn, s) for mn, hs in per_method.items()
                for s, d in hs if d == "inc"
            ]
            decs = [
                (mn, s) for mn, hs in per_method.items()
                for s, d in hs if d == "dec"
            ]
            if incs and not decs:
                mn, s = incs[0]
                yield self.finding(
                    f,
                    s,
                    f"{cls.name}: counter {kind!r} (self.{attr}) is "
                    "incremented but never decremented anywhere in "
                    "the class — the gauge can only saturate",
                    symbol=f"never-dec:{cls.name}:{attr}",
                )
                continue
            # Methods containing both sides must balance on every
            # path — the try/finally shape, checked on the CFG.
            for mn, hits in per_method.items():
                kinds = {d for _s, d in hits}
                if kinds != {"inc", "dec"}:
                    continue
                yield from self._balance(
                    f, cls, by_name[mn], attr, kind, hits
                )

    def _balance(
        self, f, cls, method, attr, kind, hits
    ) -> Iterator[Finding]:
        inc_lines = {s.lineno for s, d in hits if d == "inc"}
        dec_lines = {s.lineno for s, d in hits if d == "dec"}
        graph = cfgmod.build_cfg(method)
        state: Dict[int, Set[int]] = {graph.entry: set()}
        work = [graph.entry]
        leak: Optional[Tuple[int, int]] = None
        while work:
            n = work.pop()
            node = graph.node(n)
            s_in = state.get(n, set())
            s = set(s_in)
            line = node.line
            if line in dec_lines:
                s = set()  # any reachable dec discharges
            elif line in inc_lines:
                s = s | {line}
            for succ, ekind in graph.succs[n]:
                out = s
                if ekind == cfgmod.EDGE_EXC and line in inc_lines:
                    out = s_in  # the raising inc never incremented
                target = graph.node(succ)
                if target.kind in (
                    cfgmod.N_RETURN_EXIT, cfgmod.N_EXC_EXIT
                ):
                    for inc_line in out:
                        if leak is None or inc_line < leak[0]:
                            leak = (inc_line, line or inc_line)
                    continue
                if succ not in state:
                    state[succ] = set(out)
                    work.append(succ)
                elif not out <= state[succ]:
                    state[succ] |= out
                    work.append(succ)
        if leak is not None:
            anchor = ast.Name(
                id="x", lineno=leak[0], col_offset=0
            )
            yield self.finding(
                f,
                anchor,
                f"{cls.name}.{method.name}: counter {kind!r} "
                f"(self.{attr}) incremented here but a path reaches "
                f"function exit (around line {leak[1]}) without the "
                "decrement — move the decrement into a finally or "
                "cover the raising statements",
                symbol=(
                    f"unbalanced:{cls.name}.{method.name}:{attr}"
                ),
            )


# ------------------------------------------------------------- TPU022


def _dotted(node: ast.AST) -> Optional[str]:
    """'self.cache' / 'x' for a pure Name/Attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


class DonationWindowChecker(Checker):
    rule = "TPU022"
    name = "donation-window"
    severity = "error"
    layer = "lifetime"

    def check(self, project: Project) -> Iterator[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            fns = _walk_functions(f)
            for m in _scan_markers(f):
                if m.verb != "donates" or m.standalone:
                    continue
                fi = _enclosing(fns, m.line)
                if fi is None:
                    continue
                names = [
                    n.strip() for n in m.arg.split(",") if n.strip()
                ]
                yield from self._check_window(f, fi, m.line, names)

    def _check_window(
        self, f: SourceFile, fi: _FnInfo, line: int,
        names: List[str],
    ) -> Iterator[Finding]:
        dispatch = _innermost_stmt(fi.node, line)
        if dispatch is None:
            return
        # A name the dispatch itself rebinds has no window: its new
        # binding IS the result, the donated buffer is unreachable.
        bound = set()
        if isinstance(dispatch, ast.Assign):
            for t in dispatch.targets:
                for el in (
                    t.elts if isinstance(t, ast.Tuple) else [t]
                ):
                    d = _dotted(el)
                    if d:
                        bound.add(d)
        open_names = [n for n in names if n not in bound]
        if not open_names:
            return
        graph = cfgmod.build_cfg(fi.node)
        dispatch_nodes = [
            n.id for n in graph.nodes
            if n.stmt is not None and n.stmt.lineno == dispatch.lineno
        ]

        def closes(stmt: ast.stmt, name: str) -> bool:
            for call in _calls_in_stmt(stmt):
                if _terminal_name(call.func) == "block_until_ready":
                    return True
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for el in (
                        t.elts if isinstance(t, ast.Tuple) else [t]
                    ):
                        if _dotted(el) == name:
                            return True
            return False

        for name in open_names:
            reported = False
            for dn in dispatch_nodes:
                if reported:
                    break
                stop = {
                    n.id for n in graph.nodes
                    if n.stmt is not None
                    and n.stmt.lineno != dispatch.lineno
                    and closes(n.stmt, name)
                }
                for nid in cfgmod.reachable_between(graph, dn, stop):
                    node = graph.node(nid)
                    if node.stmt is None or nid in stop:
                        continue
                    if node.stmt.lineno == dispatch.lineno:
                        continue
                    roots = (
                        _header_exprs(node.stmt)
                        if isinstance(node.stmt, _COMPOUND)
                        else [node.stmt]
                    )
                    hit = None
                    for r in roots:
                        for sub in ast.walk(r):
                            if (
                                isinstance(
                                    sub, (ast.Name, ast.Attribute)
                                )
                                and isinstance(
                                    getattr(sub, "ctx", None),
                                    ast.Load,
                                )
                                and _dotted(sub) == name
                            ):
                                hit = sub
                                break
                        if hit:
                            break
                    if hit is not None:
                        yield self.finding(
                            f,
                            node.stmt,
                            f"{fi.qname}: reads {name!r} inside its "
                            "donation window (dispatched at line "
                            f"{dispatch.lineno}) — the donated "
                            "buffer may already be overwritten; "
                            "rebind the name from the jit's output "
                            "or block_until_ready first",
                            symbol=(
                                f"donation-window:{fi.qname}:{name}"
                            ),
                        )
                        reported = True
                        break
