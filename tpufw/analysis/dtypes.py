"""TPU008: dtype drift across the jit boundary.

Three failure modes, all silent at runtime on TPU:

- ``dtypeless``: ``jnp.zeros``/``ones``/``empty`` with no dtype inside
  traced code defaults to fp32. In a bf16 hot loop the fp32 value
  poisons downstream arithmetic (jax promotes bf16+fp32 -> fp32), so
  one forgotten dtype doubles the flop and memory cost of everything
  it touches. ``jnp.arange`` defaults to int — legitimate for
  indexing, so it is flagged only when the result feeds float
  arithmetic directly.
- ``upcast``: an expression that provably mixes strong-bf16 and
  strong-fp32 operands. jax will widen to fp32 without a word; if the
  widening is intended (an accumulator), it should be written as an
  explicit ``astype``/``preferred_element_type`` so the reader — and
  this rule — can see it.
- ``accum``: a loss/accumulation-shaped traced function that reduces
  a provably-bf16 value with no fp32 evidence anywhere in the
  function (no ``astype(float32)``, no
  ``preferred_element_type=float32``). PR 7's pipeline work showed
  bf16 loss/grad-accum sums lose ulps at scale; ops/loss.py is the
  canonical fp32-epilogue idiom this warns toward.

All three act only on *proven* local dtypes from
:class:`tpufw.analysis.dataflow.DtypeEnv` — an ``unknown`` operand
never fires a finding.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Set

from tpufw.analysis import callgraph as cg
from tpufw.analysis import dataflow as df
from tpufw.analysis.core import Checker, Finding, Project, SourceFile

_ACCUM_FN_RE = re.compile(
    r"loss|xent|cross_entropy|accum|epilogue|logit|vocab|softmax|nll"
)
_REDUCERS = {"sum", "mean"}


def _walk_no_defs(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node`` without descending into nested function scopes
    (including when ``node`` itself is a def statement)."""
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue  # a nested scope, analyzed on its own
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _scopes_in(fn: cg.FuncNode) -> Iterator[cg.FuncNode]:
    """``fn`` and every function scope nested inside it (scan steps,
    grad closures) — each analyzed with its own local dtype env."""
    yield fn
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for sub in ast.walk(stmt):
            if isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                yield sub


def _has_fp32_evidence(fn: cg.FuncNode) -> bool:
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                nm = cg.call_name(sub)
                if nm == "astype" and sub.args:
                    if df.dtype_of_node(sub.args[0]) == df.FP32:
                        return True
                for kw in sub.keywords:
                    if kw.arg == "preferred_element_type":
                        if df.dtype_of_node(kw.value) == df.FP32:
                            return True
            chain = cg.attr_chain(sub)
            if chain and chain[-1] in ("float32", "float64"):
                return True
    return False


class DtypeDriftChecker(Checker):
    rule = "TPU008"
    name = "dtype-drift"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        roots = cg.find_traced_roots(index, project.files)
        # find_jit_sites additionally sees through partial bindings
        # (`step = partial(f, ...); jax.jit(step)`), which the plain
        # root walk cannot — fold those functions in as roots.
        root_ids = {id(fi.node) for fi, _how in roots}
        for site in df.find_jit_sites(index, project.files):
            if site.fn is not None and id(site.fn.node) not in root_ids:
                roots.append((site.fn, site.how))
                root_ids.add(id(site.fn.node))
        reachable = cg.reachable_functions(index, roots)
        seen_nodes: Set[int] = set()
        for fi, _how in reachable.values():
            for scope in _scopes_in(fi.node):
                if id(scope) in seen_nodes:
                    continue
                seen_nodes.add(id(scope))
                yield from self._check_scope(fi, scope)

    # ------------------------------------------------------ one scope

    def _check_scope(
        self, fi: cg.FunctionInfo, scope: cg.FuncNode
    ) -> Iterator[Finding]:
        file: SourceFile = fi.file
        env = df.DtypeEnv(scope)
        qname = fi.qname if scope is fi.node else (
            f"{fi.qname}.{getattr(scope, 'name', '<lambda>')}"
        )
        body = scope.body if isinstance(scope.body, list) else [scope.body]
        for stmt in body:
            for node in _walk_no_defs(stmt):
                if isinstance(node, ast.Call):
                    yield from self._check_ctor(file, qname, node)
                elif isinstance(node, ast.BinOp):
                    yield from self._check_upcast(file, env, qname, node)
        yield from self._check_accum(file, env, qname, scope)

    def _check_ctor(
        self, file: SourceFile, qname: str, call: ast.Call
    ) -> Iterator[Finding]:
        name = cg.call_name(call)
        chain = cg.attr_chain(call.func) or []
        is_jnp = len(chain) >= 2 and chain[0] in df._JNP_ALIASES
        if not is_jnp:
            return
        if name in ("zeros", "ones", "empty"):
            if df._ctor_dtype_arg(call) is None:
                src = ast.unparse(call)[:48]
                yield self.finding(
                    file,
                    call,
                    f"dtype-less jnp.{name} in traced {qname!r} "
                    "defaults to fp32 and silently upcasts bf16 "
                    "arithmetic it meets; write the dtype you mean "
                    "(fp32 for accumulators, the compute dtype for "
                    "activations)",
                    symbol=f"dtypeless:{qname}:{src}",
                )

    def _check_upcast(
        self, file: SourceFile, env: df.DtypeEnv, qname: str,
        node: ast.BinOp,
    ) -> Iterator[Finding]:
        ld, rd = env.infer(node.left), env.infer(node.right)
        pair = {ld, rd}
        if pair == {df.BF16, df.FP32}:
            src = ast.unparse(node)[:48]
            yield self.finding(
                file,
                node,
                f"expression in traced {qname!r} mixes strong bf16 "
                "and strong fp32 operands — jax widens to fp32 "
                "silently; make the intent explicit with .astype()",
                symbol=f"upcast:{qname}:{src}",
            )
        # int arange feeding float math: the int default was probably
        # not what the author meant.
        for side, d in ((node.left, ld), (node.right, rd)):
            if (
                isinstance(side, ast.Call)
                and cg.call_name(side) == "arange"
                and df._ctor_dtype_arg(side) is None
                and isinstance(node.op, (ast.Div, ast.Mult))
                and {ld, rd} & {df.BF16, df.FP16, df.FP32, df.WEAK_FLOAT}
                and d == df.INT
            ):
                src = ast.unparse(side)[:48]
                yield self.finding(
                    file,
                    side,
                    f"dtype-less jnp.arange in traced {qname!r} feeds "
                    "float arithmetic: int->float promotion here is "
                    "implicit fp32; pass the intended float dtype",
                    symbol=f"dtypeless:{qname}:{src}",
                )

    def _check_accum(
        self, file: SourceFile, env: df.DtypeEnv, qname: str,
        scope: cg.FuncNode,
    ) -> Iterator[Finding]:
        simple = qname.rsplit(".", 1)[-1]
        if not _ACCUM_FN_RE.search(simple):
            return
        if _has_fp32_evidence(scope):
            return
        body = scope.body if isinstance(scope.body, list) else [scope.body]
        for stmt in body:
            for node in _walk_no_defs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = cg.call_name(node)
                if name not in _REDUCERS:
                    continue
                operand: ast.AST
                if node.args:
                    operand = node.args[0]
                elif isinstance(node.func, ast.Attribute):
                    operand = node.func.value
                else:
                    continue
                if env.infer(operand) == df.BF16:
                    yield self.finding(
                        file,
                        node,
                        f"loss/accum-shaped traced {qname!r} reduces a "
                        "bf16 value with no fp32 accumulator in sight "
                        "(no astype(float32) / "
                        "preferred_element_type): bf16 sums lose "
                        "precision at scale — accumulate in fp32 as "
                        "ops/loss.py does",
                        symbol=f"accum:{qname}:{name}",
                        severity="warning",
                    )
                    return  # one per function is signal enough
