"""TPU006: a jit whose output structurally replaces a large array
input must donate that input.

Without ``donate_argnums``/``donate_argnames``, XLA must keep the
input buffer alive while materializing the output, so every
update-and-return step — optimizer updates, KV-cache inserts, page
table rewrites — transiently holds TWO copies of its largest
arrays. On an HBM-bound TPU footprint (ISSUE 8 / the concurrency
paper in PAPERS.md) that doubling IS the capacity ceiling: the
difference between fitting 8B params + opt state on a v5e-16 and
OOMing at startup.

Detection: for every jit/pjit site whose traced function we can see,
run a forward taint pass over the body distinguishing *aliasing*
(the value merely derives from a parameter — a read, a slice, a
pass-through) from *updating* (functional replacement: ``.at[].set``,
``dynamic_update_slice``, ``optax.apply_updates``, ``.replace(...)``,
``tree_map`` over the param, a ``lax.scan`` carry seeded with it, or
rebinding the parameter's own name from a call that consumes it).
Returning an *updated* value whose source parameter matches the
large-array name heuristic and is not donated is the finding.
Pure aliased reads never fire — that asymmetry is what keeps
gather-only jits (lookups, metric reductions) clean.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Sequence, Set, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis import dataflow as df
from tpufw.analysis.core import Checker, Finding, Project

# (function qname, param) pairs where returning a non-donated large
# input is deliberate — genuinely aliased reads the heuristic cannot
# distinguish. Prefer inline `# tpulint: disable=TPU006` with a
# justification next to the jit; this list exists for cases where the
# decorator line is generated or shared.
_ALLOWED_ALIASED: Set[Tuple[str, str]] = set()

# .at[...].<op>(...) functional-update methods.
_AT_OPS = {
    "set", "add", "multiply", "mul", "divide", "div", "power",
    "min", "max", "apply", "get",
}

_UPDATE_CALLS = {"dynamic_update_slice", "apply_updates"}
_TREE_MAPS = {"tree_map", "tree_multimap"}

# x.shape / x.dtype reads are scalar metadata, not the buffer: a value
# built from them (an index, a zeros() of the same shape) does NOT
# alias x's memory.
_METADATA_ATTRS = {"shape", "dtype", "ndim", "size", "sharding",
                   "itemsize", "weak_type"}

# lax control-flow ops whose result is the (rebound) carry: the index
# of the carry-init argument.
_CARRY_ARG = {"scan": 1, "while_loop": 2, "fori_loop": 3}


class _Taint:
    """Forward alias/update taint over one traced function body."""

    def __init__(self, params: Sequence[str]):
        self.params = set(params)
        # var name -> source params it derives from (any dataflow)
        self.alias: Dict[str, Set[str]] = {p: {p} for p in params}
        # var name -> source params it is an UPDATED version of
        self.updated: Dict[str, Set[str]] = {}

    # -------------------------------------------------- expressions

    def aliases(self, node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        stack: List[ast.AST] = [node]
        while stack:
            sub = stack.pop()
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr in _METADATA_ATTRS
            ):
                continue  # vocab = logits.shape[-1] aliases nothing
            if isinstance(sub, ast.Name):
                out |= self.alias.get(sub.id, set())
            stack.extend(ast.iter_child_nodes(sub))
        return out

    def direct_updates(self, node: ast.AST) -> Set[str]:
        """Params functionally updated by an expression itself."""
        out: Set[str] = set()
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            name = cg.call_name(sub)
            # x.at[idx].set(v) — receiver is Subscript(Attribute .at)
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _AT_OPS
                and isinstance(func.value, ast.Subscript)
                and isinstance(func.value.value, ast.Attribute)
                and func.value.value.attr == "at"
            ):
                if func.attr != "get":
                    out |= self.aliases(func.value.value.value)
            elif name in _UPDATE_CALLS and sub.args:
                out |= self.aliases(sub.args[0])
                if name == "apply_updates" and len(sub.args) > 1:
                    out |= self.aliases(sub.args[1])
            elif name in _TREE_MAPS and len(sub.args) > 1:
                for a in sub.args[1:]:
                    out |= self.aliases(a)
            elif name == "apply_gradients" and isinstance(
                func, ast.Attribute
            ):
                out |= self.aliases(func.value)
            elif name == "replace" and isinstance(func, ast.Attribute):
                out |= self.aliases(func.value)
        return out

    def updated_sources(self, node: ast.AST) -> Set[str]:
        out = self.direct_updates(node)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                out |= self.updated.get(sub.id, set())
        return out

    # --------------------------------------------------- statements

    def visit_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._visit(stmt)

    def _visit(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs (scan steps) analyzed via their scan
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.target, ast.Name
        ):
            t = stmt.target.id
            self.alias[t] = self.alias.get(t, set()) | self.aliases(
                stmt.value
            )
        elif isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Call
        ):
            # out.append(updated_row): the list inherits the taint.
            call = stmt.value
            if (
                isinstance(call.func, ast.Attribute)
                and call.func.attr in ("append", "extend", "insert")
                and isinstance(call.func.value, ast.Name)
                and call.args
            ):
                t = call.func.value.id
                for a in call.args:
                    self.alias[t] = self.alias.get(t, set()) | (
                        self.aliases(a)
                    )
                    self.updated[t] = self.updated.get(t, set()) | (
                        self.updated_sources(a)
                    )
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list):
                self.visit_body([s for s in sub if isinstance(s, ast.stmt)])
        for h in getattr(stmt, "handlers", []) or []:
            self.visit_body(h.body)

    def _assign(
        self, targets: Sequence[ast.expr], value: ast.AST
    ) -> None:
        als = self.aliases(value)
        upd = self.updated_sources(value)
        # Rebinding a parameter's own name from a call that consumes it
        # is the `cache = apply(cache, ...)` idiom — the new value
        # structurally replaces the old buffer.
        rebind: Set[str] = set()
        if isinstance(value, ast.Call):
            consumed = self.aliases(value)
            for t in targets:
                for nm in _names_in(t):
                    if nm in self.params and nm in consumed:
                        rebind.add(nm)
        carry_idx = (
            _CARRY_ARG.get(cg.call_name(value) or "")
            if isinstance(value, ast.Call)
            else None
        )
        for t in targets:
            if carry_idx is not None and len(value.args) > carry_idx:
                # lax.scan/while/fori: the result carry is a rebound
                # version of the INIT argument's buffers — the step
                # function (args before the init) merely reads params
                # through its closure and must not taint the carry.
                init = value.args[carry_idx]
                als_c = self.aliases(init)
                # Only a parameter passed DIRECTLY as (part of) the
                # init is rebound by the carry; a local merely derived
                # from a param (a prefilled cache computed FROM the
                # weights) is fresh memory, not a replacement.
                upd_c = self.updated_sources(init) | (
                    _direct_names(init) & self.params
                )
                if (
                    cg.call_name(value) == "scan"
                    and isinstance(t, ast.Tuple)
                    and t.elts
                ):
                    # (carry...), ys = lax.scan(...): ys is fresh.
                    carry_names = _names_in(t.elts[0])
                    other_names: Set[str] = set()
                    for e in t.elts[1:]:
                        other_names |= _names_in(e)
                else:
                    carry_names = _names_in(t)
                    other_names = set()
                for nm in carry_names:
                    self.alias[nm] = set(als_c)
                    self.updated[nm] = set(upd_c)
                for nm in other_names:
                    self.alias[nm] = set()
                    self.updated[nm] = set()
                continue
            for nm in _names_in(t):
                self.alias[nm] = set(als)
                self.updated[nm] = set(upd) | (
                    {nm} if nm in rebind else set()
                )


def _direct_names(node: ast.AST) -> Set[str]:
    """Bare names at the top level of a (possibly nested) tuple/list
    expression — NOT names buried inside calls or subscripts."""
    if isinstance(node, ast.Name):
        return {node.id}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            out |= _direct_names(e)
        return out
    return set()


def _names_in(t: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(t):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
    return out


class DonationChecker(Checker):
    rule = "TPU006"
    name = "jit-donation"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        sites = df.find_jit_sites(index, project.files)
        for site in sites:
            if site.donate_unparsed:
                continue  # dynamic donate spec: assume the author knew
            node = site.fn.node if site.fn is not None else site.lam
            if node is None:
                continue
            params = site.positional_params() + site.kwonly_params()
            large = [
                p for p in params
                if df.is_large_param(p) and not site.is_static(p)
            ]
            if not large:
                continue
            taint = _Taint(params)
            if isinstance(node, ast.Lambda):
                returned = [node.body]
            else:
                taint.visit_body(node.body)
                returned = [
                    r.value
                    for r in ast.walk(node)
                    if isinstance(r, ast.Return) and r.value is not None
                ]
            flagged: Set[str] = set()
            for expr in returned:
                flagged |= taint.updated_sources(expr)
                flagged |= taint.direct_updates(expr)
            qname = site.display_name()
            for p in sorted(flagged):
                if p not in large or site.is_donated(p):
                    continue
                if (qname, p) in _ALLOWED_ALIASED:
                    continue
                yield self.finding(
                    site.file,
                    site.node,
                    f"jit of {qname!r} returns an updated version of "
                    f"large input {p!r} without donating it "
                    f"(donate_argnames=({p!r},)); the un-donated input "
                    "doubles peak HBM for the step",
                    symbol=f"donate:{qname}:{p}",
                )
