"""TPU002 — mesh/axis-name consistency.

The mesh is declared once (``tpufw/mesh/mesh.py``: the ``AXIS_*``
constants / ``MESH_AXES`` tuple, with ``parallel/context.py`` holding
the process-wide current mesh); every collective and every
``PartitionSpec`` then names axes *by string*. A ``psum`` over an axis
the mesh doesn't define is a shard_map/jit error only on the code path
that executes it — on an MPMD pipeline ("Scaling Deep Learning
Training with MPMD Pipeline Parallelism", PAPERS.md) that path may be
one schedule variant nobody smoke-tested. This rule resolves every
axis-name literal statically instead:

- collectives (``psum``/``pmean``/``all_gather``/``ppermute``/...)
  must name declared *mesh* axes;
- ``PartitionSpec``/``P`` literals must name declared mesh axes or
  declared flax *logical* axes (the ``logical_axis_rules`` table) —
  logical names in a raw collective are still an error.

Dynamic axis arguments (``axis_name`` parameters) are skipped: the
rule is about literals, the callers of parametric helpers are where
the literals live.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import Checker, Finding, Project, SourceFile

# jax.lax collectives taking an axis name (or tuple of axis names).
# Value = index of the positional axis argument.
COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "pshuffle": 1,
    "axis_index": 0,
    "axis_size": 0,
}

_SPEC_NAMES = {"PartitionSpec", "P"}


def declared_axes(
    project: Project, index: cg.ModuleIndex
) -> Tuple[Set[str], Set[str], List[str]]:
    """(mesh_axes, logical_axes, source_files).

    Mesh axes come from ``AXIS_* = "..."`` constants and literal
    ``Mesh(..., ("a", "b"))`` axis-name tuples under ``tpufw/mesh/``
    and ``tpufw/parallel/``; logical axes from the first element of
    every pair in ``logical_axis_rules``."""
    mesh_axes: Set[str] = set()
    logical: Set[str] = set()
    sources: List[str] = []
    decl_files = [
        f
        for f in project.files
        if f.relpath.startswith(("tpufw/mesh/", "tpufw/parallel/"))
    ]
    for f in decl_files:
        if f.tree is None:
            continue
        mod = cg.module_name(f.relpath)
        found = False
        for (m, name), val in index.constants.items():
            if m == mod and name.startswith("AXIS_"):
                mesh_axes.add(val)
                found = True
        for node in ast.walk(f.tree):
            # Mesh(devices, ("data", ...)) / axis_names= kwarg.
            if isinstance(node, ast.Call) and cg.call_name(node) == "Mesh":
                cands = list(node.args[1:2]) + [
                    kw.value
                    for kw in node.keywords
                    if kw.arg == "axis_names"
                ]
                for c in cands:
                    for _, s in index.resolve_str_elements(c, mod):
                        mesh_axes.add(s)
                        found = True
            # logical_axis_rules: (("batch", ("data", "fsdp")), ...)
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "logical_axis_rules"
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Tuple) and len(sub.elts) == 2:
                        first = sub.elts[0]
                        if isinstance(
                            first, ast.Constant
                        ) and isinstance(first.value, str):
                            logical.add(first.value)
                            found = True
        if found:
            sources.append(f.relpath)
    return mesh_axes, logical, sources


class MeshAxisChecker(Checker):
    rule = "TPU002"
    name = "mesh-axis-consistency"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        mesh_axes, logical, _src = declared_axes(project, index)
        if not mesh_axes:
            # No mesh declaration in the scanned tree (fixture subsets)
            # -> nothing to resolve against; stay silent rather than
            # flagging every axis in sight.
            return
        spec_ok = mesh_axes | logical
        for f in project.files:
            if f.tree is None:
                continue
            mod = cg.module_name(f.relpath)
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = cg.call_name(node)
                if name in COLLECTIVES:
                    yield from self._check_collective(
                        f, index, mod, node, name, mesh_axes
                    )
                elif name in _SPEC_NAMES:
                    yield from self._check_spec(
                        f, index, mod, node, spec_ok
                    )

    def _check_collective(
        self,
        f: SourceFile,
        index: cg.ModuleIndex,
        mod: str,
        node: ast.Call,
        name: str,
        mesh_axes: Set[str],
    ) -> Iterator[Finding]:
        pos = COLLECTIVES[name]
        axis_args: List[ast.AST] = []
        if len(node.args) > pos:
            axis_args.append(node.args[pos])
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names", "axes"):
                axis_args.append(kw.value)
        for arg in axis_args:
            for anode, axis in index.resolve_str_elements(arg, mod):
                if axis not in mesh_axes:
                    yield self.finding(
                        f,
                        anode if hasattr(anode, "lineno") else node,
                        f"{name}() over axis {axis!r}, which is not a "
                        f"declared mesh axis "
                        f"{tuple(sorted(mesh_axes))}",
                        symbol=f"{name}:{axis}",
                    )

    def _check_spec(
        self,
        f: SourceFile,
        index: cg.ModuleIndex,
        mod: str,
        node: ast.Call,
        spec_ok: Set[str],
    ) -> Iterator[Finding]:
        exprs = list(node.args) + [kw.value for kw in node.keywords]
        for arg in exprs:
            for anode, axis in index.resolve_str_elements(arg, mod):
                if axis not in spec_ok:
                    yield self.finding(
                        f,
                        anode if hasattr(anode, "lineno") else node,
                        f"PartitionSpec names axis {axis!r}, which is "
                        "neither a declared mesh axis nor a logical "
                        "axis from logical_axis_rules",
                        symbol=f"PartitionSpec:{axis}",
                    )
