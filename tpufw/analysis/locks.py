"""TPU009: lock discipline across this repo's threaded classes.

The serve scheduler, batcher, hang watchdog, flight recorder, and
prefetcher all share the same shape: a class owning a
``threading.Thread(target=self._loop)`` plus mutable state touched
from both the thread and the caller-facing API. The invariant is
classic monitor discipline — every attribute written on one side and
read on the other is accessed *only* under the owning lock — and a
violation is a torn read or lost update that surfaces as a once-a-week
serving hang, exactly the class of bug the obs watchdog (PR 5) exists
to catch at runtime. TPU009 checks it statically, per class:

- Inventory lock attributes (``self._cv = threading.Condition()``,
  ``Lock``/``RLock``/``Semaphore``) and intrinsically thread-safe
  attributes (``Event``, ``queue.Queue``, ``deque``, ``local`` —
  exempt).
- Partition methods into thread-side (reachable from a
  ``Thread(target=self.m)`` entry via self-calls) and main-side.
- An attribute written after ``__init__`` and touched from both sides
  must be accessed inside ``with self.<lock>:`` or in a private helper
  whose every internal call site holds the lock (monitor helpers like
  serve's ``_fail_req`` stay clean without re-acquiring). When every
  write comes from ONE side, that side owns the attribute and may
  touch it lock-free (single-writer discipline — serve's scheduler
  thread over its pool); only the reading side must lock, for
  consistent snapshots. Writes from both sides demand the lock at
  every access.
- Separately, nested ``with lockA: ... with lockB:`` acquisitions are
  recorded as an order; observing both (A,B) and (B,A) anywhere in
  the class is a deadlock-shaped inversion (warning).

Scope is deliberately class-level: module-level closures that smuggle
state through nonlocals (train/prefetch.py's worker) are invisible
here and documented as such in docs/ANALYSIS.md.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import Checker, Finding, Project, SourceFile

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_SAFE_CTORS = {"Event", "Queue", "SimpleQueue", "LifoQueue",
               "PriorityQueue", "deque", "local", "Barrier"}
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "put", "put_nowait",
}
_IGNORED_METHODS = {"__init__", "__post_init__", "__del__"}


class _Access:
    __slots__ = ("method", "attr", "kind", "held", "node")

    def __init__(self, method: str, attr: str, kind: str,
                 held: Set[str], node: ast.AST):
        self.method = method
        self.attr = attr
        self.kind = kind  # "read" | "write"
        self.held = held  # locks held lexically at the access
        self.node = node


class _ClassModel:
    """Everything TPU009 needs to know about one ClassDef."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.methods: Dict[str, ast.AST] = {
            m.name: m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.thread_targets: Set[str] = set()
        self.accesses: List[_Access] = []
        # method -> list of (callee_method, locks_held_at_call)
        self.self_calls: Dict[str, List[Tuple[str, Set[str]]]] = {}
        # ordered lock-acquisition pairs observed anywhere
        self.lock_pairs: Dict[Tuple[str, str], ast.AST] = {}
        self._inventory()
        for name, node in self.methods.items():
            self._scan_method(name, node)

    # ------------------------------------------------------ inventory

    def _inventory(self) -> None:
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            if isinstance(val, ast.Call):
                nm = cg.call_name(val)
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if nm in _LOCK_CTORS:
                        self.lock_attrs.add(attr)
                    elif nm in _SAFE_CTORS:
                        self.safe_attrs.add(attr)
            for sub in ast.walk(node.value):
                self._maybe_thread(sub)
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Call):
                self._maybe_thread(node)

    def _maybe_thread(self, node: ast.AST) -> None:
        if not (
            isinstance(node, ast.Call)
            and cg.call_name(node) == "Thread"
        ):
            return
        for kw in node.keywords:
            if kw.arg == "target":
                attr = _self_attr(kw.value)
                if attr is not None:
                    self.thread_targets.add(attr)

    # ----------------------------------------------------- per-method

    def _scan_method(self, name: str, fn: ast.AST) -> None:
        self.self_calls.setdefault(name, [])
        held: List[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.With):
                acquired: List[str] = []
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        for h in held:
                            if h != attr:
                                self.lock_pairs.setdefault(
                                    (h, attr), item.context_expr
                                )
                        held.append(attr)
                        acquired.append(attr)
                for s in node.body:
                    visit(s)
                for attr in acquired:
                    held.remove(attr)
                return
            self._record(name, node, set(held))
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    # Closures (cv.wait_for lambdas, worker defs) run
                    # with whatever the enclosing scope holds when
                    # they are *defined* under a with; treat them as
                    # part of the method at the current held set.
                    body = (
                        child.body
                        if isinstance(child.body, list)
                        else [child.body]
                    )
                    for s in body:
                        visit(s)
                    continue
                visit(child)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            visit(stmt)

    def _record(
        self, method: str, node: ast.AST, held: Set[str]
    ) -> None:
        # self.m(...) internal calls.
        if isinstance(node, ast.Call):
            attr = _self_attr(node.func)
            if attr is not None and attr in self.methods:
                self.self_calls[method].append((attr, set(held)))
                return
            # self.X.append(...) — container mutation is a write.
            if isinstance(node.func, ast.Attribute):
                recv = _self_attr(node.func.value)
                if recv is not None and node.func.attr in _MUTATOR_METHODS:
                    self.accesses.append(
                        _Access(method, recv, "write", held, node)
                    )
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is None or attr in self.methods:
                return
            kind = (
                "write"
                if isinstance(node.ctx, (ast.Store, ast.Del))
                else "read"
            )
            self.accesses.append(_Access(method, attr, kind, held, node))
        elif isinstance(node, ast.Subscript):
            # self.X[i] = v / del self.X[i] mutate the container.
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                recv = _self_attr(node.value)
                if recv is not None:
                    self.accesses.append(
                        _Access(method, recv, "write", set(held), node)
                    )

    # ------------------------------------------------- derived facts

    def thread_side(self) -> Set[str]:
        """Methods reachable from a Thread target via self-calls."""
        out: Set[str] = set()
        frontier = [t for t in self.thread_targets if t in self.methods]
        while frontier:
            m = frontier.pop()
            if m in out:
                continue
            out.add(m)
            for callee, _held in self.self_calls.get(m, []):
                if callee not in out:
                    frontier.append(callee)
        return out

    def method_guards(self) -> Dict[str, Set[str]]:
        """Locks provably held on *every* internal call path into each
        private method. Thread targets and public methods are entry
        points (empty guard): callers outside the class hold nothing."""
        callers: Dict[str, List[Tuple[str, Set[str]]]] = {}
        for caller, calls in self.self_calls.items():
            for callee, held in calls:
                callers.setdefault(callee, []).append((caller, held))
        # Only private, internally-called, non-thread-entry methods can
        # inherit a guard; everything else can be entered lock-free.
        refinable = {
            m for m in self.methods
            if m.startswith("_")
            and not m.startswith("__")
            and m not in self.thread_targets
            and m in callers
        }
        guards: Dict[str, Set[str]] = {
            m: (set(self.lock_attrs) if m in refinable else set())
            for m in self.methods
        }
        for _ in range(len(self.methods) + 1):
            changed = False
            for m in refinable:
                eff: Optional[Set[str]] = None
                for caller, held in callers[m]:
                    g = held | guards.get(caller, set())
                    eff = g if eff is None else (eff & g)
                eff = eff or set()
                if eff != guards[m]:
                    guards[m] = eff
                    changed = True
            if not changed:
                break
        return guards


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class LockDisciplineChecker(Checker):
    rule = "TPU009"
    name = "lock-discipline"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        for f in project.files:
            if f.tree is None:
                continue
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(f, node)

    def _check_class(
        self, f: SourceFile, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        model = _ClassModel(cls)
        if not model.thread_targets or not model.lock_attrs:
            return
        thread_side = model.thread_side()
        guards = model.method_guards()
        by_attr: Dict[str, List[_Access]] = {}
        for a in model.accesses:
            if a.method in _IGNORED_METHODS:
                continue
            if a.attr in model.lock_attrs or a.attr in model.safe_attrs:
                continue
            if a.attr.startswith("__"):
                continue
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            in_thread = [a for a in accs if a.method in thread_side]
            in_main = [a for a in accs if a.method not in thread_side]
            writes = [a for a in accs if a.kind == "write"]
            if not in_thread or not in_main or not writes:
                continue
            # Ownership: when ONE side performs every write, that side
            # may touch the attribute lock-free (single-writer
            # discipline — serve's scheduler thread over its pool);
            # only the READING side must take the lock, for consistent
            # snapshots. Writes from both sides are lost-update races:
            # then every access needs the lock.
            writer_sides = {
                a.method in thread_side for a in writes
            }
            if len(writer_sides) == 1:
                owner_is_thread = writer_sides == {True}
                candidates = [
                    a for a in accs
                    if (a.method in thread_side) != owner_is_thread
                ]
            else:
                candidates = accs
            unguarded = [
                a for a in candidates
                if not (a.held | guards.get(a.method, set()))
            ]
            if not unguarded:
                continue
            worst = min(
                unguarded, key=lambda a: getattr(a.node, "lineno", 0)
            )
            side = (
                "thread" if worst.method in thread_side else "caller"
            )
            locks = ", ".join(sorted(model.lock_attrs))
            yield self.finding(
                f,
                worst.node,
                f"{cls.name}.{attr} is shared between the "
                f"{cls.name} thread and its callers (written in "
                f"{writes[0].method!r}) but {worst.method!r} "
                f"accesses it from the {side} side without holding "
                f"a lock ({locks}); torn reads/lost updates follow",
                symbol=f"unguarded:{cls.name}.{attr}",
            )
        for (a, b), node in sorted(model.lock_pairs.items()):
            if (b, a) in model.lock_pairs and a < b:
                yield self.finding(
                    f,
                    node,
                    f"{cls.name} acquires {a!r} then {b!r} on one "
                    f"path and {b!r} then {a!r} on another — "
                    "lock-order inversion; pick one order",
                    symbol=f"lock-order:{cls.name}:{a},{b}",
                    severity="warning",
                )
