"""TPU005 — observability-name hygiene.

The PR-2 telemetry stack is only queryable if names stay closed-world:
an event ``kind`` outside ``tpufw.obs.events.SCHEMA`` raises at emit
time (on whichever code path finally runs it), and a metric name that
drifts from the ``docs/OBSERVABILITY.md`` catalog breaks every
dashboard and alert built on the documented series. This rule checks
both statically:

- every literal first argument to ``.emit(...)`` must be a kind
  declared in the ``SCHEMA`` dict of ``tpufw/obs/events.py``;
- every literal (or constant-resolvable) name passed to
  ``.counter()/.gauge()/.histogram()`` must start with ``tpufw_`` and
  appear in the metric catalog;
- serve.py-style prefixing wrappers (a class with a string ``PREFIX``
  attribute whose ``inc/register/reset`` methods prepend it) are
  resolved: the short names at their call sites are checked as
  ``PREFIX + name``, including the gauge dict handed to ``render``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import Checker, Finding, Project, SourceFile

EVENTS_MODULE = "tpufw/obs/events.py"
CATALOG_DOC = "docs/OBSERVABILITY.md"

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_WRAPPER_METHODS = {"inc", "register", "reset"}
_METRIC_TOKEN_RE = re.compile(r"tpufw_[a-z0-9_]+")


def schema_kinds(project: Project) -> Set[str]:
    f = project.file(EVENTS_MODULE)
    if f is None or f.tree is None:
        return set()
    kinds: Set[str] = set()
    for node in f.tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "SCHEMA" for t in targets
        ):
            continue
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    kinds.add(k.value)
    return kinds


def doc_metric_names(project: Project) -> Set[str]:
    text = project.read_doc(CATALOG_DOC)
    if text is None:
        return set()
    return set(_METRIC_TOKEN_RE.findall(text))


def _metric_prefixes(project: Project) -> Set[str]:
    """String PREFIX class attributes (the serve.py wrapper idiom)."""
    prefixes: Set[str] = set()
    for f in project.files:
        if f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "PREFIX"
                        for t in stmt.targets
                    )
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    prefixes.add(stmt.value.value)
    return prefixes


class ObsNameChecker(Checker):
    rule = "TPU005"
    name = "obs-name-hygiene"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        kinds = schema_kinds(project)
        doc_names = doc_metric_names(project)
        prefixes = _metric_prefixes(project)
        have_doc = project.read_doc(CATALOG_DOC) is not None
        for f in project.files:
            if f.tree is None or f.relpath == EVENTS_MODULE:
                continue
            mod = cg.module_name(f.relpath)
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr == "emit" and kinds:
                    yield from self._check_emit(f, node, kinds)
                elif attr in _METRIC_FACTORIES and have_doc:
                    yield from self._check_metric(
                        f, index, mod, node, doc_names
                    )
                elif attr in _WRAPPER_METHODS and prefixes and have_doc:
                    yield from self._check_wrapped(
                        f, node, prefixes, doc_names
                    )
                elif attr == "render" and prefixes and have_doc:
                    yield from self._check_render_gauges(
                        f, node, prefixes, doc_names
                    )

    def _check_emit(
        self, f: SourceFile, node: ast.Call, kinds: Set[str]
    ) -> Iterator[Finding]:
        if not node.args:
            return
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            if a0.value not in kinds:
                yield self.finding(
                    f,
                    node,
                    f"event kind {a0.value!r} is not declared in "
                    f"{EVENTS_MODULE} SCHEMA — emit() will raise at "
                    "runtime on this path",
                    symbol=f"event-kind:{a0.value}",
                )

    def _check_metric(
        self,
        f: SourceFile,
        index: cg.ModuleIndex,
        mod: str,
        node: ast.Call,
        doc_names: Set[str],
    ) -> Iterator[Finding]:
        if not node.args:
            return
        name = index.resolve_str(node.args[0], mod)
        if name is None:
            # Dynamic name (wrapper internals like self.PREFIX + name)
            # — the wrapper call sites are checked instead.
            return
        yield from self._validate_name(f, node, name, doc_names)

    def _check_wrapped(
        self,
        f: SourceFile,
        node: ast.Call,
        prefixes: Set[str],
        doc_names: Set[str],
    ) -> Iterator[Finding]:
        # metrics.inc("requests_total") — receiver must look like a
        # metrics wrapper, otherwise .inc() on a Counter itself (a
        # value, not a name) would be misread.
        base = cg.attr_chain(node.func)
        if base is None or not any("metric" in part for part in base):
            return
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                yield from self._validate_wrapped_name(
                    f, node, arg.value, prefixes, doc_names
                )

    def _check_render_gauges(
        self,
        f: SourceFile,
        node: ast.Call,
        prefixes: Set[str],
        doc_names: Set[str],
    ) -> Iterator[Finding]:
        base = cg.attr_chain(node.func)
        if base is None or not any("metric" in part for part in base):
            return
        for arg in node.args:
            if isinstance(arg, ast.Dict):
                for k in arg.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                        k.value, str
                    ):
                        yield from self._validate_wrapped_name(
                            f, node, k.value, prefixes, doc_names
                        )

    def _validate_wrapped_name(
        self,
        f: SourceFile,
        node: ast.Call,
        short: str,
        prefixes: Set[str],
        doc_names: Set[str],
    ) -> Iterator[Finding]:
        candidates = {p + short for p in prefixes}
        if candidates & doc_names:
            return
        shown = min(candidates)
        yield self.finding(
            f,
            node,
            f"metric {shown!r} (wrapper short name {short!r}) is not "
            f"in the {CATALOG_DOC} catalog — add it to the doc or fix "
            "the name",
            symbol=f"metric:{shown}",
        )

    def _validate_name(
        self,
        f: SourceFile,
        node: ast.Call,
        name: str,
        doc_names: Set[str],
    ) -> Iterator[Finding]:
        if not name.startswith("tpufw_"):
            yield self.finding(
                f,
                node,
                f"metric name {name!r} must carry the tpufw_ prefix "
                "(one namespace for every scrape)",
                symbol=f"metric-prefix:{name}",
            )
            return
        if name not in doc_names:
            yield self.finding(
                f,
                node,
                f"metric {name!r} is not in the {CATALOG_DOC} catalog "
                "— add it to the doc or fix the name",
                symbol=f"metric:{name}",
            )
