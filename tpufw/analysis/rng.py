"""TPU003 — PRNG key discipline.

JAX keys are values, not stateful generators: sampling twice with the
same key yields *identical* randomness, which silently correlates
dropout masks, rollout noise, and init across uses — a bug no test
asserting "loss goes down" catches. The rule tracks, per function
scope, every variable bound from ``jax.random.key/PRNGKey/split/
fold_in`` (plus parameters named like keys: ``key``, ``rng``,
``*_key``, ``*_rng``) and flags:

- a key consumed by two calls with no re-binding in between
  (``split`` counts as the one blessed consumption — using the parent
  key *after* splitting it is exactly the classic bug);
- a key consumed inside a loop body that never re-binds it (every
  iteration then reuses the same randomness);
- a key returned after it has already been consumed (the caller
  inherits a hot key with no way to know).

Receivers it can't see through (attributes, subscripts, closures) are
skipped — false negatives over false positives.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import Checker, Finding, Project, SourceFile

_KEY_PARAM_RE = re.compile(r"^(key|rng|prng|prng_key)$|_(key|rng)$")

# jax.random attrs that *transform* a key rather than sampling with it.
_KEY_MAKERS = {"key", "PRNGKey", "split", "fold_in", "clone", "wrap_key_data"}


def _is_random_attr(call: ast.Call) -> Optional[str]:
    """'split' for jax.random.split(...) / jrandom.split / random.split."""
    chain = cg.attr_chain(call.func)
    if not chain:
        return None
    if len(chain) >= 2 and chain[-2] in ("random", "jrandom", "jr"):
        return chain[-1]
    # Bare names: only PRNGKey is unambiguous enough — a local called
    # `split` (llama.py's jitted layer-splitter) is not jax.random.split.
    if len(chain) == 1 and chain[0] == "PRNGKey":
        return chain[0]
    return None


def _binds_key(value: ast.AST) -> bool:
    """Does this RHS produce key material?"""
    if isinstance(value, ast.Call):
        attr = _is_random_attr(value)
        if attr in _KEY_MAKERS:
            return True
    if isinstance(value, (ast.Tuple, ast.List)):
        return any(_binds_key(e) for e in value.elts)
    if isinstance(value, ast.Subscript):
        return _binds_key(value.value)
    return False


class _Use:
    __slots__ = ("node", "kind")

    def __init__(self, node: ast.AST, kind: str):
        self.node = node
        self.kind = kind  # "consume" | "rebind" | "return"


class RngDisciplineChecker(Checker):
    rule = "TPU003"
    name = "rng-key-discipline"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        for fi in index.functions:
            if fi.file.tree is None:
                continue
            yield from self._check_function(fi.file, fi)

    # ------------------------------------------------------------------

    def _check_function(
        self, f: SourceFile, fi: cg.FunctionInfo
    ) -> Iterator[Finding]:
        fn = fi.node
        key_vars: Set[str] = set()
        for p in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            if _KEY_PARAM_RE.search(p.arg):
                key_vars.add(p.arg)
        # First pass: every assignment that binds key material.
        own_body = self._own_statements(fn)
        for stmt in own_body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and _binds_key(node.value):
                    for t in node.targets:
                        key_vars.update(self._target_names(t))
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if _binds_key(node.value) and isinstance(
                        node.target, ast.Name
                    ):
                        key_vars.add(node.target.id)
        if not key_vars:
            return
        uses = self._collect_uses(own_body, key_vars)
        yield from self._linear_reuse(f, fi, uses)
        yield from self._loop_reuse(f, fi, own_body, key_vars)

    @staticmethod
    def _own_statements(fn: cg.FuncNode) -> List[ast.stmt]:
        """The function's statements, with nested def/lambda bodies
        excluded (they are their own scopes, checked separately)."""
        out: List[ast.stmt] = []
        body = fn.body if isinstance(fn.body, list) else []

        def visit(stmts: List[ast.stmt]) -> None:
            for s in stmts:
                if isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    continue
                out.append(s)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(s, field, None)
                    if isinstance(sub, list):
                        visit([x for x in sub if x is not s])
                for h in getattr(s, "handlers", []) or []:
                    visit(h.body)

        visit(body)
        return out

    @staticmethod
    def _walk_no_defs(stmt: ast.stmt) -> Iterator[ast.AST]:
        """Whole-subtree walk that skips nested def/class/lambda
        bodies — those run at another time with their own scope."""
        stack: List[ast.AST] = [stmt]
        root = True
        while stack:
            node = stack.pop()
            if not root and isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                continue
            root = False
            yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _walk_shallow(stmt: ast.stmt) -> Iterator[ast.AST]:
        """Walk one statement's expression level only: nested
        statements are in the flattened list and visited on their own
        turn (walking them here too would double-count every call
        inside a with/if/for body), and lambda bodies are a different
        execution time entirely."""
        stack: List[ast.AST] = [stmt]
        root = True
        while stack:
            node = stack.pop()
            if not root and isinstance(node, (ast.stmt, ast.Lambda)):
                continue
            root = False
            yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _target_names(t: ast.AST) -> Set[str]:
        names: Set[str] = set()
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                if isinstance(e, ast.Name):
                    names.add(e.id)
                elif isinstance(e, ast.Starred) and isinstance(
                    e.value, ast.Name
                ):
                    names.add(e.value.id)
        return names

    def _collect_uses(
        self, stmts: List[ast.stmt], key_vars: Set[str]
    ) -> Dict[str, List[_Use]]:
        """Per key var, source-ordered consume/rebind/return events
        over the function's own (non-nested) statements."""
        uses: Dict[str, List[_Use]] = {v: [] for v in key_vars}
        seen: Set[int] = set()
        for stmt in stmts:
            if id(stmt) in seen:
                continue
            seen.add(id(stmt))
            rebound: Set[str] = set()
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    rebound |= self._target_names(t) & key_vars
            for node in self._walk_shallow(stmt):
                if isinstance(node, ast.Call):
                    for v in self._consumed_keys(node, key_vars):
                        uses[v].append(_Use(node, "consume"))
                elif isinstance(node, ast.Return) and node.value is not None:
                    for sub in ast.walk(node.value):
                        if (
                            isinstance(sub, ast.Name)
                            and sub.id in key_vars
                        ):
                            uses[sub.id].append(_Use(node, "return"))
            for v in rebound:
                uses[v].append(_Use(stmt, "rebind"))
        for v in uses:
            uses[v].sort(
                key=lambda u: (
                    getattr(u.node, "lineno", 0),
                    getattr(u.node, "col_offset", 0),
                    # On the same statement, the consume happens before
                    # the rebind (k = split(k) uses then rebinds).
                    {"consume": 0, "return": 1, "rebind": 2}[u.kind],
                )
            )
        return uses

    @staticmethod
    def _consumed_keys(call: ast.Call, key_vars: Set[str]) -> Set[str]:
        """Key vars passed (top-level) to this call. jax.random key
        makers count too: split(key) is the key's one blessed use."""
        out: Set[str] = set()
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in key_vars:
                out.add(arg.id)
        return out

    def _linear_reuse(
        self, f: SourceFile, fi: cg.FunctionInfo, uses: Dict[str, List[_Use]]
    ) -> Iterator[Finding]:
        for var, events in uses.items():
            consumed_at: Optional[ast.AST] = None
            for u in events:
                if u.kind == "rebind":
                    consumed_at = None
                elif u.kind == "consume":
                    if consumed_at is not None:
                        yield self.finding(
                            f,
                            u.node,
                            f"PRNG key {var!r} reused: already "
                            "consumed at line "
                            f"{getattr(consumed_at, 'lineno', '?')} "
                            "with no split/fold_in re-binding in "
                            "between — both ops see identical "
                            "randomness",
                            symbol=f"reuse:{fi.qname}:{var}",
                        )
                        break  # one finding per var per function
                    consumed_at = u.node
                elif u.kind == "return" and consumed_at is not None:
                    yield self.finding(
                        f,
                        u.node,
                        f"PRNG key {var!r} returned after being "
                        "consumed — the caller inherits a hot key; "
                        "return a fresh split instead",
                        symbol=f"return-hot:{fi.qname}:{var}",
                    )
                    break

    def _loop_reuse(
        self,
        f: SourceFile,
        fi: cg.FunctionInfo,
        stmts: List[ast.stmt],
        key_vars: Set[str],
    ) -> Iterator[Finding]:
        flagged: Set[str] = set()
        for stmt in stmts:
            if not isinstance(stmt, (ast.For, ast.While)):
                continue
            body_nodes = list(self._walk_no_defs(stmt))
            rebound: Set[str] = set()
            loop_defined: Set[str] = set()
            if isinstance(stmt, ast.For):
                loop_defined |= self._target_names(stmt.target)
            for node in body_nodes:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        names = self._target_names(t)
                        rebound |= names & key_vars
                        loop_defined |= names
            for node in body_nodes:
                if not isinstance(node, ast.Call):
                    continue
                for v in self._consumed_keys(node, key_vars):
                    if v in rebound or v in loop_defined or v in flagged:
                        continue
                    flagged.add(v)
                    yield self.finding(
                        f,
                        node,
                        f"PRNG key {v!r} consumed inside a loop that "
                        "never re-binds it — every iteration reuses "
                        "the same randomness; split per iteration "
                        "(key, sub = jax.random.split(key))",
                        symbol=f"loop-reuse:{fi.qname}:{v}",
                    )
        return
