"""tpulint — JAX/TPU-aware static analysis for the tpufw tree.

``python -m tpufw.analysis [paths...]`` runs five domain rules no
generic linter can express (see docs/ANALYSIS.md for the catalog):

- TPU001 hot-loop purity: no host syncs in traced code or step loops
- TPU002 mesh/axis consistency: collective + PartitionSpec axis
  literals must resolve to declared mesh axes
- TPU003 RNG-key discipline: no reused / hot-returned PRNG keys
- TPU004 env-var registry: TPUFW_* knobs round-trip through
  tpufw.workloads.env and docs/ENV.md
- TPU005 obs-name hygiene: event kinds and metric names match the
  schema and the documented catalog

Stdlib-only (``ast``); importing this package never imports jax, so
the lint runs in bare CI containers and pre-commit hooks.
"""

from tpufw.analysis.core import (  # noqa: F401
    Checker,
    Finding,
    Project,
    all_checkers,
    load_baseline,
    run_analysis,
    split_by_baseline,
    write_baseline,
)

__all__ = [
    "Checker",
    "Finding",
    "Project",
    "all_checkers",
    "load_baseline",
    "run_analysis",
    "split_by_baseline",
    "write_baseline",
]
