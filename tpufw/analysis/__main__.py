"""CLI for tpulint: ``python -m tpufw.analysis [paths...]``.

Exit codes: 0 = clean (or everything baselined), 1 = new findings,
2 = usage error. With no paths the default scan set is the library,
the scripts, and bench.py. ``analysis_baseline.json`` at the repo
root is applied automatically when present (``--no-baseline`` for
the raw view); the baseline may only shrink — regenerate it with
``--write-baseline`` only to *remove* fixed entries.

``--sarif out.sarif`` additionally writes the gating findings as
SARIF 2.1.0 for GitHub code scanning. ``--cache`` enables the
whole-scan replay cache (see :mod:`tpufw.analysis.incremental`), and
``--since <ref>`` gates the exit code on findings in files changed
since ``ref`` — the pre-commit fast path.

``--layer {python,deploy,protocol,lifetime,all}`` (default ``all``)
selects the scan set: ``python`` is the stdlib-only ast rules
(TPU001-009), ``deploy`` parses ``deploy/`` and runs the cross-layer
rules (TPU010-014, requires pyyaml), ``protocol`` runs the
distributed-protocol rules (TPU015-018) over the python scan set,
``lifetime`` runs the resource-lifetime/concurrency-liveness rules
(TPU019-022) over the same set, ``all`` runs everything — degrading past the deploy half with a
stderr notice when pyyaml is missing. When ``--layer`` is not given,
``TPUFW_LINT_LAYERS`` (a comma list, e.g. ``python,protocol``) picks
the default instead — findings from the listed layers are merged and
deduplicated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from tpufw.analysis import core, incremental

DEFAULT_BASELINE = "analysis_baseline.json"


def _default_paths(root: str) -> List[str]:
    out = []
    for p in ("tpufw", "scripts", "bench.py"):
        full = os.path.join(root, p)
        if os.path.exists(full):
            out.append(full)
    return out


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpufw.analysis",
        description="tpulint: JAX/TPU-aware static analysis",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: tpufw scripts bench.py)",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--rules",
        help="comma-separated rule subset (e.g. TPU001,TPU004)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--layer",
        choices=core.LAYERS,
        default=None,
        help=(
            "scan layer: python = ast rules over .py files, deploy = "
            "TPU010-014 over deploy/ (needs pyyaml), protocol = "
            "TPU015-018 wire/SPMD contracts over .py files, lifetime "
            "= TPU019-022 resource-lifetime/liveness rules over .py "
            "files, all = everything (default; deploy half skipped "
            "with a notice if pyyaml is missing). Unset, "
            "TPUFW_LINT_LAYERS (comma list) picks the default"
        ),
    )
    ap.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE} if present)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline; report every finding",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="PATH",
        help="write current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--sarif",
        metavar="PATH",
        help="also write gating findings as SARIF 2.1.0",
    )
    ap.add_argument(
        "--cache",
        nargs="?",
        const=incremental.DEFAULT_CACHE,
        default=None,
        metavar="PATH",
        help=(
            "replay cache file (default "
            f"<root>/{incremental.DEFAULT_CACHE}); an exact "
            "signature hit skips the scan entirely"
        ),
    )
    ap.add_argument(
        "--manifest",
        action="append",
        metavar="PATH",
        default=None,
        help=(
            "additional manifest to verify with the deploy layer, on "
            "top of the deploy/ scan set (repeatable) — e.g. a fleet "
            "scaling-recommendation artifact; disables the replay "
            "cache for the run"
        ),
    )
    ap.add_argument(
        "--since",
        metavar="REF",
        help=(
            "gate the exit code only on findings in files changed "
            "since REF (committed or not); the full tree is still "
            "analyzed so cross-file rules stay sound"
        ),
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        checkers = core.all_checkers()
        by_layer: dict = {}
        for c in checkers:
            by_layer.setdefault(c.layer, []).append(c)
        # Present layers in the canonical LAYERS order so the output
        # is stable for tooling that diffs it.
        order = [l for l in core.LAYERS if l in by_layer]
        order += [l for l in by_layer if l not in order]
        for layer in order:
            print(f"layer {layer}:")
            for c in by_layer[layer]:
                print(f"  {c.rule}  {c.name}  [{c.severity}]")
        return 0

    root = core.find_repo_root(args.paths[0] if args.paths else ".")
    paths = args.paths or _default_paths(root)
    if not paths:
        print("tpulint: nothing to scan", file=sys.stderr)
        return 2
    if args.layer is not None:
        layers = [args.layer]
    else:
        from tpufw.workloads.env import env_str

        layers = [
            part.strip()
            for part in env_str("lint_layers", "all").split(",")
            if part.strip()
        ] or ["all"]
        for part in layers:
            if part not in core.LAYERS:
                print(
                    f"tpulint: TPUFW_LINT_LAYERS: unknown layer "
                    f"{part!r} (choices: {', '.join(core.LAYERS)})",
                    file=sys.stderr,
                )
                return 2
    layer_spec = ",".join(layers)
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    cache_path = None
    if args.cache is not None and not args.manifest:
        # Extra manifests live outside the scan signature's file set;
        # a cached replay would silently skip them.
        cache_path = (
            os.path.join(root, args.cache)
            if args.cache == incremental.DEFAULT_CACHE
            else args.cache
        )

    from tpufw.analysis import manifests

    if "all" in layers and not manifests.yaml_available():
        print(
            "tpulint: pyyaml not importable — deploy layer "
            "(TPU010-014) skipped; pip install pyyaml or use "
            "--layer python to silence this",
            file=sys.stderr,
        )

    findings = None
    signature = None
    if cache_path is not None:
        signature = incremental.scan_signature(
            root, core.iter_py_files(paths, root), rules,
            layer=layer_spec,
        )
        findings = incremental.load_cached(cache_path, signature)
        if findings is not None:
            print(
                f"tpulint: replayed {len(findings)} finding(s) from "
                f"cache {os.path.relpath(cache_path, root)}",
                file=sys.stderr,
            )
    if findings is None:
        try:
            findings = []
            seen = set()
            for layer in layers:
                for f in core.run_analysis(
                    paths, root=root, rules=rules, layer=layer,
                    extra_manifests=args.manifest,
                ):
                    # Layers overlap (TPU000 parse errors fire in
                    # every layer; "all" subsumes the rest) — one
                    # finding, one report.
                    k = (f.key(), f.line)
                    if k not in seen:
                        seen.add(k)
                        findings.append(f)
            if len(layers) > 1:
                findings.sort(key=lambda f: (f.path, f.line, f.rule))
        except ValueError as e:
            print(f"tpulint: {e}", file=sys.stderr)
            return 2
        if cache_path is not None and signature is not None:
            incremental.save_cache(cache_path, signature, findings)

    if args.write_baseline:
        core.write_baseline(args.write_baseline, findings)
        print(
            f"tpulint: wrote baseline with {len(findings)} finding(s) "
            f"to {args.write_baseline}"
        )
        return 0

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    baseline = set()
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = core.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"tpulint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, old, stale = core.split_by_baseline(findings, baseline)

    since_excluded = 0
    if args.since:
        changed = incremental.changed_files(root, args.since)
        if changed is None:
            print(
                f"tpulint: --since {args.since}: git could not "
                "resolve the ref; gating on all findings",
                file=sys.stderr,
            )
        else:
            kept = incremental.filter_since(new, changed)
            since_excluded = len(new) - len(kept)
            new = kept

    if args.sarif:
        from tpufw.analysis import sarif

        sarif.write_sarif(args.sarif, new)

    if args.json:
        # Tooling partitions results by layer without re-parsing rule
        # IDs; TPU000 parse errors belong to every layer -> "core".
        layer_of = {c.rule: c.layer for c in core.all_checkers()}

        def as_dict(f):
            d = f.as_dict()
            d["layer"] = layer_of.get(f.rule, "core")
            return d

        print(
            json.dumps(
                {
                    "findings": [as_dict(f) for f in new],
                    "baselined": [as_dict(f) for f in old],
                    "stale_baseline_keys": sorted(stale),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        if old:
            print(
                f"tpulint: {len(old)} pre-existing finding(s) tolerated "
                f"by baseline {os.path.relpath(baseline_path, root)}"
            )
        if stale:
            print(
                f"tpulint: {len(stale)} baseline entr"
                f"{'y is' if len(stale) == 1 else 'ies are'} no longer "
                "observed — shrink the baseline "
                "(python -m tpufw.analysis --write-baseline "
                f"{os.path.relpath(baseline_path, root)}):"
            )
            for k in sorted(stale):
                print(f"  stale: {k}")
        if since_excluded:
            print(
                f"tpulint: {since_excluded} finding(s) outside "
                f"--since {args.since} not gating this run"
            )
        if not new:
            print(
                f"tpulint: clean ({len(findings)} finding(s) total, "
                f"{len(old)} baselined)"
            )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
