"""TPU007: call sites that feed a jit unbounded host-varying values.

jax caches one compiled program per (static-arg values, dynamic-arg
shapes/dtypes) key. A call site that passes a *varying* Python value
into a static slot — or a *varying-shape* array into a dynamic slot —
recompiles on every distinct value, and each recompile is host-side
serialization of exactly the kind the concurrency paper (PAPERS.md)
identifies as the real TPU throughput ceiling. This repo defends the
invariant at runtime with TRACE_COUNTS assertions and bounds program
counts with pow2 chunk/cache ladders (``_pow2_ceil``,
``_cache_bucket`` in workloads/serve.py); TPU007 is the same contract
checked statically, before a run is burned discovering it.

A host value is "varying" when it is a loop target, or flows from
``len(...)`` / another varying name; it is "pinned" (not churn) the
moment it routes through a ladder/bucket call
(:data:`tpufw.analysis.dataflow.PIN_CALL_RE`). Shapes vary when an
array is built by a size-taking constructor or slice whose bound is a
varying value. Owner-function parameters and attributes are treated
as non-varying — one call site cannot see its callers, and the bias
throughout tpulint is false negatives over false positives.

Call sites already under trace (a jitted helper invoked from a jitted
step) are skipped: inner jits inline into the outer trace, so there
is no per-call recompile key to protect.
"""

from __future__ import annotations

from typing import Iterator

from tpufw.analysis import callgraph as cg
from tpufw.analysis import dataflow as df
from tpufw.analysis.core import Checker, Finding, Project


class RetraceChurnChecker(Checker):
    rule = "TPU007"
    name = "recompile-churn"
    severity = "warning"

    def check(self, project: Project) -> Iterator[Finding]:
        index = cg.ModuleIndex(project)
        sites = df.find_jit_sites(index, project.files)
        calls = df.find_call_sites(index, project.files, sites)
        roots = cg.find_traced_roots(index, project.files)
        traced = cg.reachable_functions(index, roots)
        envs: dict = {}
        for site in sites:
            if site.static_unparsed:
                continue
            for cs in calls.get(id(site), []):
                if cs.owner is None:
                    continue  # module top level runs once: no churn
                if id(cs.owner.node) in traced:
                    continue  # inner jit: inlined into the outer trace
                env = envs.get(id(cs.owner.node))
                if env is None:
                    env = df.VaryingEnv(cs.owner.node)
                    envs[id(cs.owner.node)] = env
                qname = site.display_name()
                for param, arg in cs.bound_args():
                    if site.is_static(param):
                        if env.expr_value_varying(arg):
                            yield self.finding(
                                cs.file,
                                cs.call,
                                f"call to jitted {qname!r} passes a "
                                f"host-varying value for static arg "
                                f"{param!r}: every distinct value "
                                "recompiles; pin it through a pow2 "
                                "ladder/bucket or drop it from "
                                "static_argnums",
                                symbol=f"static-churn:{qname}:{param}",
                            )
                    elif env.expr_shape_varying(arg):
                        yield self.finding(
                            cs.file,
                            cs.call,
                            f"call to jitted {qname!r} passes arg "
                            f"{param!r} whose shape varies per call "
                            "(unpinned size flows into its "
                            "constructor/slice): each new shape is a "
                            "fresh compile; bucket the size first",
                            symbol=f"shape-churn:{qname}:{param}",
                        )
