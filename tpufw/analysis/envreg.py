"""TPU004 — TPUFW_* environment-variable registry hygiene.

The manifest-is-the-config contract (YAML manifest -> env ->
dataclass, SURVEY.md §5) only holds if every ``TPUFW_*`` knob goes
through one choke point: the typed helpers in
``tpufw/workloads/env.py``. A raw ``os.environ.get("TPUFW_...")``
bypasses the type discipline (bool parsing, empty-string-means-off)
and — worse — invents knobs no manifest author can discover. The rule:

- every ``TPUFW_*`` read must round-trip through the env.py helpers
  (direct ``environ.get`` / ``getenv`` / subscript / ``in`` reads are
  flagged);
- every ``TPUFW_*`` name appearing in code must be documented in
  ``docs/ENV.md`` (the catalog) or another doc page;
- names documented in ``docs/ENV.md`` but absent from code are stale
  (warning);
- near-identical name pairs (edit distance 1) are probable typos
  (warning).

Writes (``os.environ["TPUFW_X"] = ...`` for subprocess setup, the
autotuner's set/restore dance) are not reads and are not flagged.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from tpufw.analysis import callgraph as cg
from tpufw.analysis.core import (
    Checker,
    Finding,
    Project,
    SourceFile,
    deploy_text_env_names,
)

ENV_HELPERS = {
    "env_str",
    "env_int",
    "env_float",
    "env_bool",
    "env_opt_int",
    "env_opt_str",
}
ENV_MODULE = "tpufw/workloads/env.py"
# Doc-page parsing is single-sourced in core.load_env_catalog (shared
# with TPU012); CATALOG_DOC stays as the name findings point at.
CATALOG_DOC = "docs/ENV.md"

_NAME_RE = re.compile(r"^TPUFW_[A-Z0-9_]+$")

# Receiver names that look like an environment mapping.
_ENVISH = {"environ", "env", "_env"}

# Name pairs at edit distance 1 that are genuinely distinct knobs,
# not typos. Extend deliberately; each entry should be obvious.
_NEAR_DUP_OK = {
    frozenset({"TPUFW_TOP_K", "TPUFW_TOP_P"}),
}


def _is_envish(node: ast.AST) -> bool:
    chain = cg.attr_chain(node)
    if chain is None:
        return False
    return bool(set(chain) & _ENVISH) or chain[-1] in ("getenv",)


def _edit_distance_1(a: str, b: str) -> bool:
    if a == b or abs(len(a) - len(b)) > 1:
        return False
    if len(a) > len(b):
        a, b = b, a
    if len(a) == len(b):
        return sum(x != y for x, y in zip(a, b)) == 1
    for i in range(len(b)):
        if a == b[:i] + b[i + 1:]:
            return True
    return False


class EnvRegistryChecker(Checker):
    rule = "TPU004"
    name = "env-var-registry"
    severity = "error"

    def check(self, project: Project) -> Iterator[Finding]:
        registered: Dict[str, Tuple[SourceFile, ast.AST]] = {}
        direct_reads: List[Tuple[SourceFile, ast.AST, str]] = []
        mentioned: Dict[str, Tuple[SourceFile, ast.AST]] = {}

        for f in project.files:
            if f.tree is None:
                continue
            is_env_module = f.relpath == ENV_MODULE
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call):
                    name = cg.call_name(node)
                    if name in ENV_HELPERS and node.args:
                        a0 = node.args[0]
                        if isinstance(a0, ast.Constant) and isinstance(
                            a0.value, str
                        ):
                            full = "TPUFW_" + a0.value.upper()
                            registered.setdefault(full, (f, a0))
                            mentioned.setdefault(full, (f, a0))
                        continue
                    # environ.get("TPUFW_X") / os.getenv("TPUFW_X")
                    if (
                        name in ("get", "getenv", "pop", "setdefault")
                        and _is_envish(node.func)
                        and node.args
                    ):
                        lit = self._tpufw_literal(node.args[0])
                        if lit and not is_env_module:
                            kind = (
                                "read"
                                if name in ("get", "getenv")
                                else name
                            )
                            if kind == "read":
                                direct_reads.append((f, node, lit))
                            mentioned.setdefault(lit, (f, node))
                elif isinstance(node, ast.Subscript) and _is_envish(
                    node.value
                ):
                    lit = self._tpufw_literal(node.slice)
                    if lit:
                        mentioned.setdefault(lit, (f, node))
                        if isinstance(
                            node.ctx, ast.Load
                        ) and not is_env_module:
                            direct_reads.append((f, node, lit))
                elif isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops
                ):
                    lit = self._tpufw_literal(node.left)
                    if (
                        lit
                        and node.comparators
                        and _is_envish(node.comparators[0])
                    ):
                        mentioned.setdefault(lit, (f, node))
                        if not is_env_module:
                            direct_reads.append((f, node, lit))
                elif isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    if _NAME_RE.match(node.value):
                        mentioned.setdefault(node.value, (f, node))

        for f, node, lit in direct_reads:
            yield self.finding(
                f,
                node,
                f"direct environment read of {lit!r} bypasses the "
                "typed tpufw.workloads.env helpers (env_str/env_int/"
                "env_bool/...) — route it through the registry or "
                "suppress with a justification",
                symbol=f"direct-read:{lit}",
            )

        catalog = project.env_catalog()
        for name in sorted(mentioned):
            if name not in catalog.doc_names:
                f, node = mentioned[name]
                yield self.finding(
                    f,
                    node,
                    f"{name} is not documented in {CATALOG_DOC} (or "
                    "any doc page) — every env knob must be "
                    "discoverable by a manifest author",
                    symbol=f"undocumented:{name}",
                )
        # "Stale" = cataloged but used neither in python code nor in
        # any deploy artifact (raw-text scan: works without pyyaml, so
        # chart-only knobs don't read as stale under --layer python).
        used = set(mentioned) | deploy_text_env_names(project.root)
        for name in sorted(catalog.catalog_names - used):
            yield Finding(
                rule=self.rule,
                path=CATALOG_DOC,
                line=1,
                col=1,
                message=(
                    f"{name} is documented in {CATALOG_DOC} but no "
                    "longer appears in code — stale catalog entry"
                ),
                severity="warning",
                symbol=f"stale-doc:{name}",
            )

        names = sorted(mentioned)
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                if frozenset({a, b}) in _NEAR_DUP_OK:
                    continue
                if _edit_distance_1(a, b):
                    f, node = mentioned[b]
                    yield self.finding(
                        f,
                        node,
                        f"{b} is one edit away from {a} — probable "
                        "typo'd duplicate knob",
                        symbol=f"near-duplicate:{a}~{b}",
                        severity="warning",
                    )

    @staticmethod
    def _tpufw_literal(node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _NAME_RE.match(node.value):
                return node.value
        return None
