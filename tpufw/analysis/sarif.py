"""SARIF 2.1.0 output for tpulint (``--sarif out.sarif``).

SARIF is the interchange format GitHub code scanning ingests
(``github/codeql-action/upload-sarif``), so tpulint findings show up
as PR annotations with the same identity the baseline ratchet uses:
the ``rule:path:symbol`` key is carried as a ``partialFingerprints``
entry, which lets code scanning track a finding across line drift
exactly like the baseline does.

Only the subset of the (large) SARIF spec that code scanning reads is
emitted: tool.driver with per-rule metadata, and one ``result`` per
finding with level, message, physical location, and fingerprint. URIs
are repo-relative with a SRCROOT base, which is what the uploader
expects when it resolves annotations against the checked-out tree.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from tpufw.analysis.core import Checker, Finding, all_checkers

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

# SARIF "level" vocabulary; tpulint's "info" maps to SARIF's "note".
_LEVELS = {"error": "error", "warning": "warning", "info": "note"}

_RULE_HELP = {
    "TPU000": "file failed to parse; nothing else can be checked",
    "TPU001": "host-side impurity inside the jitted hot loop",
    "TPU002": "mesh axis name not declared by tpufw/mesh",
    "TPU003": "jax PRNG key reuse / missing fold_in discipline",
    "TPU004": "workload env var missing from the env registry",
    "TPU005": "observability event/metric name drift",
    "TPU006": "jit updates a large input without donate_argnums: "
              "two copies of the buffer live across the call",
    "TPU007": "call-site Python value/shape varies per call without "
              "static_argnums or a pow2 ladder: recompile churn",
    "TPU008": "dtype drift across the jit boundary (dtype-less "
              "constructors, silent bf16/fp32 mixing, bf16 accums)",
    "TPU009": "shared mutable attribute accessed across the thread "
              "boundary without the owning lock",
    "TPU010": "deploy topology math broken: chip limits x workers vs "
              "gke-tpu-topology product vs chips-per-host vs mesh "
              "factorization disagree",
    "TPU011": "multi-host JobSet missing the env/downward-API inputs "
              "cluster bootstrap's tier detection needs",
    "TPU012": "TPUFW_* env assignment names an uncataloged knob or "
              "fails its docs/ENV.md type",
    "TPU013": "deploy config field unknown to the run-config "
              "dataclasses, or estimated footprint exceeds HBM",
    "TPU014": "chart template or manifest failed to render/parse — "
              "unverifiable deploy artifact",
    "TPU015": "wire-contract drift on a marked channel: key written "
              "never read, read never written, type mismatch, or an "
              "optional field read without a guard",
    "TPU016": "host-varying value (process_index, env, time, random, "
              "io) steers control flow that dominates a collective / "
              "jax.distributed call / jit dispatch — SPMD divergence",
    "TPU017": "HTTP surface drift: endpoint/status/header claimed by "
              "the smoke harness or docs but not served, or served "
              "but never claimed",
    "TPU018": "metric label carries an id-shaped value (trace/request/"
              "uuid): unbounded time-series cardinality",
    "TPU019": "resource lifetime: a path (raise, early return, "
              "swallowed except) exits with an acquired resource "
              "(pages, slots, inflight credits, tickets, file "
              "handles) unreleased and untransferred",
    "TPU020": "condition-variable discipline: wait() without a while-"
              "predicate loop, notify outside the owning lock, or "
              "predicate-state write with no reachable notify",
    "TPU021": "counter balance: a marked gauge increments on a path "
              "with no post-dominating decrement (or never decrements "
              "at all)",
    "TPU022": "single-flight donation window: donated-buffer leaves "
              "read between the marked dispatch and its "
              "block_until_ready / result rebind",
}


def _tool_rules(checkers: Sequence[Checker]) -> List[dict]:
    rules: List[dict] = [
        {
            "id": "TPU000",
            "name": "syntax-error",
            "shortDescription": {"text": _RULE_HELP["TPU000"]},
            "defaultConfiguration": {"level": "error"},
        }
    ]
    for c in checkers:
        rules.append(
            {
                "id": c.rule,
                "name": c.name,
                "shortDescription": {
                    "text": _RULE_HELP.get(c.rule, c.name)
                },
                "help": {
                    "text": f"See docs/ANALYSIS.md, section {c.rule}."
                },
                "defaultConfiguration": {
                    "level": _LEVELS.get(c.severity, "error")
                },
            }
        )
    return rules


def to_sarif(findings: Sequence[Finding]) -> dict:
    checkers = all_checkers()
    rules = _tool_rules(checkers)
    index: Dict[str, int] = {r["id"]: i for i, r in enumerate(rules)}
    results: List[dict] = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": _LEVELS.get(f.severity, "error"),
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": max(1, f.col),
                        },
                    }
                }
            ],
            # The baseline key doubles as the cross-commit identity
            # GitHub code scanning uses to dedupe across line drift.
            "partialFingerprints": {"tpulintKey/v1": f.key()},
        }
        if f.rule in index:
            res["ruleIndex"] = index[f.rule]
        results.append(res)
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpulint",
                        "organization": "tpufw",
                        "semanticVersion": "5.0.0",
                        "rules": rules,
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }


def write_sarif(path: str, findings: Sequence[Finding]) -> None:
    doc = to_sarif(findings)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
