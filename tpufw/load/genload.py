"""Deterministic traffic-replay generation for the load observatory.

Everything the serving stack gets judged against starts here: a
seeded, fully deterministic **offered-load schedule** — who asks,
when, with what prompt — that a thread-pool replay client then drives
through the router's real HTTP surface. Determinism is the whole
point: the same ``MixConfig`` (seed included) produces a byte-
identical arrival schedule and prompt set on every machine, so two
bench rungs, or the same rung before and after a code change, compare
A/B on *identical* traffic instead of on two different draws from the
same distribution.

The generator models the traffic shapes production LLM serving
actually sees:

- **arrival processes** — open-loop (arrivals do not wait for
  completions, so an overloaded server falls behind instead of
  silently throttling the benchmark): homogeneous Poisson, a
  two-state MMPP (Markov-modulated Poisson — calm/burst regimes with
  exponential dwell times), and a compressed diurnal envelope (one
  "day" of sinusoidal rate modulation squeezed into the run, sampled
  by thinning);
- **heavy-tailed lengths** — bounded Pareto prompt and output
  lengths (most requests short, a fat tail of long ones — the mix
  that makes naive FCFS scheduling fall over);
- **prefix sharing** — a configurable fraction of prompts open with
  one of a small pool of shared system-prompt prefixes, page-aligned
  so the radix tries and affinity router downstream see real reuse;
- **multi-tenant weight mixes** — tenants drawn by weight, so SLO
  attainment curves decompose per tenant;
- **sticky multi-turn sessions** — a fraction of requests continue
  an open session (same ``session`` id, previous turn's prompt
  extended), exercising the router's session→replica affinity.

The ``ReplayClient`` half records every request's lifecycle —
offered time, send, first token, done — as one schema'd record in
``load-trace.jsonl`` (``LOAD_TRACE_REQUIRED`` below; the reader is
torn-tolerant like every JSONL reader here). jax-free and stdlib
only, like the router it drives.
"""

# http: claims

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import math
import os
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpufw.workloads.env import env_float, env_int, env_str

#: Arrival processes ``MixConfig.process`` accepts.
ARRIVAL_PROCESSES = ("poisson", "mmpp", "diurnal")

#: Fields every load-trace record must carry (envelope included).
#: Extra fields (stages, trace, replica, error, ...) are allowed —
#: floor, not ceiling, same contract as the event log's SCHEMA.
LOAD_TRACE_REQUIRED = frozenset(
    {
        "ts_offered",
        "ts_sent",
        "ts_done",
        "tenant",
        "status",
        "rung",
        "offered_rps",
        "n_prompt",
        "max_new",
    }
)


def parse_tenant_weights(spec: str) -> Tuple[Tuple[str, float], ...]:
    """``"vip:3,batch:1"`` -> (("vip", 3.0), ("batch", 1.0)).
    Malformed entries are skipped (bad knob ≠ dead harness)."""
    out: List[Tuple[str, float]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.rpartition(":")
        if not sep:
            out.append((part, 1.0))
            continue
        try:
            out.append((name.strip(), float(w)))
        except ValueError:
            continue
    return tuple(out) or (("default", 1.0),)


@dataclasses.dataclass(frozen=True)
class MixConfig:
    """One reproducible traffic mix. Frozen: the config IS the
    traffic — hash it, log it, replay it."""

    seed: int = 0
    process: str = "poisson"  # poisson | mmpp | diurnal
    rate_rps: float = 4.0
    duration_s: float = 10.0
    #: (tenant, weight) pairs — tuple-of-tuples so the config stays
    #: hashable/frozen; order matters for determinism.
    tenants: Tuple[Tuple[str, float], ...] = (("default", 1.0),)
    #: Bounded-Pareto prompt lengths: len = min(cap, base * pareto(α)).
    prompt_len_base: int = 24
    prompt_len_alpha: float = 2.2
    prompt_len_cap: int = 96
    max_new_base: int = 6
    max_new_alpha: float = 2.2
    max_new_cap: int = 24
    vocab: int = 256
    #: Fraction of prompts opening with a shared prefix, drawn from a
    #: pool of ``n_prefixes`` fixed ``prefix_len``-token prefixes.
    prefix_ratio: float = 0.5
    prefix_len: int = 32
    n_prefixes: int = 4
    #: Fraction of requests that ride a sticky multi-turn session.
    session_ratio: float = 0.25
    session_turns: int = 3
    # MMPP: burst-state rate multiplier and mean state dwell time.
    mmpp_burst_factor: float = 6.0
    mmpp_dwell_s: float = 2.0
    # Diurnal: rate(t) = rate_rps * (1 + amp * sin(2πt/duration)).
    diurnal_amplitude: float = 0.8

    def __post_init__(self) -> None:
        if self.process not in ARRIVAL_PROCESSES:
            raise ValueError(
                f"unknown arrival process {self.process!r} "
                f"(one of {ARRIVAL_PROCESSES})"
            )
        if self.rate_rps <= 0 or self.duration_s <= 0:
            raise ValueError("rate_rps and duration_s must be > 0")

    @classmethod
    def from_env(cls) -> "MixConfig":
        """Build from the TPUFW_LOAD_* knobs (docs/ENV.md)."""
        return cls(
            seed=env_int("load_seed", 0),
            process=env_str("load_process", "poisson"),
            rate_rps=env_float("load_rate_rps", 4.0),
            duration_s=env_float("load_duration_s", 10.0),
            tenants=parse_tenant_weights(
                env_str("load_tenants", "default:1")
            ),
            prefix_ratio=env_float("load_prefix_ratio", 0.5),
            session_ratio=env_float("load_session_ratio", 0.25),
        )


@dataclasses.dataclass(frozen=True)
class Offered:
    """One offered request: WHEN (seconds from schedule start), WHO,
    and WHAT. ``session`` is "" for one-shot requests."""

    t: float
    tenant: str
    session: str
    prompt: Tuple[int, ...]
    max_new: int


# ------------------------------------------------- arrival processes


def _arrivals(cfg: MixConfig, rng: random.Random) -> List[float]:
    """Offered-time offsets in [0, duration_s), per ``cfg.process``.
    Open-loop by construction: times depend only on the seed, never
    on service behavior."""
    out: List[float] = []
    if cfg.process == "poisson":
        t = rng.expovariate(cfg.rate_rps)
        while t < cfg.duration_s:
            out.append(t)
            t += rng.expovariate(cfg.rate_rps)
        return out
    if cfg.process == "mmpp":
        # Two-state MMPP: exponential dwell in each state; arrivals
        # within a state are Poisson at that state's rate. The
        # exponential's memorylessness makes "re-draw at the state
        # boundary" exact, not an approximation.
        t, burst = 0.0, False
        state_end = rng.expovariate(1.0 / cfg.mmpp_dwell_s)
        while t < cfg.duration_s:
            rate = cfg.rate_rps * (
                cfg.mmpp_burst_factor if burst else 1.0
            )
            nxt = t + rng.expovariate(rate)
            if nxt >= state_end:
                t, burst = state_end, not burst
                state_end = t + rng.expovariate(1.0 / cfg.mmpp_dwell_s)
                continue
            t = nxt
            if t < cfg.duration_s:
                out.append(t)
        return out
    # diurnal: nonhomogeneous Poisson by thinning against the
    # envelope's peak rate — one compressed "day" per run.
    amp = max(0.0, min(1.0, cfg.diurnal_amplitude))
    peak = cfg.rate_rps * (1.0 + amp)
    t = rng.expovariate(peak)
    while t < cfg.duration_s:
        envelope = cfg.rate_rps * (
            1.0 + amp * math.sin(2.0 * math.pi * t / cfg.duration_s)
        )
        if rng.random() < envelope / peak:
            out.append(t)
        t += rng.expovariate(peak)
    return out


# --------------------------------------------------- prompt assembly


class _SessionBook:
    """Open sticky sessions per tenant. A continued turn reuses the
    session id and extends the previous turn's prompt — the shape the
    router's session affinity and the KV fabric's re-home path are
    built for."""

    def __init__(self, turns: int):
        self._turns = max(1, turns)
        self._open: Dict[str, List[Tuple[str, int, Tuple[int, ...]]]] = {}
        self._seq = 0

    def next_turn(
        self,
        tenant: str,
        rng: random.Random,
        fresh_prompt: Tuple[int, ...],
        vocab: int,
        cap: int,
    ) -> Tuple[str, Tuple[int, ...]]:
        book = self._open.setdefault(tenant, [])
        if book and rng.random() < 0.7:
            i = rng.randrange(len(book))
            sid, left, prior = book[i]
            grown = prior + tuple(
                rng.randrange(1, vocab) for _ in range(4)
            )
            grown = grown[:cap]
            if left <= 1:
                book.pop(i)
            else:
                book[i] = (sid, left - 1, grown)
            return sid, grown
        self._seq += 1
        sid = f"s-{tenant}-{self._seq}"
        book.append((sid, self._turns - 1, fresh_prompt))
        return sid, fresh_prompt


def _bounded_pareto(
    rng: random.Random, base: int, alpha: float, cap: int
) -> int:
    return max(1, min(cap, int(base * rng.paretovariate(alpha))))


def schedule(cfg: MixConfig) -> List[Offered]:
    """The deterministic offered-load schedule for ``cfg``. One
    ``random.Random(seed)`` consumed in a fixed order: same config ⇒
    byte-identical schedule (see ``schedule_digest``)."""
    rng = random.Random(cfg.seed)
    prefixes = [
        tuple(
            rng.randrange(1, cfg.vocab) for _ in range(cfg.prefix_len)
        )
        for _ in range(max(1, cfg.n_prefixes))
    ]
    names = [t for t, _w in cfg.tenants]
    weights = [max(1e-9, w) for _t, w in cfg.tenants]
    total_w = sum(weights)
    cum: List[float] = []
    acc = 0.0
    for w in weights:
        acc += w / total_w
        cum.append(acc)
    sessions = _SessionBook(cfg.session_turns)
    out: List[Offered] = []
    for t in _arrivals(cfg, rng):
        u = rng.random()
        tenant = names[-1]
        for i, edge in enumerate(cum):
            if u <= edge:
                tenant = names[i]
                break
        n_prompt = _bounded_pareto(
            rng, cfg.prompt_len_base, cfg.prompt_len_alpha,
            cfg.prompt_len_cap,
        )
        body = tuple(
            rng.randrange(1, cfg.vocab) for _ in range(n_prompt)
        )
        if rng.random() < cfg.prefix_ratio:
            pfx = prefixes[rng.randrange(len(prefixes))]
            body = (pfx + body)[: cfg.prompt_len_cap]
        max_new = _bounded_pareto(
            rng, cfg.max_new_base, cfg.max_new_alpha, cfg.max_new_cap
        )
        session = ""
        if rng.random() < cfg.session_ratio:
            session, body = sessions.next_turn(
                tenant, rng, body, cfg.vocab, cfg.prompt_len_cap
            )
        out.append(
            Offered(
                t=round(t, 6),
                tenant=tenant,
                session=session,
                prompt=body,
                max_new=max_new,
            )
        )
    return out


def schedule_digest(reqs: Sequence[Offered]) -> str:
    """sha256 of the canonical JSON encoding — the replayability
    fingerprint two runs of the same config must agree on, and the
    one BENCH_load.json echoes so a regression bisect can prove both
    arms saw identical traffic."""
    blob = json.dumps(
        [dataclasses.asdict(r) for r in reqs], sort_keys=True
    ).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


# ------------------------------------------------------- trace file


def validate_trace_record(rec: dict) -> None:
    """Raise ValueError unless ``rec`` is a well-formed load-trace
    line — emit-side validation, same stance as the event log."""
    missing = LOAD_TRACE_REQUIRED - rec.keys()
    if missing:
        raise ValueError(
            f"load-trace record missing fields {sorted(missing)}"
        )


def read_trace(path: str) -> List[dict]:
    """Parse ``load-trace.jsonl`` back into dicts. Torn-tolerant: a
    replay killed mid-write must not take the digest with it —
    unparseable or schema-short lines are dropped, whatever parses
    flows through."""
    out: List[dict] = []
    try:
        f = open(path, encoding="utf-8")
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail on an unclean shutdown
            if (
                isinstance(rec, dict)
                and not (LOAD_TRACE_REQUIRED - rec.keys())
            ):
                out.append(rec)
    return out


class TraceWriter:
    """Append-only, schema-validating ``load-trace.jsonl`` writer.
    Thread-safe — worker threads record completions concurrently —
    and flushed per record so a SIGKILLed sweep keeps everything but
    its torn final line."""

    def __init__(self, path: str):
        # resource: acquires file-handle
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f: Optional[io.TextIOWrapper] = open(  # noqa: SIM115
            path, "a", encoding="utf-8"
        )

    def append(self, rec: dict) -> None:
        validate_trace_record(rec)
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        # resource: releases file-handle
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ----------------------------------------------------- replay client


class ReplayClient:
    """Drives an offered-load schedule through a router's real HTTP
    surface from a thread pool, open-loop: the dispatcher sleeps to
    each request's offered time and hands it to a worker regardless
    of how far behind the server is. Every request becomes one
    load-trace record."""

    def __init__(
        self,
        base_url: str,
        trace: Optional[TraceWriter] = None,
        *,
        threads: int = 8,
        timeout_s: float = 120.0,
        rung: int = 0,
        offered_rps: float = 0.0,
    ):
        self.base = base_url.rstrip("/")
        self.trace = trace
        self.threads = max(1, int(threads))
        self.timeout_s = float(timeout_s)
        self.rung = int(rung)
        self.offered_rps = float(offered_rps)
        self.records: List[dict] = []
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, base_url: str, **kw) -> "ReplayClient":
        kw.setdefault("threads", env_int("load_threads", 8))
        return cls(base_url, **kw)

    def _one(self, r: Offered, t0_wall: float, t0_mono: float) -> dict:
        # The offered instant is schedule-relative; the dispatcher
        # already slept to it, so "sent" is now.
        ts_offered = round(t0_wall + r.t, 6)
        ts_sent = round(t0_wall + (time.monotonic() - t0_mono), 6)
        body = {
            "prompt": list(r.prompt),
            "max_new": r.max_new,
            "tenant": r.tenant,
        }
        if r.session:
            body["session"] = r.session
        req = urllib.request.Request(
            self.base + "/generate",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        reply: Dict[str, Any] = {}
        error = ""
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout_s
            ) as resp:
                status = resp.status
                reply = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            status = e.code
            try:
                reply = json.loads(e.read().decode("utf-8"))
            except (ValueError, OSError):
                reply = {}
            error = str(reply.get("error", ""))
        except (urllib.error.URLError, OSError, ValueError) as e:
            status = 0
            error = f"{type(e).__name__}: {e}"
        ts_done = round(t0_wall + (time.monotonic() - t0_mono), 6)
        rec: Dict[str, Any] = {
            "ts_offered": ts_offered,
            "ts_sent": ts_sent,
            "ts_done": ts_done,
            "tenant": r.tenant,
            "status": status,
            "rung": self.rung,
            "offered_rps": self.offered_rps,
            "n_prompt": len(r.prompt),
            "max_new": r.max_new,
        }
        if r.session:
            rec["session"] = r.session
        if status == 200:
            ttft = reply.get("ttft_s")
            tokens = reply.get("tokens") or []
            rec["n_tokens"] = len(tokens)
            rec["latency_s"] = round(ts_done - ts_sent, 6)
            if isinstance(ttft, (int, float)):
                rec["ttft_s"] = round(float(ttft), 6)
                # First token is router-observed (this client is not
                # streaming); per-token pace derives from the rest.
                rec["ts_first_token"] = round(ts_sent + float(ttft), 6)
                if len(tokens) > 1:
                    rec["tok_s"] = round(
                        (ts_done - ts_sent - float(ttft))
                        / (len(tokens) - 1),
                        6,
                    )
            if isinstance(reply.get("stages"), dict):
                rec["stages"] = reply["stages"]
            if reply.get("trace"):
                rec["trace"] = str(reply["trace"])
            if reply.get("replica"):
                rec["replica"] = str(reply["replica"])
        elif status == 429:
            rec["reason"] = "rejected"
        if error:
            rec["error"] = error
        if self.trace is not None:
            self.trace.append(rec)
        with self._lock:
            self.records.append(rec)
        return rec

    def run(self, reqs: Sequence[Offered]) -> dict:
        """Replay ``reqs`` (schedule order) open-loop; returns a
        summary dict. Blocks until every in-flight request lands."""
        t0_wall = time.time()
        t0_mono = time.monotonic()
        with ThreadPoolExecutor(max_workers=self.threads) as pool:
            futures = []
            for r in reqs:
                delay = r.t - (time.monotonic() - t0_mono)
                if delay > 0:
                    time.sleep(delay)
                futures.append(
                    pool.submit(self._one, r, t0_wall, t0_mono)
                )
            for fut in futures:
                fut.result()
        wall = time.monotonic() - t0_mono
        with self._lock:
            recs = list(self.records)
        completed = sum(1 for r in recs if r["status"] == 200)
        rejected = sum(1 for r in recs if r["status"] == 429)
        return {
            "offered": len(reqs),
            "completed": completed,
            "rejected": rejected,
            "errors": len(recs) - completed - rejected,
            "wall_s": round(wall, 6),
        }
