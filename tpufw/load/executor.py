"""Closed-loop scaling executor: recommendation → replica → recovery.

PR 17's ``ScalingRecommender`` turns sustained burn into a patched
manifest and a ``fleet_recommendation`` event — and then the loop
dangles, because nothing in-tree *applies* the decision. This module
closes it for the in-process gang: ``GangExecutor`` subscribes to the
recommender's decision stream and translates each pool delta into
real replicas — spawning a fresh engine via a caller-provided factory
and registering it with the router on scale-up, draining (PR 19's
session-safe drain path) and deregistering on scale-in. Prefill and
decode pools scale independently, the disaggregation dividend.

Every step is stamped as a schema'd ``scale_action`` event so the
whole causal chain is reconstructable from the event log alone:
burn-rate alert (``fleet_alert``) → decision
(``fleet_recommendation``, with its burn-rate-at-decision) → action
(``scale_action`` add/remove, carrying the decision timestamp) →
observed recovery (``scale_action`` action="recovered", once the fast
burn window falls back under 1.0). The obs_summary load digest
renders that chain as a timeline.

Safety rails: the executor only ever removes replicas *it* spawned
(LIFO), so the base gang survives any recommendation storm, and a
scale-down with nothing of its own to remove records an explicit
``skipped`` action instead of guessing.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

#: Factory signature: given a replica name, build + return a replica
#: client (e.g. a LocalReplica over a fresh engine) ready to serve.
SpawnFn = Callable[[str], object]


class GangExecutor:
    """Applies ScalingRecommender decisions to an in-process gang."""

    def __init__(
        self,
        router,
        *,
        spawn: Dict[str, SpawnFn],
        events=None,
        slo=None,
        burn_window: Optional[str] = None,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.router = router
        self.spawn = dict(spawn)
        self.events = events
        self.slo = slo
        #: Burn-rate window to judge decisions/recovery by; None means
        #: the tracker's fastest window (max_burn default).
        self.burn_window = burn_window
        self._wall = wall_clock
        self._lock = threading.Lock()
        #: Replicas this executor spawned, per pool — the only ones
        #: it is allowed to remove, newest first out.
        self.spawned: Dict[str, List[object]] = {}
        #: Applied/skipped/recovered action records, oldest first.
        self.actions: List[dict] = []
        self._seq = 0
        #: Decision ts of the last scale-up still awaiting observed
        #: burn-rate recovery (None once recovered).
        self._awaiting: Optional[dict] = None

    # ----------------------------------------------------- wiring

    def subscribe(self, recommender) -> None:
        """Attach to a ScalingRecommender's decision stream."""
        recommender.listeners.append(self.on_decision)

    # ----------------------------------------------------- helpers

    def _burn(self) -> Optional[float]:
        if self.slo is None:
            return None
        try:
            return self.slo.max_burn(self.burn_window)
        except Exception:
            return None

    def _emit(self, *, pool: str, action: str, replica: str, **extra):
        rec = {
            "pool": pool,
            "action": action,
            "replica": replica,
            "ts": round(self._wall(), 3),
            **extra,
        }
        burn = self._burn()
        if burn is not None:
            rec["burn"] = round(burn, 4)
        with self._lock:
            self.actions.append(rec)
        if self.events is not None:
            self.events.emit("scale_action", **rec)
        return rec

    # ----------------------------------------------------- actions

    def on_decision(self, decision: dict) -> None:
        """Recommender listener: apply each pool's delta. Exceptions
        are contained per pool — a failed prefill spawn must not
        strand the decode delta."""
        ts = decision.get("ts")
        reason = decision.get("reason", "")
        for pool, move in sorted(decision.get("pools", {}).items()):
            delta = int(move["to"]) - int(move["from"])
            try:
                if delta > 0:
                    for _ in range(delta):
                        self._scale_up(pool, ts, reason)
                elif delta < 0:
                    for _ in range(-delta):
                        self._scale_down(pool, ts, reason)
            except Exception as e:
                self._emit(
                    pool=pool,
                    action="error",
                    replica="",
                    decision_ts=ts,
                    error=f"{type(e).__name__}: {e}",
                )

    def _scale_up(self, pool: str, decision_ts, reason: str) -> None:
        factory = self.spawn.get(pool)
        if factory is None:
            self._emit(
                pool=pool,
                action="skipped",
                replica="",
                decision_ts=decision_ts,
                why="no spawn factory for pool",
            )
            return
        with self._lock:
            self._seq += 1
            name = f"{pool}-auto{self._seq}"
        client = factory(name)
        self.router.add_replica(client, pool)
        with self._lock:
            self.spawned.setdefault(pool, []).append(client)
        rec = self._emit(
            pool=pool,
            action="add",
            replica=name,
            decision_ts=decision_ts,
            reason=reason,
        )
        with self._lock:
            self._awaiting = {
                "pool": pool,
                "replica": name,
                "decision_ts": decision_ts,
                "action_ts": rec["ts"],
            }

    def _scale_down(self, pool: str, decision_ts, reason: str) -> None:
        with self._lock:
            own = self.spawned.get(pool) or []
            client = own.pop() if own else None
        if client is None:
            # Never touch the base gang: nothing of ours to remove.
            self._emit(
                pool=pool,
                action="skipped",
                replica="",
                decision_ts=decision_ts,
                why="no executor-spawned replica in pool",
            )
            return
        name = getattr(client, "name", "")
        self.router.remove_replica(name, drain=True)
        close = getattr(client, "close", None)
        if callable(close):
            close()
        self._emit(
            pool=pool,
            action="remove",
            replica=name,
            decision_ts=decision_ts,
            reason=reason,
        )

    # ----------------------------------------------------- recovery

    def poll_recovery(self) -> Optional[dict]:
        """Close the causal chain: after a scale-up, once the fast
        burn window drops back under 1.0 (burning slower than budget)
        stamp a ``recovered`` scale_action linking back to the
        decision. Call from the smoke/sweep loop after each scrape;
        returns the action record when recovery is observed."""
        with self._lock:
            awaiting = self._awaiting
        if awaiting is None:
            return None
        burn = self._burn()
        if burn is None or burn >= 1.0:
            return None
        with self._lock:
            self._awaiting = None
        return self._emit(
            pool=awaiting["pool"],
            action="recovered",
            replica=awaiting["replica"],
            decision_ts=awaiting["decision_ts"],
            recovery_s=round(self._wall() - awaiting["action_ts"], 3),
        )

    # ----------------------------------------------------- teardown

    def close(self) -> None:
        """Drain and remove every replica this executor spawned —
        newest first, per pool. Idempotent."""
        with self._lock:
            pools = {p: list(cs) for p, cs in self.spawned.items()}
            self.spawned = {}
        for pool, clients in sorted(pools.items()):
            for client in reversed(clients):
                name = getattr(client, "name", "")
                try:
                    self.router.remove_replica(name, drain=True)
                except Exception:
                    pass
                close = getattr(client, "close", None)
                if callable(close):
                    try:
                        close()
                    except Exception:
                        pass
                self._emit(pool=pool, action="remove", replica=name)
