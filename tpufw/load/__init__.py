"""Load observatory: deterministic traffic replay, capacity-frontier
sweeps, and the closed-loop scaling executor. jax-free, like the
router it drives — importable on a laptop, a CI runner, or a TPU host
without pulling in the training stack."""

from tpufw.load.genload import (  # noqa: F401
    LOAD_TRACE_REQUIRED,
    MixConfig,
    Offered,
    ReplayClient,
    TraceWriter,
    parse_tenant_weights,
    read_trace,
    schedule,
    schedule_digest,
    validate_trace_record,
)
from tpufw.load.sweep import (  # noqa: F401
    SweepConfig,
    detect_knee,
    rung_stats,
    run_sweep,
)
from tpufw.load.executor import GangExecutor  # noqa: F401
