"""Operator CLI for the load observatory.

    python -m tpufw.load sweep --base-url http://router:8080 \
        --rungs 1,2,4,8 --hold-s 10 --out BENCH_load.json

Replays the TPUFW_LOAD_* mix (docs/ENV.md) against a live router at
each rung and writes the BENCH_load payload. The in-process hooks
(SLO phase stamps, fleet joins, executor) are only available when the
sweep shares a process with the gang — bench.py's ``load`` tier and
scripts/load_smoke.py do that; this CLI is the remote-router case.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpufw.load.genload import MixConfig, TraceWriter
from tpufw.load.sweep import SweepConfig, run_sweep, write_payload
from tpufw.workloads.env import env_opt_str


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpufw.load")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sw = sub.add_parser("sweep", help="run a capacity sweep")
    sw.add_argument("--base-url", required=True)
    sw.add_argument(
        "--rungs", default="1,2,4,8",
        help="comma-separated offered rps per rung",
    )
    sw.add_argument("--hold-s", type=float, default=6.0)
    sw.add_argument("--settle-s", type=float, default=1.0)
    sw.add_argument("--goal", type=float, default=0.99)
    sw.add_argument("--ttft-target-s", type=float, default=2.0)
    sw.add_argument("--tok-target-s", type=float, default=0.2)
    sw.add_argument("--threads", type=int, default=8)
    sw.add_argument("--out", default="BENCH_load.json")
    args = ap.parse_args(argv)

    mix = MixConfig.from_env()
    sweep = SweepConfig(
        rungs=tuple(
            float(r) for r in args.rungs.split(",") if r.strip()
        ),
        hold_s=args.hold_s,
        settle_s=args.settle_s,
        goal=args.goal,
        ttft_target_s=args.ttft_target_s,
        tok_target_s=args.tok_target_s,
        threads=args.threads,
    )
    trace_dir = env_opt_str("load_dir") or os.path.dirname(
        os.path.abspath(args.out)
    )
    trace = TraceWriter(os.path.join(trace_dir, "load-trace.jsonl"))
    try:
        payload = run_sweep(args.base_url, mix, sweep, trace=trace)
    finally:
        trace.close()
    write_payload(payload, args.out)
    print(json.dumps({"knee": payload["knee"]}, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
