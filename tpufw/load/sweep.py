"""Capacity-frontier sweep: step offered load, hold each rung to
steady state, find the knee.

The question every scaling decision hangs on is "how much load can
this gang take before the SLO goal slips?" — attainment as a function
of offered load, per tenant. The sweep answers it empirically: replay
the same seeded mix at increasing arrival rates (rungs), hold each
rung long enough to reach steady state, score every request against
its tenant's TTFT/per-token targets from the client side, and join
each rung's window with the router's ``tpufw_slo_*`` gauges and the
fleet observatory's derived series so the server-side view rides
along in the artifact.

The **knee** is the last rung whose overall attainment still meets
the SLO goal — the capacity frontier. Everything past it is load the
gang accepts but cannot serve within target, which is exactly the
regime the burn-rate autoscaling loop (executor.py) exists to escape.

Queueing-delay decomposition comes free: the router already returns
its TTFT stage breakdown (queue wait, prefill, first decode step) in
every response body, so per-rung stage means show *where* the added
latency lands as rungs climb — queue growth (admission-bound) reads
very differently from prefill growth (compute-bound).
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from tpufw.load.genload import (
    MixConfig,
    ReplayClient,
    TraceWriter,
    schedule,
    schedule_digest,
)

#: BENCH_load.json schema version.
SWEEP_SCHEMA = 1


@dataclasses.dataclass(frozen=True)
class SweepConfig:
    """The sweep plan: which rungs (offered rps), how long to hold
    each, how much of each hold to discard as warm-up, and what
    "good" means (TTFT / per-token targets, attainment goal)."""

    rungs: Tuple[float, ...] = (1.0, 2.0, 4.0, 8.0)
    hold_s: float = 6.0
    settle_s: float = 1.0
    goal: float = 0.99
    ttft_target_s: float = 2.0
    tok_target_s: float = 0.2
    #: Per-tenant (ttft_s, tok_s) target overrides.
    tenant_targets: Tuple[Tuple[str, Tuple[float, float]], ...] = ()
    threads: int = 8

    def targets_for(self, tenant: str) -> Tuple[float, float]:
        for name, tgt in self.tenant_targets:
            if name == tenant:
                return tgt
        return (self.ttft_target_s, self.tok_target_s)


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(
        len(sorted_vals) - 1, int(q / 100.0 * (len(sorted_vals) - 1))
    )
    return sorted_vals[i]


def _is_good(rec: dict, ttft_t: float, tok_t: float) -> bool:
    if rec.get("status") != 200:
        return False
    ttft = rec.get("ttft_s")
    if isinstance(ttft, (int, float)) and ttft > ttft_t:
        return False
    tok = rec.get("tok_s")
    if isinstance(tok, (int, float)) and tok > tok_t:
        return False
    return True


def rung_stats(
    records: Sequence[dict], sweep: SweepConfig, wall_s: float
) -> dict:
    """Score one rung's trace records. Attainment counts rejected and
    errored load *against* the tenant — a 429 is offered load the SLO
    did not serve, which is why the router's reject counter carries
    the tenant label (satellite fix this PR)."""
    tenants: Dict[str, List[dict]] = {}
    for rec in records:
        tenants.setdefault(str(rec.get("tenant", "")), []).append(rec)
    per_tenant: Dict[str, dict] = {}
    good_all = offered_all = 0
    goodput_tokens = 0
    stage_acc: Dict[str, List[float]] = {}
    for tenant, recs in sorted(tenants.items()):
        ttft_t, tok_t = sweep.targets_for(tenant)
        offered = len(recs)
        completed = sum(1 for r in recs if r["status"] == 200)
        rejected = sum(1 for r in recs if r["status"] == 429)
        good = sum(1 for r in recs if _is_good(r, ttft_t, tok_t))
        ttfts = sorted(
            float(r["ttft_s"])
            for r in recs
            if isinstance(r.get("ttft_s"), (int, float))
        )
        toks = sorted(
            float(r["tok_s"])
            for r in recs
            if isinstance(r.get("tok_s"), (int, float))
        )
        good_tokens = sum(
            int(r.get("n_tokens", 0))
            for r in recs
            if _is_good(r, ttft_t, tok_t)
        )
        per_tenant[tenant] = {
            "offered": offered,
            "completed": completed,
            "rejected": rejected,
            "errors": offered - completed - rejected,
            "good": good,
            "attainment": round(good / offered, 6) if offered else 1.0,
            "goodput_tok_s": (
                round(good_tokens / wall_s, 6) if wall_s > 0 else 0.0
            ),
            "ttft_p50_s": round(_percentile(ttfts, 50), 6),
            "ttft_p95_s": round(_percentile(ttfts, 95), 6),
            "tok_p50_s": round(_percentile(toks, 50), 6),
            "ttft_target_s": ttft_t,
            "tok_target_s": tok_t,
        }
        good_all += good
        offered_all += offered
        goodput_tokens += good_tokens
        for r in recs:
            for stage, v in (r.get("stages") or {}).items():
                if isinstance(v, (int, float)):
                    stage_acc.setdefault(str(stage), []).append(
                        float(v)
                    )
    return {
        "tenants": per_tenant,
        "attainment": (
            round(good_all / offered_all, 6) if offered_all else 1.0
        ),
        "offered": offered_all,
        "goodput_tok_s": (
            round(goodput_tokens / wall_s, 6) if wall_s > 0 else 0.0
        ),
        "stages_mean_s": {
            stage: round(sum(vs) / len(vs), 6)
            for stage, vs in sorted(stage_acc.items())
        },
    }


def detect_knee(rungs: Sequence[dict], goal: float) -> Optional[dict]:
    """The capacity frontier: the LAST rung whose overall attainment
    meets the goal. "Last" rather than "first failing minus one"
    because noisy middle rungs shouldn't hide real capacity above
    them; a monotone sweep gives the same answer either way."""
    knee = None
    for r in rungs:
        if r["attainment"] >= goal:
            knee = {
                "rung": r["rung"],
                "offered_rps": r["offered_rps"],
                "attainment": r["attainment"],
            }
    return knee


def _scrape_slo(base_url: str, timeout_s: float = 5.0) -> Dict[str, float]:
    """Snapshot the router's tpufw_slo_* gauges — the server-side SLO
    view joined into each rung record. Best-effort: a sweep against a
    router without an SLO tracker still produces curves."""
    from tpufw.obs import promtext

    try:
        with urllib.request.urlopen(
            base_url.rstrip("/") + "/metrics", timeout=timeout_s
        ) as resp:
            text = resp.read().decode("utf-8")
    except (OSError, ValueError):
        return {}
    return {
        k: v
        for k, v in promtext.flatten(text).items()
        if k.startswith("tpufw_slo_")
    }


def run_sweep(
    base_url: str,
    mix: MixConfig,
    sweep: SweepConfig,
    *,
    trace: Optional[TraceWriter] = None,
    events=None,
    slo=None,
    fleet_records: Optional[Sequence[dict]] = None,
) -> dict:
    """Run the full rung ladder against ``base_url`` and return the
    BENCH_load payload.

    ``events``/``slo`` are optional in-process hooks: when the sweep
    shares a process with the gang (bench, smoke), rung boundaries
    land in the event log as ``load_phase`` events and stamp the SLO
    tracker's phase so violations attribute to their rung.
    ``fleet_records`` (a SeriesStore read) joins each rung's window
    with the fleet's derived series.
    """
    from tpufw.obs import fleet as fleet_mod

    rungs_out: List[dict] = []
    for i, rate in enumerate(sweep.rungs):
        phase = f"rung-{i}"
        if events is not None:
            events.emit("load_phase", phase=phase)
        if slo is not None and hasattr(slo, "set_phase"):
            slo.set_phase(phase)
        # Per-rung seed derived from the mix seed: deterministic, but
        # rungs don't replay literally identical arrival gaps.
        cfg = dataclasses.replace(
            mix,
            seed=mix.seed + i,
            rate_rps=rate,
            duration_s=sweep.hold_s,
        )
        reqs = schedule(cfg)
        client = ReplayClient(
            base_url,
            trace,
            threads=sweep.threads,
            rung=i,
            offered_rps=rate,
        )
        t_start = time.time()
        summary = client.run(reqs)
        t_end = time.time()
        # Steady state only: drop the rung's warm-up head.
        cut = t_start + sweep.settle_s
        steady = [r for r in client.records if r["ts_offered"] >= cut]
        stats = rung_stats(steady, sweep, summary["wall_s"])
        rung = {
            "rung": i,
            "offered_rps": rate,
            "hold_s": sweep.hold_s,
            "schedule_digest": schedule_digest(reqs),
            "summary": summary,
            "slo_snapshot": _scrape_slo(base_url),
            **stats,
        }
        if fleet_records is not None:
            rung["fleet_window"] = fleet_mod.window_stats(
                fleet_records, t_start, t_end
            )
        rungs_out.append(rung)
    if slo is not None and hasattr(slo, "set_phase"):
        slo.set_phase("")
    if events is not None:
        events.emit("load_phase", phase="done")
    return {
        "bench": "load",
        "schema": SWEEP_SCHEMA,
        "mix": dataclasses.asdict(mix),
        "sweep": dataclasses.asdict(sweep),
        "goal": sweep.goal,
        "rungs": rungs_out,
        "knee": detect_knee(rungs_out, sweep.goal),
    }


def write_payload(payload: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
