"""TPUFW_* environment configuration helpers (manifest -> env -> dataclass)."""

from __future__ import annotations

import os


def _get(name: str):
    return os.environ.get(f"TPUFW_{name.upper()}")


def env_str(name: str, default: str) -> str:
    v = _get(name)
    return default if v is None else v


def env_int(name: str, default: int) -> int:
    v = _get(name)
    return default if v is None else int(v)


def env_opt_int(name: str, default: "int | None" = None) -> "int | None":
    """Optional int knob where None means "feature off" (e.g.
    TPUFW_METRICS_PORT). Unset -> default; set to the empty string ->
    None (a manifest's way to explicitly disable an inherited value)."""
    v = _get(name)
    if v is None:
        return default
    if v.strip() == "":
        return None
    return int(v)


def env_opt_str(name: str, default: "str | None" = None) -> "str | None":
    """Optional string knob where None means "feature off" (e.g.
    TPUFW_TELEMETRY_DIR). Unset -> default; set to the empty string ->
    None (a manifest's way to explicitly disable an inherited value)."""
    v = _get(name)
    if v is None:
        return default
    if v.strip() == "":
        return None
    return v


def env_float(name: str, default: float) -> float:
    v = _get(name)
    return default if v is None else float(v)


def env_bool(name: str, default: bool) -> bool:
    v = _get(name)
    if v is None:
        return default
    if v.lower() in ("1", "true", "yes", "on"):
        return True
    if v.lower() in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"TPUFW_{name.upper()}={v!r} is not a boolean")
