"""Smoke workload: the ``nvidia-smi``-in-a-pod analog (BASELINE configs 1-2).

The reference proves enablement by running ``nvidia-smi`` in a pod and
reading the device table from ``kubectl logs`` (reference README.md:303-335).
The TPU proof is the same shape: print ``jax.devices()`` and run a real
``jnp.matmul`` on them so the logs show both *enumeration* and *compute*.
Config 1 runs this with no accelerator request (CPU devices); config 2
requests ``google.com/tpu: 1`` and must show TpuDevice entries.
"""

from __future__ import annotations

import time

from tpufw.workloads.env import env_int


def main() -> int:
    from tpufw.cluster import initialize_cluster

    cluster = initialize_cluster()

    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    print(f"tpufw smoke: process {cluster.process_id}/{cluster.num_processes}"
          f" (source={cluster.source})")
    print(f"jax.devices() -> {devices}")
    print(f"platform: {devices[0].platform}  kind: {devices[0].device_kind}")

    import numpy as np

    n = env_int("smoke_matmul_dim", 4096)
    reps = env_int("smoke_matmul_reps", 20)
    # Scaled so repeated self-multiplication stays finite in bf16.
    x = (jax.random.normal(jax.random.key(0), (n, n)) / n).astype(jnp.bfloat16)
    f = jax.jit(lambda a: a @ a + a)
    checksum = float(np.asarray(f(x))[0, 0])  # compile + real sync
    # Chain the iterations (each consumes the last) and end on a
    # device-to-host read: runtimes that overlap/elide repeated identical
    # dispatches can't fake this, so the TFLOP/s line is honest.
    a = x
    t0 = time.perf_counter()
    # tpulint: disable=TPU016 — f is a per-host matmul on host-local
    # arrays (no collectives, no GSPMD sharding): hosts running different
    # rep counts finish at different times but cannot deadlock.
    for _ in range(reps):
        a = f(a)
    np.asarray(a[0, 0])
    dt = (time.perf_counter() - t0) / reps
    tflops = 2 * n**3 / dt / 1e12
    # "effective": includes per-dispatch/transfer overhead — this is a
    # does-the-chip-compute proof, not a peak benchmark (bench.py is that).
    print(f"matmul[{n}x{n}] checksum={checksum:.4f} "
          f"time={dt * 1e3:.2f}ms/iter ({tflops:.1f} effective TFLOP/s)")
    print("SMOKE OK: device enumerated and exercised")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
