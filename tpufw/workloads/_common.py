"""Shared scaffolding for the training workload entry points.

One copy of the JSON-lines telemetry channel (cold-start record + step
metrics — ``kubectl logs`` is the metrics surface, the reference's
verification pattern, reference README.md:331-335) so train_llama and
train_pipeline can't silently diverge.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

from tpufw.train.metrics import StepMetrics


def check_global_batch(batch_size: int, n_processes: int) -> int:
    """Global-batch contract: returns the LOCAL batch size per process."""
    if batch_size % n_processes:
        raise ValueError(
            f"global batch {batch_size} not divisible by "
            f"{n_processes} processes"
        )
    return batch_size // n_processes


def metrics_printer(
    t0: float, compile_cache: Optional[str]
) -> Callable[[StepMetrics], None]:
    """on_metrics callback: first call emits the cold-start->first-step
    record (BASELINE.md metric 2), every call emits the step JSON line."""
    first_step: dict = {}

    def on_metrics(m: StepMetrics) -> None:
        if not first_step:
            first_step["t"] = time.time()
            print(
                json.dumps(
                    {
                        "cold_start_to_first_step_s": round(
                            first_step["t"] - t0, 1
                        ),
                        "compile_cache": compile_cache or None,
                    }
                ),
                flush=True,
            )
        print(json.dumps(m.as_dict()), flush=True)

    return on_metrics


def resume_data_seed(base_seed: int, restored_step: int) -> int:
    """Data seed for a (possibly) resumed run.

    A restart resumes the OPTIMIZER at step N but a fresh data iterator
    would replay batches 1..N — the resumed run re-trains on data it
    already consumed and never sees the tail it skipped. Exact
    fast-forward would cost O(N) host-side packing, so tpufw makes the
    standard streaming-trainer trade instead: fold the restored step
    into the shuffle seed, giving the resumed run a FRESH permutation
    of the corpus. Not sample-exact resume, but no duplication bias,
    O(1), and deterministic given (seed, step).
    """
    if restored_step <= 0:
        return base_seed
    return base_seed + 1_000_003 * restored_step


def resolve_encode(tok_name: str):
    """Tokenizer selection shared by the SFT / DPO / RL data paths:
    "bytes" = the dependency-free byte tokenizer, anything else = a HF
    tokenizer name loaded context-free (no special-token injection, so
    span masks stay exact)."""
    if tok_name == "bytes":
        from tpufw.train.sft import byte_encode

        return byte_encode
    from transformers import AutoTokenizer

    _tok = AutoTokenizer.from_pretrained(tok_name)

    def encode(text):
        return _tok.encode(text, add_special_tokens=False)

    return encode


def report_preemption(trainer) -> None:
    """One JSON line when the run stopped on SIGTERM (the forced
    checkpoint is down; a clean exit lets the JobSet policy resume)."""
    if getattr(trainer, "preempted", False):
        print(
            json.dumps(
                {"preempted": True, "step": int(trainer.state.step)}
            ),
            flush=True,
        )


def report_telemetry(trainer) -> None:
    """One JSON line pointing at the run's telemetry artifacts
    (events.jsonl + trace.json under TPUFW_TELEMETRY_DIR) so log
    scrapers and CI can find them without knowing the env."""
    tel = getattr(trainer, "telemetry", None)
    if tel is not None and getattr(tel, "out_dir", None):
        print(
            json.dumps({"telemetry_dir": tel.out_dir}), flush=True
        )


def print_summary(history: list[StepMetrics]) -> None:
    if not history:
        return
    last = history[-1]
    print(
        f"TRAIN OK: {len(history)} steps, final loss {last.loss:.4f}, "
        f"{last.tokens_per_sec_per_chip:.0f} tok/s/chip, "
        f"MFU {last.mfu:.1%}"
    )
