"""ViT training workload — the MXU-native vision counterpart of
tpufw.workloads.train_resnet (same VisionTrainer, JSON step metrics to
pod logs, checkpoint/preemption contract; reference analog is the
log-visible device proof at reference README.md:303-335).

Env knobs (TPUFW_*): MODEL (vit_b16|vit_s16|vit_l16), BATCH_SIZE,
TOTAL_STEPS, plus the shared checkpoint/preemption set.
"""

from __future__ import annotations

import json

from tpufw.workloads.env import env_bool, env_int, env_str


def main() -> int:
    from tpufw.cluster import initialize_cluster
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()
    cluster = initialize_cluster()

    import dataclasses

    import jax

    from tpufw.models import VIT_CONFIGS, ViT
    from tpufw.train import (
        VisionTrainer,
        VisionTrainerConfig,
        synthetic_images,
    )

    name = env_str("model", "vit_b16")
    if name not in VIT_CONFIGS:
        raise SystemExit(
            f"TPUFW_MODEL={name!r} unknown; choose from "
            f"{sorted(VIT_CONFIGS)}"
        )
    mcfg = dataclasses.replace(
        VIT_CONFIGS[name],
        num_classes=env_int("num_classes", 1000),
        # Default to the PRESET's remat (True for the production
        # sizes: without it the layer scan saves every block's f32
        # [B,H,T,T] attention tensor — measured compile-OOM at ViT-B
        # batch 128 on one v5e chip). TPUFW_REMAT=0 overrides.
        remat=env_bool("remat", VIT_CONFIGS[name].remat),
    )
    cfg = VisionTrainerConfig(
        batch_size=env_int("batch_size", 256),
        image_size=mcfg.image_size,
        num_classes=mcfg.num_classes,
        total_steps=env_int("total_steps", 50),
        lr=env_int("lr_milli", 1) / 1000.0,
        checkpoint_dir=env_str("checkpoint_dir", "") or None,
        checkpoint_every=env_int("checkpoint_every", 100),
        handle_preemption=env_bool("handle_preemption", True),
        preemption_sync_every=env_int("preemption_sync_every", 1),
        sync_every=env_int("sync_every", 4),
    )
    print(
        f"tpufw train_vit[{name}]: process {cluster.process_id}/"
        f"{cluster.num_processes} devices={jax.devices()}"
    )
    trainer = VisionTrainer(ViT(mcfg), cfg)
    if trainer.maybe_restore():
        print(f"resumed from checkpoint at step {int(trainer.state.step)}")
    else:
        trainer.init_state(seed=env_int("seed", 0))

    history = trainer.run(
        synthetic_images(
            cfg.batch_size, cfg.image_size, cfg.num_classes,
            on_device=True,
        ),
        flops_per_image=mcfg.flops_per_image(),
        on_metrics=lambda m: print(json.dumps(m.as_dict()), flush=True),
    )
    from tpufw.workloads._common import report_preemption

    report_preemption(trainer)
    if history:
        last = history[-1]
        print(
            f"TRAIN OK: {len(history)} windows, final loss "
            f"{last.loss:.4f}, {last.tokens_per_sec_per_chip:.1f} "
            f"images/s/chip, MFU {last.mfu:.1%}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
