"""Pipeline-parallel LM training workload over the ``pipe`` axis.

The deploy-facing entry for tpufw.train.PipelineTrainer: same JSON-lines
metrics channel as train_llama (``kubectl logs`` is the telemetry
surface, the reference's verification pattern upgraded —
reference README.md:331-335), driven by TPUFW_* env:

  TPUFW_PIPE_STAGES (required, >1)   pipeline stages == mesh pipe size
  TPUFW_PIPE_MICROBATCHES (default 2*stages)
  TPUFW_PIPELINE_SCHEDULE            gpipe (default) | 1f1b |
                                     interleaved | zb1
  TPUFW_PIPELINE_VSTAGES             virtual stages v for interleaved
  TPUFW_PIPE_SCHEDULE                older spelling of the schedule
                                     knob (gpipe | 1f1b); the
                                     TPUFW_PIPELINE_* form wins
  TPUFW_MODEL / TPUFW_BATCH_SIZE / TPUFW_SEQ_LEN / ... (as train_llama)
  TPUFW_MESH_DATA / TPUFW_MESH_FSDP  data-parallel axes alongside pipe
  TPUFW_MESH_TENSOR / TPUFW_MESH_EXPERT  in-stage Megatron split /
                                     pipelined-MoE expert sharding

Data: synthetic batches; TPUFW_EVAL_EVERY > 0 adds the in-loop
held-out eval (forward-only pipeline, token-weighted loss/ppl JSON
lines). Packed batches (segment_ids + loss_mask) are supported — the
masks ride the pipe ring with their microbatch.
"""

from __future__ import annotations

import json
import time

from tpufw.workloads.env import (
    env_bool,
    env_float,
    env_int,
    env_opt_int,
    env_str,
)

_T0 = time.time()


def build_trainer():
    """(PipelineTrainer, model_cfg) from TPUFW_* env; import-light."""
    from tpufw.configs import bench_model_config
    from tpufw.mesh import MeshConfig
    from tpufw.models import GEMMA_CONFIGS, LLAMA_CONFIGS
    from tpufw.parallel.pipeline import PipelineConfig
    from tpufw.train import PipelineTrainer, TrainerConfig

    stages = env_int("pipe_stages", 0)
    if stages < 2:
        raise ValueError(
            f"TPUFW_PIPE_STAGES={stages}: pipeline training needs >= 2 "
            "stages (use tpufw.workloads.train_llama for pipe=1)"
        )
    from tpufw.models import MIXTRAL_CONFIGS

    name = env_str("model", "llama3_600m_bench")
    if name == "llama3_600m_bench":
        model_cfg = bench_model_config()
    elif name in LLAMA_CONFIGS:
        model_cfg = LLAMA_CONFIGS[name]
    elif name in GEMMA_CONFIGS:
        model_cfg = GEMMA_CONFIGS[name]
    elif name in MIXTRAL_CONFIGS:
        # Pipelined MoE: expert stacks shard over `expert` inside the
        # GPipe stages (pp x ep — tpufw.parallel.pipeline._moe_mlp).
        model_cfg = MIXTRAL_CONFIGS[name]
    else:
        raise ValueError(
            f"unknown TPUFW_MODEL={name!r} for pipeline training; choose "
            f"from {['llama3_600m_bench', *LLAMA_CONFIGS, *GEMMA_CONFIGS, *MIXTRAL_CONFIGS]}"
        )
    pipe = PipelineConfig(
        n_stages=stages,
        n_microbatches=env_int("pipe_microbatches", 2 * stages),
        # TPUFW_PIPELINE_SCHEDULE (full set: gpipe | 1f1b |
        # interleaved | zb1) wins over the older TPUFW_PIPE_SCHEDULE
        # spelling, which stays honored so existing manifests keep
        # working.
        schedule=env_str("pipeline_schedule", "")
        or env_str("pipe_schedule", "gpipe"),
        n_virtual=env_int("pipeline_vstages", 1),
    )
    trainer_cfg = TrainerConfig(
        batch_size=env_int("batch_size", 8),
        seq_len=env_int("seq_len", model_cfg.max_seq_len),
        total_steps=env_int("total_steps", 100),
        lr=env_float("lr", 3e-4),
        warmup_steps=env_int("warmup_steps", 10),
        log_every=env_int("log_every", 10),
        checkpoint_dir=env_str("checkpoint_dir", "") or None,
        checkpoint_every=env_int("checkpoint_every", 100),
        adam_mu_dtype=env_str("adam_mu_dtype", "") or None,
        # grad_accum is still READ so PipelineTrainer's loud
        # NotImplementedError fires on a configured-but-ignored knob
        # (microbatching IS the schedule; size n_microbatches instead).
        grad_accum=env_int("grad_accum", 1),
        loss_chunk_size=env_int("loss_chunk_size", 0) or None,
        loss_chunk_dtype=env_str("loss_chunk_dtype", "bfloat16"),
        profile_dir=env_str("profile_dir", "") or None,
        profile_start=env_int("profile_start", 3),
        profile_stop=env_int("profile_stop", 6),
        eval_every=env_int("eval_every", 0),
        eval_batches=env_int("eval_batches", 8),
        # Same SIGTERM-to-forced-checkpoint contract as train_llama.
        handle_preemption=env_bool("handle_preemption", True),
        preemption_sync_every=env_int("preemption_sync_every", 1),
        sync_every=env_int("sync_every", 1),
        # Unified telemetry (tpufw.obs) — same knobs as train_llama.
        telemetry_dir=env_str("telemetry_dir", "") or None,
        metrics_port=env_opt_int("metrics_port"),
        straggler_factor=env_float("straggler_factor", 2.0),
    )
    mesh_cfg = MeshConfig(
        data=env_int("mesh_data", 1),
        pipe=stages,
        fsdp=env_int("mesh_fsdp", -1),
        tensor=env_int("mesh_tensor", 1),
        expert=env_int("mesh_expert", 1),
    )
    return PipelineTrainer(model_cfg, pipe, trainer_cfg, mesh_cfg), model_cfg


def main() -> int:
    from tpufw.cluster import initialize_cluster
    from tpufw.utils.profiling import enable_compile_cache

    cache = enable_compile_cache()
    cluster = initialize_cluster()

    import jax

    from tpufw.train import synthetic_batches

    trainer, model_cfg = build_trainer()
    print(
        f"tpufw train_pipeline: process {cluster.process_id}/"
        f"{cluster.num_processes} devices={len(jax.devices())} "
        f"mesh={dict(trainer.mesh.shape)} "
        f"stages={trainer.pipe.n_stages} "
        f"microbatches={trainer.pipe.n_microbatches} "
        f"bubble={trainer.pipe.bubble_fraction():.1%} "
        f"params={model_cfg.n_params():,}"
        + (f" compile_cache={cache}" if cache else "")
    )

    resumed = trainer.maybe_restore()
    if resumed:
        print(f"resumed from checkpoint at step {int(trainer.state.step)}")
    else:
        trainer.init_state(seed=env_int("seed", 0))
    from tpufw.workloads._common import (
        check_global_batch,
        metrics_printer,
        print_summary,
        resume_data_seed,
    )

    # Fresh data permutation on resume (no replayed batches) — the
    # same contract as train_llama; see resume_data_seed. The EVAL
    # stream keeps the BASE seed: the held-out set must keep its
    # identity across restarts or eval_loss jumps spuriously.
    data_seed = resume_data_seed(
        env_int("data_seed", 0), int(trainer.state.step)
    )

    cfg = trainer.cfg
    local_bs = check_global_batch(cfg.batch_size, cluster.num_processes)
    # Held-out eval stream (TPUFW_EVAL_EVERY > 0 enables) — same disjoint
    # odd-seed space convention as train_llama.
    eval_data = None
    if cfg.eval_every:

        def eval_data():
            return synthetic_batches(
                local_bs, cfg.seq_len, model_cfg.vocab_size,
                # BASE seed: the held-out set keeps its identity
                # across restarts (only the TRAIN stream re-seeds).
                seed=env_int("data_seed", 0) * 2000
                + 2 * cluster.process_id + 1,
            )

    history = trainer.run(
        synthetic_batches(
            local_bs,
            cfg.seq_len,
            model_cfg.vocab_size,
            seed=data_seed * 2000 + 2 * cluster.process_id,
        ),
        model_flops_per_token=model_cfg.flops_per_token(cfg.seq_len - 1),
        on_metrics=metrics_printer(_T0, cache),
        eval_data=eval_data,
        on_eval=lambda ev: print(json.dumps(ev), flush=True),
    )
    from tpufw.workloads._common import (
        report_preemption,
        report_telemetry,
    )

    report_preemption(trainer)
    report_telemetry(trainer)
    print_summary(history)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
