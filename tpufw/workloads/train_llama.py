"""LM training workload (BASELINE configs 3-5): Llama-3 / Mixtral over a mesh.

One entry point covers single-chip through multi-host: the cluster bootstrap
no-ops when not distributed, the mesh axes come from TPUFW_MESH_* env vars,
and checkpoint-resume makes a JobSet gang restart transparent. Structured
step metrics (loss, tokens/sec/chip, MFU) stream to stdout as JSON lines —
``kubectl logs`` is the metrics channel, the reference's verification
pattern (README.md:331-335) upgraded from a device table to training
telemetry.
"""

from __future__ import annotations

import json
import time

from tpufw.workloads.env import (
    env_bool,
    env_float,
    env_int,
    env_opt_int,
    env_str,
)

# Import time ~= process start: the anchor for cold-start→first-step
# (BASELINE.md metric 2 — the reference's analog is its unmeasured
# Steps 1-9 wall clock, reference README.md:70-74).
_T0 = time.time()


def build_trainer():
    """Construct (trainer, model_cfg) from config layers. Import-light so
    tests can exercise config resolution without touching a backend.

    Precedence (lowest first): ``TPUFW_CONFIG`` YAML of record
    (tpufw.configs.loader, SURVEY.md §5) < ``TPUFW_*`` env vars — so a
    manifest points at the YAML and overrides only deployment-specifics.
    """
    import dataclasses

    from tpufw.configs import bench_model_config
    from tpufw.mesh import MeshConfig
    from tpufw.models import (
        DEEPSEEK_CONFIGS,
        Deepseek,
        GEMMA_CONFIGS,
        Gemma,
        LLAMA_CONFIGS,
        Llama,
        MIXTRAL_CONFIGS,
        Mixtral,
    )
    from tpufw.train import Trainer, TrainerConfig

    run = None
    cfg_path = env_str("config", "")
    if cfg_path:
        from tpufw.configs.loader import load_run_config

        run = load_run_config(cfg_path)
        if not isinstance(run.trainer, TrainerConfig):
            raise ValueError(
                f"{cfg_path}: preset {run.model_preset!r} is not an LM "
                "config; use tpufw.workloads.train_resnet for vision runs"
            )
    base_t = run.trainer if run else TrainerConfig()
    base_m = run.mesh if run else MeshConfig()

    name = env_str("model", run.model_preset if run else "llama3_600m_bench")
    def model_for(model_cfg):
        tname = type(model_cfg).__name__
        if "Mixtral" in tname:
            return Mixtral(model_cfg)
        if "Gemma" in tname:
            return Gemma(model_cfg)
        if "Deepseek" in tname:
            return Deepseek(model_cfg)
        return None  # Llama built after the backend override below

    if run and name == run.model_preset:
        model_cfg = run.model_cfg  # keeps the YAML's model.overrides
        model = model_for(model_cfg)
    elif name == "llama3_600m_bench":
        model_cfg, model = bench_model_config(), None
    elif name in LLAMA_CONFIGS:
        model_cfg, model = LLAMA_CONFIGS[name], None
    elif name in MIXTRAL_CONFIGS:
        model_cfg = MIXTRAL_CONFIGS[name]
        model = Mixtral(model_cfg)
    elif name in GEMMA_CONFIGS:
        model_cfg = GEMMA_CONFIGS[name]
        model = Gemma(model_cfg)
    elif name in DEEPSEEK_CONFIGS:
        model_cfg = DEEPSEEK_CONFIGS[name]
        model = Deepseek(model_cfg)
    else:
        raise ValueError(
            f"unknown TPUFW_MODEL={name!r}; choose from "
            f"{['llama3_600m_bench', *LLAMA_CONFIGS, *MIXTRAL_CONFIGS, *GEMMA_CONFIGS, *DEEPSEEK_CONFIGS]}"
        )
    backend = env_str("attention", "")
    if backend:
        model_cfg = dataclasses.replace(model_cfg, attention_backend=backend)
        model = None if model is None else type(model)(model_cfg)
    # TPUFW_MOE_DISPATCH=sorted: grouped ragged_dot expert matmuls
    # (2.26x the einsum dispatch on one v5e chip, docs/PERF.md) for
    # MoE configs training without expert-axis sharding; "einsum"
    # (default) is the EP-shardable path. Ignored by dense configs.
    moe_dispatch = env_str("moe_dispatch", "")
    if moe_dispatch and hasattr(model_cfg, "moe_dispatch"):
        model_cfg = dataclasses.replace(
            model_cfg, moe_dispatch=moe_dispatch
        )
        model = None if model is None else type(model)(model_cfg)
    # LoRA fine-tune: TPUFW_LORA_RANK > 0 adds adapters and freezes the
    # base (pairs with TPUFW_INIT_FROM pointing at a bare-params
    # checkpoint, e.g. an import_hf conversion).
    lora_rank = env_int("lora_rank", getattr(model_cfg, "lora_rank", 0))
    lora_alpha = env_float(
        "lora_alpha", getattr(model_cfg, "lora_alpha", 16.0)
    )
    if lora_rank and not hasattr(model_cfg, "lora_rank"):
        raise NotImplementedError(
            f"TPUFW_LORA_RANK: {type(model_cfg).__name__} does not "
            "implement LoRA adapters (the MLA family is full-fine-tune "
            "only today)"
        )
    if (lora_rank, lora_alpha) != (
        getattr(model_cfg, "lora_rank", 0),
        getattr(model_cfg, "lora_alpha", 16.0),
    ):
        model_cfg = dataclasses.replace(
            model_cfg, lora_rank=lora_rank, lora_alpha=lora_alpha
        )
        model = None if model is None else type(model)(model_cfg)
    if model is None:
        model = Llama(model_cfg)

    trainer_cfg = TrainerConfig(
        batch_size=env_int("batch_size", base_t.batch_size),
        seq_len=env_int(
            "seq_len",
            base_t.seq_len if run else model_cfg.max_seq_len,
        ),
        total_steps=env_int("total_steps", base_t.total_steps),
        lr=env_float("lr", base_t.lr if run else 3e-4),
        warmup_steps=env_int("warmup_steps", base_t.warmup_steps),
        log_every=env_int("log_every", base_t.log_every),
        checkpoint_dir=env_str("checkpoint_dir", base_t.checkpoint_dir or "")
        or None,
        checkpoint_every=env_int(
            "checkpoint_every", base_t.checkpoint_every if run else 100
        ),
        # 0/unset = full logits; >0 enables chunked-vocab CE.
        loss_chunk_size=env_int(
            "loss_chunk_size",
            (base_t.loss_chunk_size or 0) if run else 512,
        )
        or None,
        # "float32" restores exact full-logits numerics (slower head).
        loss_chunk_dtype=env_str("loss_chunk_dtype", base_t.loss_chunk_dtype),
        profile_dir=env_str("profile_dir", base_t.profile_dir or "") or None,
        profile_start=env_int("profile_start", base_t.profile_start),
        profile_stop=env_int("profile_stop", base_t.profile_stop),
        eval_every=env_int("eval_every", base_t.eval_every),
        eval_batches=env_int("eval_batches", base_t.eval_batches),
        grad_accum=env_int("grad_accum", base_t.grad_accum),
        adam_mu_dtype=env_str(
            "adam_mu_dtype", base_t.adam_mu_dtype or ""
        )
        or None,
        # Deployed pods handle SIGTERM by default: k8s termination →
        # forced final checkpoint → clean exit → JobSet restart resumes.
        handle_preemption=env_bool(
            "handle_preemption", base_t.handle_preemption
        ),
        preemption_sync_every=env_int(
            "preemption_sync_every", base_t.preemption_sync_every
        ),
        sync_every=env_int("sync_every", base_t.sync_every),
        # MFU autotuning (tpufw.tune): "cached" applies a persisted
        # winner, "search" measures candidates before the first step.
        autotune=env_str("autotune", base_t.autotune),
        autotune_budget_s=env_float(
            "autotune_budget_s", base_t.autotune_budget_s
        ),
        autotune_steps=env_int("autotune_steps", base_t.autotune_steps),
        # Unified telemetry (tpufw.obs): TPUFW_TELEMETRY_DIR writes
        # events.jsonl + trace.json per host; TPUFW_METRICS_PORT
        # serves Prometheus /metrics (unset = off, 0 = ephemeral).
        telemetry_dir=env_str(
            "telemetry_dir", base_t.telemetry_dir or ""
        ) or None,
        metrics_port=env_opt_int("metrics_port", base_t.metrics_port),
        straggler_factor=env_float(
            "straggler_factor", base_t.straggler_factor
        ),
    )
    if trainer_cfg.autotune not in ("off", "cached", "search"):
        raise ValueError(
            f"TPUFW_AUTOTUNE={trainer_cfg.autotune!r}: expected "
            "off | cached | search"
        )
    mesh_cfg = MeshConfig(
        data=env_int("mesh_data", base_m.data),
        fsdp=env_int("mesh_fsdp", base_m.fsdp),
        expert=env_int("mesh_expert", base_m.expert),
        sequence=env_int("mesh_sequence", base_m.sequence),
        tensor=env_int("mesh_tensor", base_m.tensor),
        # >1 = multi-slice: data parallelism across slices over DCN.
        dcn_data=env_int("mesh_dcn_data", base_m.dcn_data),
    )
    if (
        getattr(model_cfg, "moe_dispatch", "einsum") == "sorted"
        and mesh_cfg.expert not in (0, 1)
    ):
        # Silently defeating EP would be worse than refusing: the
        # sorted path's whole expert stacks would be all-gathered to
        # every device each layer under an expert-sharded mesh.
        raise ValueError(
            "moe_dispatch='sorted' keeps expert weight stacks whole "
            f"and cannot shard the expert mesh axis (got expert="
            f"{mesh_cfg.expert}); use the default einsum dispatch for "
            "expert parallelism"
        )
    # Objective selection: TPUFW_DPO_DATA switches to preference pairs
    # (DPOTrainer), TPUFW_DISTILL_TEACHER to teacher-student KL
    # (DistillTrainer); default is the LM objective. Mutually exclusive
    # — each replaces the loss, not the data alone.
    dpo_path = env_str("dpo_data", "")
    teacher_name = env_str("distill_teacher", "")
    if dpo_path and teacher_name:
        raise ValueError(
            "TPUFW_DPO_DATA and TPUFW_DISTILL_TEACHER are mutually "
            "exclusive objectives"
        )
    if dpo_path:
        from tpufw.train import DPOConfig, DPOTrainer

        trainer = DPOTrainer(
            model, trainer_cfg, mesh_cfg,
            dpo=DPOConfig(
                beta=env_float("dpo_beta", 0.1),
                label_smoothing=env_float("dpo_label_smoothing", 0.0),
            ),
        )
    elif teacher_name:
        from tpufw.train import DistillConfig, DistillTrainer

        trainer = DistillTrainer(
            model, trainer_cfg, mesh_cfg,
            distill=DistillConfig(
                temperature=env_float("distill_temperature", 2.0),
                alpha=env_float("distill_alpha", 0.5),
            ),
        )
    else:
        trainer = Trainer(model, trainer_cfg, mesh_cfg)
    return trainer, model_cfg


def main() -> int:
    from tpufw.cluster import initialize_cluster
    from tpufw.utils.profiling import enable_compile_cache

    # Before any compile: persistent XLA cache makes pod-restart recompiles
    # near-free (cold-start -> first-step, the BASELINE metric).
    cache = enable_compile_cache()
    cluster = initialize_cluster()

    import jax

    from tpufw.train import synthetic_batches

    trainer, model_cfg = build_trainer()
    print(
        f"tpufw train_llama: process {cluster.process_id}/"
        f"{cluster.num_processes} devices={len(jax.devices())} "
        f"mesh={dict(trainer.mesh.shape)} params={model_cfg.n_params():,}"
        + (f" compile_cache={cache}" if cache else "")
    )

    from tpufw.train import DPOTrainer as _DPOT

    init_from = env_str("init_from", "")
    if isinstance(trainer, _DPOT) and init_from:
        # DPO resume safety (mirrors rl.py's ordering): anchor the
        # reference snapshot to the ORIGINAL base weights BEFORE
        # restoring — maybe_restore() overwrites only policy/optimizer
        # state, so ref_params keeps the step-0 anchor and a pod
        # restart after the first checkpoint no longer crash-loops.
        trainer.init_from_params(init_from, seed=env_int("seed", 0))
        print(f"initialized params from {init_from}")
    resumed = trainer.maybe_restore()
    if resumed:
        print(f"resumed from checkpoint at step {int(trainer.state.step)}")
    elif trainer.state is None:
        if init_from:
            # Bare-params checkpoint (tpufw.tools.import_hf CLI output):
            # fine-tune from imported weights, fresh optimizer state.
            trainer.init_from_params(init_from, seed=env_int("seed", 0))
            print(f"initialized params from {init_from}")
        else:
            trainer.init_state(seed=env_int("seed", 0))

    from tpufw.workloads._common import (
        check_global_batch,
        metrics_printer,
        print_summary,
        resume_data_seed,
    )

    from tpufw.train.distill import DistillTrainer as _DT

    if isinstance(trainer, _DT):
        # Teacher preset + optional bare-params checkpoint; without a
        # checkpoint the teacher is RANDOM — only good for smoke tests,
        # so say so loudly.
        from tpufw.models import (
            DEEPSEEK_CONFIGS as _DC,
            GEMMA_CONFIGS as _GC,
            LLAMA_CONFIGS as _LC,
            MIXTRAL_CONFIGS as _MC,
            model_for_config,
        )

        t_name = env_str("distill_teacher", "")
        t_cfgs = {**_LC, **_MC, **_GC, **_DC}
        if t_name not in t_cfgs:
            raise ValueError(
                f"unknown TPUFW_DISTILL_TEACHER={t_name!r}; choose "
                f"from {sorted(t_cfgs)}"
            )
        t_cfg = t_cfgs[t_name]
        teacher = model_for_config(t_cfg)
        t_ckpt = env_str("distill_teacher_ckpt", "")
        if t_ckpt:
            trainer.set_teacher_from(teacher, t_ckpt)
            print(f"teacher {t_name} restored from {t_ckpt}")
        else:
            from flax.core import meta as _meta

            import jax.numpy as _jnp

            t_params = _meta.unbox(
                jax.jit(teacher.init)(
                    jax.random.key(env_int("seed", 0) + 1),
                    _jnp.zeros((2, 8), _jnp.int32),
                )["params"]
            )
            trainer.set_teacher(teacher, t_params)
            print(
                f"WARNING: teacher {t_name} is RANDOM-INIT (no "
                "TPUFW_DISTILL_TEACHER_CKPT) — smoke-test only"
            )

    cfg = trainer.cfg
    # Resumed runs get a FRESH data permutation (seed folded with the
    # restored step) instead of replaying consumed batches — see
    # resume_data_seed; the EVAL streams below keep the BASE seed so
    # the held-out set's identity survives restarts.
    data_seed = resume_data_seed(
        env_int("data_seed", 0), int(trainer.state.step)
    )
    flops_per_token = model_cfg.flops_per_token(cfg.seq_len - 1)
    if isinstance(trainer, _DT):
        # Teacher forward = 2N_t per token; flops_per_token is the 6N
        # train convention, so the forward is a third of the TEACHER's
        # own figure — without this, distill MFU undercounts real work
        # (the DPO branch makes the matching 4/3 correction).
        flops_per_token += (
            trainer.teacher_model.cfg.flops_per_token(cfg.seq_len - 1)
            / 3.0
        )
    # cfg.batch_size is GLOBAL; each process loads its local shard.
    n_proc = cluster.num_processes
    local_bs = check_global_batch(cfg.batch_size, n_proc)
    sft_path = env_str("sft_data", "")
    dpo_path = env_str("dpo_data", "")
    data_prefix = env_str("data_prefix", "")
    if dpo_path:
        # Preference pairs (tpufw.train.dpo): local rows = 2 * pairs;
        # interleaved layout keeps multi-process pairing correct.
        from tpufw.train import prefetch_to_device
        from tpufw.train.dpo import dpo_batches
        from tpufw.workloads._common import resolve_encode

        if local_bs % 2:
            raise ValueError(
                f"DPO local batch {local_bs} must be even (2 rows/pair)"
            )
        # The reference forward adds 2N FLOPs to the 6N train
        # convention (DPOTrainer docstring).
        flops_per_token = flops_per_token * 4.0 / 3.0
        data = prefetch_to_device(
            dpo_batches(
                dpo_path,
                local_bs // 2,
                cfg.seq_len,
                resolve_encode(env_str("sft_tokenizer", "bytes")),
                template=env_str("sft_template", "plain"),
                seed=data_seed,
                shard_id=cluster.process_id,
                num_shards=n_proc,
            ),
            trainer.mesh,
        )
    elif sft_path:
        # Supervised fine-tuning: JSONL conversations, chat-template
        # rendered, assistant-masked (tpufw.train.sft). Pairs with
        # TPUFW_INIT_FROM (imported base weights) + TPUFW_LORA_RANK.
        from tpufw.train.sft import sft_batches
        from tpufw.workloads._common import resolve_encode

        encode = resolve_encode(env_str("sft_tokenizer", "bytes"))

        from tpufw.train import prefetch_to_device

        data = prefetch_to_device(
            sft_batches(
                sft_path,
                local_bs,
                cfg.seq_len,
                encode,
                template=env_str("sft_template", "plain"),
                seed=data_seed,
                # Disjoint per-process conversation shards (same
                # contract as the TokenCorpus path below).
                shard_id=cluster.process_id,
                num_shards=n_proc,
            ),
            trainer.mesh,
        )
    elif data_prefix:
        # Real corpus (native/ mmap packer; TPUFW_DATA_PREFIX points at the
        # <prefix>.bin/.idx pair): disjoint per-process doc shards, H2D
        # transfer prefetched off the step path.
        from tpufw.train import TokenCorpus, prefetch_to_device

        data = prefetch_to_device(
            iter(
                TokenCorpus(
                    data_prefix, local_bs, cfg.seq_len,
                    shuffle=True, seed=data_seed,
                    shard_id=cluster.process_id, num_shards=n_proc,
                )
            ),
            trainer.mesh,
        )
    else:
        data = synthetic_batches(
            local_bs, cfg.seq_len, model_cfg.vocab_size,
            # Even seed space; the synthetic eval stream uses odd.
            seed=data_seed * 2000 + 2 * cluster.process_id,
        )
    # Held-out eval stream (TPUFW_EVAL_EVERY > 0 enables): a disjoint
    # corpus prefix when given, else synthetic batches from a disjoint
    # seed space (train seeds are even, eval seeds odd — no collision
    # for any TPUFW_DATA_SEED / process id).
    eval_data = None
    if cfg.eval_every:
        eval_prefix = env_str("eval_data_prefix", "")
        if eval_prefix:
            from tpufw.train import TokenCorpus

            def eval_data():
                return iter(
                    TokenCorpus(
                        eval_prefix, local_bs, cfg.seq_len,
                        shard_id=cluster.process_id, num_shards=n_proc,
                    )
                )
        else:

            def eval_data():
                return synthetic_batches(
                    local_bs, cfg.seq_len, model_cfg.vocab_size,
                    seed=env_int("data_seed", 0) * 2000
                    + 2 * cluster.process_id + 1,
                )

    history = trainer.run(
        data,
        model_flops_per_token=flops_per_token,
        on_metrics=metrics_printer(_T0, cache),
        eval_data=eval_data,
        on_eval=lambda ev: print(json.dumps(ev), flush=True),
    )
    from tpufw.workloads._common import (
        report_preemption,
        report_telemetry,
    )

    if trainer.last_tune is not None:
        # One JSON line, same channel as step metrics: the chosen
        # config and the tuning wall-clock, kubectl-logs greppable.
        print(
            json.dumps({"autotune": trainer.last_tune.summary()}),
            flush=True,
        )
    report_preemption(trainer)
    report_telemetry(trainer)
    print_summary(history)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
