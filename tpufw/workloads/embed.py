"""Embedding fine-tuning workload: contrastive pairs -> encoder, deployed.

The kubectl-apply shape of the other training workloads (reference
README.md:303-335's log-visible verification, retrieval edition):
``kubectl logs`` streams the InfoNCE loss JSON line per step (the
Trainer metrics channel carries the loss; per-step in-batch accuracy
stays internal), and the run ends with a cosine-similarity retrieval
probe — matched vs mismatched pair similarity, the log-visible proof
the embeddings separate.

Env surface (TPUFW_*):
  MODEL / INIT_FROM / SEED          — as train_llama (Llama-family)
  EMBED_DATA                        — JSONL {"query","positive"} pairs
  SFT_TOKENIZER                     — "bytes" (default) or a HF name
  POOLING                           — "mean" (default) | "last"
  BIDIRECTIONAL                     — 1 = LLM2Vec-style causal=False
                                      (requires sliding_window-free
                                      configs); default 0 (E5-style)
  TEMPERATURE                       — InfoNCE temperature (0.05)
  BATCH_SIZE (rows = 2*pairs) / SEQ_LEN / TOTAL_STEPS / LR / ...
  MESH_*                            — mesh axes, as train_llama
"""

from __future__ import annotations

import dataclasses
import json
import time

from tpufw.workloads.env import env_bool, env_float, env_int, env_str

_T0 = time.time()


def build_trainer():
    from tpufw.mesh import MeshConfig
    from tpufw.models import LLAMA_CONFIGS, Llama
    from tpufw.train import TrainerConfig
    from tpufw.train.contrastive import ContrastiveConfig, EmbeddingTrainer

    name = env_str("model", "llama3_tiny")
    if name not in LLAMA_CONFIGS:
        raise ValueError(
            f"unknown TPUFW_MODEL={name!r}; embedding workload "
            f"presets: {sorted(LLAMA_CONFIGS)}"
        )
    model_cfg = LLAMA_CONFIGS[name]
    if env_bool("bidirectional", False):
        model_cfg = dataclasses.replace(
            model_cfg, causal=False, sliding_window=None
        )
    trainer_cfg = TrainerConfig(
        batch_size=env_int("batch_size", 16),
        seq_len=env_int("seq_len", min(512, model_cfg.max_seq_len)),
        total_steps=env_int("total_steps", 100),
        lr=env_float("lr", 2e-5),
        warmup_steps=env_int("warmup_steps", 10),
        checkpoint_dir=env_str("checkpoint_dir", "") or None,
        checkpoint_every=env_int("checkpoint_every", 100),
        log_every=env_int("log_every", 1),
    )
    mesh_cfg = MeshConfig(
        data=env_int("mesh_data", 1),
        fsdp=env_int("mesh_fsdp", -1),
        tensor=env_int("mesh_tensor", 1),
    )
    trainer = EmbeddingTrainer(
        Llama(model_cfg), trainer_cfg, mesh_cfg,
        contrastive=ContrastiveConfig(
            temperature=env_float("temperature", 0.05),
            pooling=env_str("pooling", "mean"),
        ),
    )
    return trainer, model_cfg


def main() -> int:
    from tpufw.cluster import initialize_cluster
    from tpufw.utils.profiling import enable_compile_cache

    cache = enable_compile_cache()
    cluster = initialize_cluster()

    import numpy as np

    import jax

    trainer, model_cfg = build_trainer()
    print(
        f"tpufw embed: process {cluster.process_id}/"
        f"{cluster.num_processes} devices={len(jax.devices())} "
        f"mesh={dict(trainer.mesh.shape)} params={model_cfg.n_params():,}"
        f" pooling={trainer.contrastive.pooling}"
        f" causal={getattr(model_cfg, 'causal', True)}"
        + (f" compile_cache={cache}" if cache else "")
    )

    resumed = trainer.maybe_restore()
    if resumed:
        print(f"resumed from checkpoint at step {int(trainer.state.step)}")
    else:
        init_from = env_str("init_from", "")
        if init_from:
            trainer.init_from_params(init_from, seed=env_int("seed", 0))
            print(f"initialized params from {init_from}")
        else:
            trainer.init_state(seed=env_int("seed", 0))

    from tpufw.train.contrastive import pair_batches
    from tpufw.workloads._common import (
        check_global_batch,
        metrics_printer,
        report_preemption,
        resolve_encode,
        resume_data_seed,
    )

    cfg = trainer.cfg
    local_bs = check_global_batch(cfg.batch_size, cluster.num_processes)
    if local_bs % 2:
        raise ValueError(
            f"embedding local batch {local_bs} must be even (2 rows/pair)"
        )
    data_path = env_str("embed_data", "")
    if not data_path:
        raise ValueError(
            "TPUFW_EMBED_DATA is required: JSONL "
            '{"query": ..., "positive": ...} pairs'
        )
    encode = resolve_encode(env_str("sft_tokenizer", "bytes"))
    data = pair_batches(
        data_path,
        local_bs // 2,
        cfg.seq_len,
        encode,
        seed=resume_data_seed(
            env_int("data_seed", 0), int(trainer.state.step)
        ),
        shard_id=cluster.process_id,
        num_shards=cluster.num_processes,
    )
    # InfoNCE has no LM head: fwd+bwd over the trunk = 6N minus the
    # head's 6*D*V share. flops_per_token causal-halves the attention
    # score term; a bidirectional encoder attends all keys, so add the
    # halved term once more.
    flops = model_cfg.flops_per_token(
        cfg.seq_len - 1
    ) - 6.0 * model_cfg.d_model * model_cfg.vocab_size
    if not getattr(model_cfg, "causal", True):
        flops += model_cfg._attn_score_flops(cfg.seq_len - 1)
    history = trainer.run(
        data,
        model_flops_per_token=flops,
        on_metrics=metrics_printer(_T0, cache),
    )
    report_preemption(trainer)
    # Log-visible retrieval proof — single-process only: embed() runs
    # an eager forward on host-local arrays, which a multi-host mesh
    # rejects (the training loop above is the multi-process surface).
    if history and cluster.num_processes == 1:
        from tpufw.train.contrastive import _fit, read_pairs

        probe = []
        for i, p in enumerate(read_pairs(data_path)):
            if i >= 4:
                break
            probe.append(p)
        toks = np.zeros((2 * len(probe), cfg.seq_len), np.int32)
        seg = np.zeros_like(toks)
        for i, p in enumerate(probe):
            # _fit: the SAME length-based masking training used (a
            # (tokens != 0) mask would mis-mark a legitimate id-0
            # token under HF tokenizers).
            toks[2 * i], seg[2 * i] = _fit(
                encode(p["query"]), cfg.seq_len
            )
            toks[2 * i + 1], seg[2 * i + 1] = _fit(
                encode(p["positive"]), cfg.seq_len
            )
        emb = trainer.embed(toks, seg)
        sim = emb[0::2] @ emb[1::2].T
        print(json.dumps({
            "probe_sim_matched": round(float(np.diag(sim).mean()), 4),
            "probe_sim_mismatched": round(
                float(
                    (sim.sum() - np.diag(sim).sum())
                    / max(sim.size - len(probe), 1)
                ),
                4,
            ),
        }), flush=True)
    if history:
        print(
            f"EMBED OK: {len(history)} steps, final loss "
            f"{history[-1].loss:.4f}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
