"""GRPO RL fine-tuning workload: rollout -> reward -> update, deployed.

The same kubectl-apply shape as the other training workloads (reference
README.md:303-335 upgraded from a device table to RL telemetry):
``kubectl logs`` streams one JSON line per step with reward_mean,
clip_frac, kl, and loss.

Env surface (TPUFW_*):
  MODEL / INIT_FROM / SEED       — as train_llama
  PROMPTS_FILE                   — JSONL: {"prompt": <text>} or a bare
                                   token list per line (default: two
                                   built-in demo prompts)
  SFT_TOKENIZER                  — "bytes" (default) or a HF name, for
                                   text prompts
  REWARD                         — "low_token" (demo: fraction of ids
                                   < vocab/2), "length" (completion
                                   length / max_new), or "pkg.mod:fn"
                                   importing a custom
                                   fn(prompts, completions) -> [N]
  GRPO_GROUP / GRPO_CLIP / GRPO_KL_BETA / GRPO_TEMPERATURE /
  GRPO_MAX_NEW / EOS_ID          — GRPOConfig knobs
  BATCH_SIZE / SEQ_LEN / TOTAL_STEPS / LR / ... — TrainerConfig knobs
  MESH_*                         — mesh axes, as train_llama
"""

from __future__ import annotations

import json
import time

from tpufw.workloads.env import env_float, env_int, env_str

_T0 = time.time()

_DEMO_PROMPTS = [[7, 8, 9, 10], [11, 12, 13]]


def load_prompts(path: str, encode) -> list[list[int]]:
    """JSONL prompts: {"prompt": <text>} rows are tokenized; bare lists
    pass through as token ids."""
    prompts: list[list[int]] = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if isinstance(obj, dict) and "prompt" in obj:
                prompts.append(encode(obj["prompt"]))
            elif isinstance(obj, list) and all(
                isinstance(t, int) for t in obj
            ):
                prompts.append(obj)
            else:
                raise ValueError(
                    f"{path}:{ln}: expected {{'prompt': text}} or a "
                    "token-id list"
                )
    if not prompts:
        raise ValueError(f"{path}: no prompts")
    return prompts


def resolve_reward(spec: str, vocab_size: int, max_new: int):
    """Built-in demo rewards or an importable ``pkg.mod:fn``."""
    import numpy as np

    if spec == "low_token":
        half = vocab_size // 2

        def low_token(prompts, completions):
            return np.array([
                np.mean([t < half for t in c]) if c else 0.0
                for c in completions
            ])

        return low_token
    if spec == "length":

        def length(prompts, completions):
            return np.array(
                [len(c) / max_new for c in completions], np.float32
            )

        return length
    if ":" in spec:
        import importlib

        mod_name, fn_name = spec.split(":", 1)
        fn = getattr(importlib.import_module(mod_name), fn_name)
        if not callable(fn):
            raise TypeError(f"{spec} is not callable")
        return fn
    raise ValueError(
        f"TPUFW_REWARD={spec!r}: expected 'low_token', 'length', or an "
        "importable 'pkg.mod:fn'"
    )


def build_trainer():
    """(trainer, model_cfg) for the RL loop; import-light like
    train_llama.build_trainer."""
    from tpufw.mesh import MeshConfig
    from tpufw.models import LLAMA_CONFIGS, Llama
    from tpufw.train import TrainerConfig
    from tpufw.train.grpo import GRPOConfig, GRPOTrainer

    name = env_str("model", "llama3_tiny")
    if name not in LLAMA_CONFIGS:
        raise ValueError(
            f"unknown TPUFW_MODEL={name!r}; RL workload presets: "
            f"{sorted(LLAMA_CONFIGS)}"
        )
    model_cfg = LLAMA_CONFIGS[name]
    grpo = GRPOConfig(
        group_size=env_int("grpo_group", 8),
        clip_eps=env_float("grpo_clip", 0.2),
        kl_beta=env_float("grpo_kl_beta", 0.02),
        temperature=env_float("grpo_temperature", 1.0),
        max_new_tokens=env_int("grpo_max_new", 64),
        # -1 sentinel: 0 is a valid EOS id in several vocabularies.
        eos_id=(lambda e: None if e < 0 else e)(env_int("eos_id", -1)),
    )
    trainer_cfg = TrainerConfig(
        batch_size=env_int("batch_size", 16),
        seq_len=env_int("seq_len", min(512, model_cfg.max_seq_len)),
        total_steps=env_int("total_steps", 50),
        lr=env_float("lr", 1e-5),
        warmup_steps=env_int("warmup_steps", 5),
        loss_chunk_size=env_int("loss_chunk_size", 512) or None,
        checkpoint_dir=env_str("checkpoint_dir", "") or None,
        checkpoint_every=env_int("checkpoint_every", 100),
        log_every=1,
    )
    mesh_cfg = MeshConfig(
        data=env_int("mesh_data", 1),
        fsdp=env_int("mesh_fsdp", -1),
        tensor=env_int("mesh_tensor", 1),
    )
    return (
        GRPOTrainer(Llama(model_cfg), trainer_cfg, mesh_cfg, grpo=grpo),
        model_cfg,
    )


def main() -> int:
    from tpufw.cluster import initialize_cluster
    from tpufw.utils.profiling import enable_compile_cache

    cache = enable_compile_cache()
    cluster = initialize_cluster()
    if cluster.num_processes > 1:
        raise NotImplementedError(
            "the RL workload is single-process for now: rollouts are "
            "host-driven; shard prompts across independent Jobs instead"
        )

    import jax

    trainer, model_cfg = build_trainer()
    print(
        f"tpufw rl: devices={len(jax.devices())} "
        f"mesh={dict(trainer.mesh.shape)} params={model_cfg.n_params():,}"
        + (f" compile_cache={cache}" if cache else "")
    )

    init_from = env_str("init_from", "")
    if init_from:
        # Base init FIRST (snapshots the step-0 KL reference), THEN
        # resume: a JobSet restart mid-RL keeps the correct anchor.
        trainer.init_from_params(init_from, seed=env_int("seed", 0))
        print(f"initialized params from {init_from}")
    else:
        trainer.init_state(seed=env_int("seed", 0))
    if trainer.maybe_restore():
        print(f"resumed from checkpoint at step {int(trainer.state.step)}")

    from tpufw.workloads._common import resolve_encode

    prompts_file = env_str("prompts_file", "")
    if prompts_file:
        encode = resolve_encode(env_str("sft_tokenizer", "bytes"))
        prompts = load_prompts(prompts_file, encode)
    else:
        prompts = _DEMO_PROMPTS
        print("no TPUFW_PROMPTS_FILE: using built-in demo prompts")
    per_step = trainer.cfg.batch_size // trainer.grpo.group_size
    if len(prompts) < per_step:
        raise ValueError(
            f"{len(prompts)} prompts < {per_step} needed per step "
            f"(batch_size {trainer.cfg.batch_size} / group "
            f"{trainer.grpo.group_size})"
        )
    reward_fn = resolve_reward(
        env_str("reward", "low_token"),
        model_cfg.vocab_size,
        trainer.grpo.max_new_tokens,
    )

    first = {}

    def on_metrics(entry: dict) -> None:
        if not first:
            first["t"] = time.time()
            print(
                json.dumps({
                    "cold_start_to_first_step_s": round(
                        first["t"] - _T0, 1
                    ),
                    "compile_cache": cache or None,
                }),
                flush=True,
            )
        print(json.dumps(entry), flush=True)

    # Rotate through the prompt set: each step uses a contiguous
    # (wrapping) window, so every prompt gets rollouts over a long run.
    def window(i: int):
        return [
            prompts[(i * per_step + j) % len(prompts)]
            for j in range(per_step)
        ]

    history = trainer.run_rl(
        window, reward_fn, seed=env_int("seed", 0),
        on_metrics=on_metrics,
    )

    from tpufw.workloads._common import report_preemption

    report_preemption(trainer)
    if history:
        last = history[-1]
        print(
            f"RL OK: {len(history)} steps, reward_mean "
            f"{last['reward_mean']:.4f}, kl {last['kl']:.4f}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
