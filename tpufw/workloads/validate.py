"""In-container enablement validator (the chart's validator Job payload).

The GPU Operator ships validation pods that check the runtime injected the
driver correctly (SURVEY.md §2b X8); this is the TPU analog, run inside a
container that REQUESTS the accelerator. Checks ascend the same ladder as
recipe/TROUBLESHOOTING.md tree #3: device nodes mounted -> libtpu visible ->
allocation env present -> jax actually enumerates TPU cores. Exit 0 only if
every applicable check passes; each check prints PASS/FAIL so the Job log is
the diagnosis.
"""

from __future__ import annotations

import glob
import os


def _report(name: str, ok: bool, detail: str = "") -> bool:
    print(f"{'PASS' if ok else 'FAIL'}: {name}" + (f" — {detail}" if detail else ""))
    return ok


def run_checks(require_jax_tpu: bool = True) -> list[tuple[str, bool]]:
    results: list[tuple[str, bool]] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        results.append((name, _report(name, ok, detail)))

    nodes = glob.glob("/dev/accel*") + glob.glob("/dev/vfio/*")
    check(
        "TPU device nodes mounted", bool(nodes),
        ", ".join(nodes) or "none under /dev",
    )

    libtpu_candidates = [
        os.environ.get("TPU_LIBRARY_PATH", ""),
        "/lib/libtpu.so",
        "/usr/lib/libtpu.so",
        "/usr/local/lib/libtpu.so",
    ]
    lib = next((p for p in libtpu_candidates if p and os.path.exists(p)), None)
    check("libtpu present", lib is not None, lib or "not found")

    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    check(
        "allocation env injected", bool(visible and bounds),
        f"TPU_VISIBLE_CHIPS={visible!r} TPU_CHIPS_PER_HOST_BOUNDS={bounds!r}",
    )

    if require_jax_tpu:
        try:
            import jax

            devs = jax.devices()
            ok = any(d.platform == "tpu" for d in devs)
            detail = str(devs)
        except Exception as e:  # backend init failure IS the finding
            ok, detail = False, f"{type(e).__name__}: {e}"
        check("jax enumerates TPU cores", ok, detail)

    return results


def main() -> int:
    from tpufw.workloads.env import env_bool

    require_jax = env_bool("validate_require_jax", True)
    results = run_checks(require_jax_tpu=require_jax)
    failed = [n for n, ok in results if not ok]
    if failed:
        print(f"VALIDATION FAILED: {failed} — see recipe/TROUBLESHOOTING.md tree #3")
        return 1
    print("VALIDATION OK: container is TPU-enabled end to end")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
