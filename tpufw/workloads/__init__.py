"""Runnable workload entry points — what the deploy/ manifests execute.

Each module has a ``main()`` and is invocable as ``python -m
tpufw.workloads.<name>``; configuration comes from ``TPUFW_*`` environment
variables so a Kubernetes manifest is the config-of-record (SURVEY.md §5
"config/flag system": YAML manifest -> env -> dataclass, no flag DSL).
"""

from tpufw.workloads.env import (  # noqa: F401
    env_bool,
    env_float,
    env_int,
    env_opt_int,
    env_opt_str,
    env_str,
)
