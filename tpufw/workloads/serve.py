"""Inference serving workload: checkpoint -> batch generation / HTTP server.

The serving half of the same `kubectl apply` flow the trainer uses
(deploy/manifests/07-infer-v5e1.yaml): load the latest checkpoint from
TPUFW_CHECKPOINT_DIR, build the decode-mode model (KV cache + jitted
lax.scan loop, tpufw.infer.generate), and either

- batch mode (default): generate continuations for TPUFW_PROMPTS_FILE
  (JSON: list of token-id lists) or built-in demo prompts, printing one
  JSON line per prompt — `kubectl logs` is the result channel, the
  reference's verification pattern (reference README.md:331-335);
- server mode (TPUFW_SERVE_PORT > 0): a stdlib ThreadingHTTPServer with
  POST /generate {"prompts": [[ids]], "max_new_tokens": N} -> outputs,
  GET /healthz, and GET /metrics (Prometheus text exposition: request/
  error/tick/token counters + queue-depth gauge, the serving analog of
  the device plugin's endpoint). Prompt lengths are bucketed (multiples
  of 64) and batch
  rows padded to a power of two so repeat traffic reuses compiled programs
  instead of recompiling per ragged shape — the static-shape discipline
  XLA serving needs.

Without a checkpoint the model initializes randomly (flagged in output):
the manifest flow stays verifiable end-to-end before any training ran.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from tpufw.obs import events as obs_events
from tpufw.obs import goodput as obs_goodput
from tpufw.obs import perf as obs_perf
from tpufw.obs import trace as obs_trace
from tpufw.obs.health import NULL_WATCHDOG
from tpufw.obs.registry import Registry as ObsRegistry
from tpufw.workloads.env import env_bool, env_float, env_int, env_str

_T0 = time.time()


def _backend_name() -> str:
    """jax backend for the run_info gauge; 'unknown' when jax is not
    initialized enough to ask (run_info must never crash serving)."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001
        return "unknown"

DEMO_PROMPTS = [[1, 42, 7, 99], [1, 5], [1, 1000, 2000, 3000, 17]]


def build_generator():
    """Construct (decode_model, params, cfg, restored) from TPUFW_* env."""
    import dataclasses

    import jax

    from tpufw.configs import bench_model_config
    from tpufw.mesh import MeshConfig
    from tpufw.models import (
        DEEPSEEK_CONFIGS,
        Deepseek,
        GEMMA_CONFIGS,
        Gemma,
        LLAMA_CONFIGS,
        Llama,
        MIXTRAL_CONFIGS,
        Mixtral,
    )
    from tpufw.train import Trainer, TrainerConfig

    hf_dir = env_str("hf_checkpoint", "")
    if hf_dir:
        # Serve HF weights directly (TPUFW_HF_CHECKPOINT=<dir with
        # config.json + *.safetensors>): the torch-ecosystem on-ramp —
        # no Orbax conversion step needed. The HF config.json is the
        # source of truth for the architecture, so this branch runs
        # FIRST and TPUFW_MODEL is genuinely ignored (stale manifest
        # values can't break it). Params load onto the default device in
        # the activation dtype (bf16 — serving keeps no fp32 master
        # copy); for models larger than one chip, convert once via
        # `python -m tpufw.tools.import_hf` and use the Orbax path,
        # which restores sharded over the mesh.
        from tpufw.models.gemma import GemmaConfig
        from tpufw.models.mixtral import MixtralConfig
        from tpufw.tools.import_hf import config_from_hf, from_hf

        with open(os.path.join(hf_dir, "config.json")) as f:
            hf_cfg = config_from_hf(json.load(f))
        hf_cfg = dataclasses.replace(
            hf_cfg,
            max_seq_len=env_int("max_seq_len", hf_cfg.max_seq_len),
        )
        params = from_hf(hf_dir, hf_cfg, dtype=hf_cfg.dtype)
        from tpufw.models import model_for_config

        hf_cfg, params = _maybe_quantize(hf_cfg, params)
        hf_cfg, params = _maybe_unroll(hf_cfg, params)
        return (
            model_for_config(hf_cfg.decode_config()),
            params,
            hf_cfg,
            True,
        )

    name = env_str("model", "llama3_600m_bench")
    if name == "llama3_600m_bench":
        model_cfg = bench_model_config()
        model_cls = Llama
    elif name in LLAMA_CONFIGS:
        model_cfg, model_cls = LLAMA_CONFIGS[name], Llama
    elif name in MIXTRAL_CONFIGS:
        model_cfg, model_cls = MIXTRAL_CONFIGS[name], Mixtral
    elif name in GEMMA_CONFIGS:
        model_cfg, model_cls = GEMMA_CONFIGS[name], Gemma
    elif name in DEEPSEEK_CONFIGS:
        model_cfg, model_cls = DEEPSEEK_CONFIGS[name], Deepseek
    else:
        raise ValueError(
            f"unknown TPUFW_MODEL={name!r}; choose from "
            f"{['llama3_600m_bench', *LLAMA_CONFIGS, *MIXTRAL_CONFIGS, *GEMMA_CONFIGS, *DEEPSEEK_CONFIGS]}"
        )
    # Serving wants the full sequence budget but no training-only features.
    model_cfg = dataclasses.replace(
        model_cfg,
        max_seq_len=env_int("max_seq_len", model_cfg.max_seq_len),
    )

    params_dir = env_str("params_checkpoint", "")
    if params_dir:
        # Bare-params Orbax checkpoint (tpufw.tools.import_hf CLI
        # output) — TPUFW_MODEL still names the architecture. Restored
        # SHARDED onto the mesh (no throwaway init materializes), so
        # multi-chip models load split, not on device 0.
        params = _restore_bare_params(model_cfg, params_dir)
        model_cfg, params = _maybe_quantize(model_cfg, params)
        model_cfg, params = _maybe_unroll(model_cfg, params)
        return model_cls(model_cfg.decode_config()), params, model_cfg, True

    # Reuse the trainer's restore machinery (abstract state + reshard-on-
    # restore) rather than reimplementing orbax plumbing; params are then
    # pulled out of the restored TrainState.
    trainer = Trainer(
        model_cls(model_cfg),
        TrainerConfig(
            batch_size=1,
            seq_len=min(32, model_cfg.max_seq_len),
            total_steps=1,
            checkpoint_dir=env_str("checkpoint_dir", "") or None,
        ),
        MeshConfig(),
    )
    restored = trainer.maybe_restore()
    if not restored:
        trainer.init_state(seed=env_int("seed", 0))
    params = trainer.state.params
    del trainer.state  # drop optimizer moments; serving only needs params

    model_cfg, params = _maybe_quantize(model_cfg, params)
    model_cfg, params = _maybe_unroll(model_cfg, params)
    decode_model = model_cls(model_cfg.decode_config())
    _ = jax  # backend initialized above via Trainer
    return decode_model, params, model_cfg, restored


def _maybe_unroll(model_cfg, params):
    """Decode with the UNSCANNED layer stack (default ON) — the scanned
    trunk's decode loop slices its stacked [L, ...] weights per layer
    per step, which the unrolled twin avoids. Measured on the v5e chip
    (docs/evidence/DECODE_PROFILE_r5.jsonl, 2026-08-01): 1.16x decode
    throughput on the Llama bench model (1.05x on MLA), at ~10x the
    compile time per serving shape bucket (38 s vs 4 s). The default
    bucket is compiled by _Server._warmup before the listener binds;
    OTHER buckets pay the bigger compile on their first live hit — a
    compile-latency/steady-throughput trade serving takes by default
    per VERDICT r4 item 4. TPUFW_DECODE_UNROLL=0 opts out (e.g.
    compile-latency-sensitive dev loops, very deep models).
    Checkpoints stay scanned on disk; the param tree is unstacked in
    memory (tpufw.models.unstack_layer_params). Applied to EVERY
    build_generator source, after quantization (the unstack is
    tree-generic, quantized leaves included)."""
    import dataclasses as _dc

    if not env_int("decode_unroll", 1):
        return model_cfg, params
    from tpufw.models import unstack_layer_params

    return (
        _dc.replace(model_cfg, scan_layers=False),
        # donate: every caller rebinds params immediately, and the
        # donation bounds startup peak memory at weights + one stacked
        # leaf instead of 2x weights.
        unstack_layer_params(params, donate=True),
    )


def _maybe_quantize(model_cfg, params):
    """TPUFW_QUANTIZE=int8: convert projection weights to the int8
    serving form (tpufw.ops.quant) and flip the config so the modules
    declare the quantized params. Applied to EVERY build_generator
    source (HF dir, bare params, TrainState checkpoint)."""
    import dataclasses as _dc

    mode = env_str("quantize", "")
    if not mode:
        return model_cfg, params
    if mode != "int8":
        raise ValueError(
            f"TPUFW_QUANTIZE={mode!r}: only 'int8' is implemented"
        )
    from tpufw.ops.quant import quantize_params

    return (
        _dc.replace(model_cfg, quantized_weights=True),
        quantize_params(params),
    )


def _bucket(n: int, mult: int) -> int:
    return ((max(n, 1) + mult - 1) // mult) * mult


def _pow2_ceil(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor) — the ONE bucketing rung
    shared by batch-size padding and the KV-cache ladder."""
    size = floor
    while size < n:
        size *= 2
    return size


def _cache_bucket(need: int, cap: int, floor: int = 128) -> int:
    """Smallest pow-2 KV-cache length >= ``need`` (min ``floor``),
    capped at the model's ``cap``. Per-step attention/update traffic
    scales with cache length, so a short chat on a long-context model
    must not pay the full-cache bill; the pow-2 ladder bounds how many
    cache shapes the generate jit ever specializes on."""
    return min(_pow2_ceil(need, floor), cap)


def text_codec():
    """(encode, decode) for text prompts, from TPUFW_TOKENIZER.

    "bytes" (default) is the dependency-free byte-level codec shared
    with tpufw.tools.pack_corpus (id 0 reserved for padding); any other
    value is a HuggingFace tokenizer name/path — pair it with
    TPUFW_HF_CHECKPOINT so ids match the served model's vocab.
    """
    name = env_str("tokenizer", "bytes")
    if name == "bytes":
        from tpufw.tools.pack_corpus import byte_tokenizer

        def decode(ids: list[int]) -> str:
            return bytes(
                t - 1 for t in ids if 0 < t <= 256
            ).decode("utf-8", errors="replace")

        return byte_tokenizer, decode
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(name)
    return tok.encode, tok.decode


def make_sampling(
    temperature=0.0,
    top_k=0,
    top_p=1.0,
    min_p=0.0,
    repetition_penalty=1.0,
):
    """ONE copy of the sampling-knob coercion + validation rules,
    shared by the env path (``sampling_from_env``) and the untrusted
    per-request HTTP path — so explicit-default requests always compare
    equal to the env config and keep coalescing.

    Values are range-checked (clients can send anything) and floats
    QUANTIZED (temperature to 0.01, top_p/min_p/penalty to 0.001):
    sampling is a compiled-program parameter, and unquantized
    client-chosen floats would compile unboundedly many variants."""
    from tpufw.infer import SamplingConfig

    t = round(float(temperature), 2)
    if t < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    kf = float(top_k or 0)
    if kf != int(kf):
        raise ValueError(f"top_k must be an integer, got {top_k}")
    k = int(kf)
    if k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    p = round(float(1.0 if top_p is None else top_p), 3)
    if p <= 0:
        raise ValueError(f"top_p must be > 0, got {top_p}")
    m = round(float(min_p or 0.0), 3)
    if not 0 <= m <= 1:
        raise ValueError(f"min_p must be in [0, 1], got {min_p}")
    r = round(
        float(1.0 if repetition_penalty is None else repetition_penalty),
        3,
    )
    if r <= 0:
        raise ValueError(
            f"repetition_penalty must be > 0, got {repetition_penalty}"
        )
    return SamplingConfig(
        temperature=t,
        top_k=k or None,
        top_p=p if p < 1.0 else None,
        min_p=m or None,
        repetition_penalty=None if r == 1.0 else r,
    )


def sampling_from_env():
    """SamplingConfig from TPUFW_* env — ONE resolution for the batch
    and HTTP serving modes. Default stays greedy/deterministic."""
    return make_sampling(
        temperature=env_float("temperature", 0.0),
        top_k=env_int("top_k", 0),
        top_p=env_float("top_p", 1.0),
        min_p=env_float("min_p", 0.0),
        repetition_penalty=env_float("repetition_penalty", 1.0),
    )


def eos_from_env() -> Optional[int]:
    """TPUFW_EOS_ID: stop rows at this token (the token itself is
    emitted, outputs are truncated after it — tpufw.infer.generate).
    Unset/negative = run every row to max_new_tokens."""
    eos = env_int("eos_id", -1)
    return eos if eos >= 0 else None


def build_draft_generator(sampling):
    """TPUFW_DRAFT_MODEL: enable speculative decoding
    (tpufw.infer.speculative) with this preset as the draft — greedy
    acceptance at TPUFW_TEMPERATURE=0, rejection-resampling otherwise
    (every sampler knob composes, including the repetition penalty —
    tpufw.infer.speculative threads the seen-token mask through both
    the draft proposals and the per-position verify distributions).

    Draft weights come from TPUFW_DRAFT_PARAMS_CHECKPOINT (bare Orbax
    params, e.g. an import_hf of the small family member) — without it
    the draft initializes randomly, which is only useful for wiring
    tests (proposals rarely match, throughput degrades to ~plain decode
    plus draft overhead; outputs stay exactly target-distributed either
    way). Returns (draft_model, draft_params, k) or None when
    speculation is off."""
    import dataclasses

    import jax

    name = env_str("draft_model", "")
    if not name:
        return None
    from tpufw.configs.loader import resolve_model_preset
    from tpufw.models import model_for_config

    base = resolve_model_preset(name)
    cfg = dataclasses.replace(
        base, max_seq_len=env_int("max_seq_len", base.max_seq_len)
    )
    ckpt = env_str("draft_params_checkpoint", "")
    if ckpt:
        params = _restore_bare_params(cfg, ckpt)
    else:
        model = model_for_config(cfg)
        params = jax.jit(model.init)(
            jax.random.key(env_int("seed", 0) + 1),
            jax.numpy.zeros((1, min(8, cfg.max_seq_len)), jax.numpy.int32),
        )["params"]
    return (
        model_for_config(cfg.decode_config()),
        params,
        env_int("draft_k", 4),
    )


def _restore_bare_params(model_cfg, params_dir: str):
    """Bare-params Orbax restore via the trainer's abstract-tree helper
    — sharded onto the mesh, no throwaway init. ONE copy for the target
    (TPUFW_PARAMS_CHECKPOINT) and draft (TPUFW_DRAFT_PARAMS_CHECKPOINT)
    paths."""
    from tpufw.mesh import MeshConfig
    from tpufw.models import model_for_config
    from tpufw.train import Trainer, TrainerConfig

    shape_trainer = Trainer(
        model_for_config(model_cfg),
        TrainerConfig(
            batch_size=1, seq_len=min(32, model_cfg.max_seq_len)
        ),
        MeshConfig(),
    )
    params, _ = shape_trainer.restore_params(params_dir)
    return params


def _maybe_cast_decode(params):
    """Apply the TPUFW_DECODE_DTYPE serving-precision cast (e.g.
    ``bfloat16``; see tpufw.infer.cast_decode_params) if set — ONE
    knob for both the HTTP server and batch mode."""
    cast = env_str("decode_dtype", "")
    if not cast:
        return params
    import jax.numpy as jnp

    from tpufw.infer import cast_decode_params

    return cast_decode_params(params, jnp.dtype(cast))


def _pad_batch(
    prompts: list[list[int]], fill_id: int = 0
) -> tuple[list[list[int]], int]:
    """Pad the batch to a power of two so the jitted generate
    specializes on few batch shapes. Returns (padded, real_n).

    Filler rows are seeded with ``fill_id`` — callers pass the EOS id
    when one is configured, and thread the matching ``live_rows`` mask
    into generate so the done-mask kills fillers at step 1 instead of
    decoding max_new tokens of garbage (and, in the streaming path,
    holding the all-done early exit hostage)."""
    n = len(prompts)
    return prompts + [[fill_id]] * (_pow2_ceil(n) - n), n


def run_batch(prompts: list[list[int]], max_new_tokens: int) -> list[dict]:
    from tpufw.infer import generate_text, speculative_generate_text

    decode_model, params, cfg, restored = build_generator()
    params = _maybe_cast_decode(params)
    sampling = sampling_from_env()  # default greedy: deterministic
    draft = build_draft_generator(sampling)
    eos = eos_from_env()
    padded, real_n = _pad_batch(prompts, eos if eos is not None else 0)
    if draft is not None:
        draft_model, draft_params, k = draft
        draft_params = _maybe_cast_decode(draft_params)
        outs, _stats = speculative_generate_text(
            draft_model,
            draft_params,
            decode_model,
            params,
            padded,
            max_new_tokens=max_new_tokens,
            eos_id=eos,
            k=k,
            live_rows=[i < real_n for i in range(len(padded))],
            sampling=sampling,
            prefill_chunk_size=env_int("prefill_chunk", 0) or None,
        )
        outs = outs[:real_n]
    else:
        outs = generate_text(
            decode_model,
            params,
            padded,
            max_new_tokens=max_new_tokens,
            sampling=sampling,
            eos_id=eos,
            live_rows=[i < real_n for i in range(len(padded))],
            # Long-prompt lever: prefill activations scale with the
            # chunk, not the prompt (tpufw.infer.generate). 0 = off.
            prefill_chunk_size=env_int("prefill_chunk", 0) or None,
        )[:real_n]
    return [
        {
            "prompt": p,
            "output": o,
            "restored_checkpoint": restored,
            "model_params": cfg.n_params(),
        }
        for p, o in zip(prompts, outs)
    ]


def _oai_to_native(req: dict) -> dict:
    """OpenAI `/v1/completions` request -> the native `/generate`
    shape, so users switching stacks can point an existing client at
    the server. Supported: `prompt` (string, list of strings, token
    list, or list of token lists), `max_tokens`, `temperature`,
    `top_p`, `seed`-free determinism per the tick-seed contract.
    Unsupported knobs fail loudly with the native alternative named
    (an OpenAI client silently getting different semantics is worse
    than a 400)."""
    if "prompt" not in req:
        raise ValueError("prompt is required")
    if req.get("stream"):
        raise ValueError(
            "stream is not supported on /v1/completions; use "
            "/generate with \"stream\": true (SSE)"
        )
    # `n: 1` is the OpenAI default and many SDK wrappers send it
    # explicitly — it requests exactly this server's behavior.
    if req.get("n") not in (None, 1):
        raise ValueError(
            "n > 1 is not supported on /v1/completions; post the "
            "prompt n times (ticks draw fresh seeds)"
        )
    # Semantics-changing knobs must fail LOUDLY — a client silently
    # getting different semantics is worse than a 400 — but values
    # that REQUEST the default behavior pass (SDK wrappers send
    # explicit defaults: echo: false, zero penalties, best_of: 1,
    # stop: null/[]). logprobs: 0 is meaningful (sampled-token
    # logprobs, zero alternatives), so only None passes there.
    defaults = {
        "logprobs": (None,),
        "echo": (None, False),
        "best_of": (None, 1),
        "presence_penalty": (None, 0, 0.0),
        "frequency_penalty": (None, 0, 0.0),
        "stop": (None, "", []),
    }
    alts = {
        "logprobs": "not supported",
        "echo": "prepend the prompt client-side",
        "best_of": "post the prompt best_of times and rank",
        "presence_penalty": "use repetition_penalty on /generate",
        "frequency_penalty": "use repetition_penalty on /generate",
        "stop": "set TPUFW_EOS_ID on the server",
    }
    for knob, ok_values in defaults.items():
        if knob in req and req[knob] not in ok_values:
            raise ValueError(
                f"{knob} is not supported on /v1/completions; "
                f"{alts[knob]}"
            )
    p = req["prompt"]
    native: dict = {"_oai_model": req.get("model", "")}
    if isinstance(p, str):
        native["texts"] = [p]
    elif isinstance(p, list) and p and all(
        isinstance(x, str) for x in p
    ):
        native["texts"] = p
    elif isinstance(p, list) and p and all(
        isinstance(x, int) for x in p
    ):
        native["prompts"] = [p]
    else:
        native["prompts"] = p  # [[int]] — /generate validates
    if "max_tokens" in req:
        native["max_new_tokens"] = req["max_tokens"]
    for knob in ("temperature", "top_p"):
        if knob in req:
            native[knob] = req[knob]
    return native


def _oai_response(
    outs, texts, prompts, max_new: int, model: str
) -> dict:
    """OpenAI text_completion response shape. finish_reason: a row
    shorter than max_new ended at the server's eos ("stop"), otherwise
    it ran out of budget ("length")."""
    import uuid

    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model or "tpufw",
        "choices": [
            {
                "text": texts[i],
                "index": i,
                "logprobs": None,
                "finish_reason": (
                    "stop" if len(outs[i]) < max_new else "length"
                ),
            }
            for i in range(len(outs))
        ],
        "usage": {
            "prompt_tokens": sum(len(p) for p in prompts),
            "completion_tokens": sum(len(o) for o in outs),
            "total_tokens": sum(len(p) for p in prompts)
            + sum(len(o) for o in outs),
        },
    }


class _Pending:
    """One enqueued /generate request awaiting its tick."""

    __slots__ = ("prompts", "max_new", "sampling", "done", "outputs",
                 "error", "batched_with", "stream_q")

    def __init__(
        self,
        prompts: list[list[int]],
        max_new: int,
        sampling=None,
        stream_q=None,
    ):
        self.prompts = prompts
        self.max_new = max_new
        # None = the server's env-default SamplingConfig; a request
        # override makes this tick-compatible only with same-config
        # requests (the rng and transforms are shared per device call).
        self.sampling = sampling
        # Streaming request: per-chunk outputs go onto this queue
        # (lists of per-row new tokens, then a ("done",)/("error", e)
        # sentinel). Stream requests run as SOLO ticks — their device
        # work is a chunk loop, not one coalescible call.
        self.stream_q = stream_q
        self.done = threading.Event()
        self.outputs: list | None = None
        self.error: Exception | None = None
        self.batched_with = 1


class _Metrics:
    """Serving metrics on the shared ``tpufw.obs`` registry — the same
    ``tpufw_serve_*`` names and text exposition as the original
    hand-rolled class; the exposition code itself now lives in
    ``tpufw.obs.registry`` (one implementation for this endpoint, the
    trainer's ``TPUFW_METRICS_PORT``, and the device-plugin analog).
    Call sites keep the short names ("requests_total"); the prefix is
    applied here."""

    PREFIX = "tpufw_serve_"

    def __init__(self, registry: Optional[ObsRegistry] = None):
        self.registry = registry if registry is not None else ObsRegistry()
        # Pre-initialized to 0 (client-library convention): an alert on
        # increase(...errors_total) must see a real 0-valued series
        # before the first error, not an absent one.
        self.register(
            "requests_total",
            "request_errors_total",
            "request_seconds_total",
            "ticks_total",
            "tick_rows_total",
            "tokens_generated_total",
        )

    def inc(self, name: str, v: float = 1.0) -> None:
        self.registry.counter(self.PREFIX + name).inc(v)

    def register(self, *names: str) -> None:
        """Expose counters at 0 before their first increment (same
        absent-series rationale as the pre-initialized set) — for
        feature-gated counters like the speculative pair."""
        for name in names:
            self.registry.counter(self.PREFIX + name)

    def reset(self, *names: str) -> None:
        """Zero counters that moved during work that must stay
        invisible to scrapes (warmup runs before the listener binds)."""
        for name in names:
            self.registry.counter(self.PREFIX + name).reset()

    def render(self, gauges: dict[str, float]) -> str:
        """Prometheus text exposition; ``gauges`` are the caller's
        point-in-time values, refreshed into the registry at scrape
        time (they have one source of truth elsewhere)."""
        for name, v in gauges.items():
            self.registry.gauge(self.PREFIX + name).set(float(v))
        return self.registry.render()


class _Batcher:
    """Continuous batching at request granularity (VERDICT r2 #7).

    Requests enqueue; one worker thread drains the queue per tick,
    coalescing every waiting request into ONE batched generate call
    (rows concatenated, padded to a power of two; max_new_tokens run to
    the tick's bucketed max and sliced per request). While a tick's
    generate runs on the device, new arrivals accumulate for the next
    tick — so N concurrent clients cost ~one batched call instead of N
    serialized full-latency calls. A short coalescing window
    (TPUFW_BATCH_WAIT_MS, default 5) after the first dequeue lets
    near-simultaneous requests land in the same tick; TPUFW_BATCH_MAX_ROWS
    (default 64) caps rows per tick, the rest stay queued.
    """

    def __init__(
        self,
        run_tick,
        metrics: Optional[_Metrics] = None,
        run_stream=None,
    ):
        self._run_tick = run_tick
        self._run_stream = run_stream
        self._metrics = metrics
        self._queue: list[_Pending] = []
        self._cv = threading.Condition()
        self.max_rows = env_int("batch_max_rows", 64)
        self.wait_s = env_int("batch_wait_ms", 5) / 1000.0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def submit(self, prompts: list[list[int]], max_new: int, sampling=None):
        p = _Pending(prompts, max_new, sampling)
        with self._cv:
            self._queue.append(p)
            self._cv.notify()
        p.done.wait()
        if p.error is not None:
            raise p.error
        return p.outputs, p.batched_with

    def submit_stream(
        self, prompts: list[list[int]], max_new: int, sampling, q
    ) -> None:
        """Enqueue a streaming request and return immediately — the
        caller consumes per-chunk row outputs from ``q`` until the
        ("done",)/("error", e) sentinel. Device order is still the
        batcher thread's: the stream runs as its own tick."""
        p = _Pending(prompts, max_new, sampling, stream_q=q)
        with self._cv:
            self._queue.append(p)
            self._cv.notify()

    def _take_tick(self) -> list[_Pending]:
        with self._cv:
            while not self._queue:
                self._cv.wait()
        time.sleep(self.wait_s)  # let near-simultaneous arrivals land
        with self._cv:
            tick: list[_Pending] = []
            rows = 0
            rest: list[_Pending] = []
            # One device call = one SamplingConfig (it's a jit static
            # arg and the rng transforms are shared): the head request
            # defines the tick's config and every compatible request
            # joins; mismatches keep their queue order for a later
            # tick. No starvation — the head of the remainder defines
            # the NEXT tick's config. FIFO holds WITHIN a config: once
            # a same-config request misses the row budget, no later
            # same-config request may overtake it into this tick (only
            # config mismatches are diverted past it).
            budget_closed = False
            solo = False
            for nxt in self._queue:
                if not tick:
                    tick.append(nxt)
                    rows += len(nxt.prompts)
                    # A streaming head runs alone: its device work is a
                    # chunk LOOP, not one coalescible call.
                    solo = nxt.stream_q is not None
                elif solo or nxt.stream_q is not None:
                    rest.append(nxt)
                elif nxt.sampling != tick[0].sampling:
                    rest.append(nxt)
                elif (
                    budget_closed
                    or rows + len(nxt.prompts) > self.max_rows
                ):
                    budget_closed = True
                    rest.append(nxt)
                else:
                    tick.append(nxt)
                    rows += len(nxt.prompts)
            # tpulint: disable=TPU020 — consumer-side pop: shrinking
            # the queue only makes the wait predicate ("queue
            # non-empty") falser; there is no waiter this write could
            # unblock, so a notify would be a spurious wakeup.
            self._queue = rest
            return tick

    def _run_group(self, group: list[_Pending]) -> None:
        """Run one coalesced device call for ``group``; raises on
        failure without touching the pendings (the caller decides
        whether to isolate)."""
        if len(group) == 1 and group[0].stream_q is not None:
            pend = group[0]
            self._run_stream(pend)
            pend.batched_with = 1
            return
        all_prompts = [p for pend in group for p in pend.prompts]
        # Bucket the group's max_new to a power of two: the scan
        # length is a compiled-shape dimension, so arbitrary
        # per-request values would each compile a fresh program.
        want = max(p.max_new for p in group)
        run_new = 1
        while run_new < want:
            run_new *= 2
        outs = self._run_tick(all_prompts, run_new, group[0].sampling)
        i = 0
        for pend in group:
            rows = outs[i: i + len(pend.prompts)]
            pend.outputs = [r[: pend.max_new] for r in rows]
            pend.batched_with = len(group)
            i += len(pend.prompts)

    def _loop(self):
        while True:
            tick = self._take_tick()
            if self._metrics is not None:
                self._metrics.inc("ticks_total")
                self._metrics.inc(
                    "tick_rows_total",
                    sum(len(p.prompts) for p in tick),
                )
            try:
                try:
                    self._run_group(tick)
                except Exception:  # noqa: BLE001 — serving loop
                    if len(tick) == 1:
                        raise
                    # Failure isolation: coalescing must not create a
                    # shared fate — one invalid request (or a prompt/
                    # max_new combination that only overflows the KV
                    # budget when COMBINED with a co-batched request's
                    # bucket) falls back to per-request runs so the
                    # innocent ones still succeed.
                    for pend in tick:
                        try:
                            self._run_group([pend])
                        except Exception as e:  # noqa: BLE001
                            pend.error = e
            except Exception as e:  # noqa: BLE001 — serving loop
                for pend in tick:
                    pend.error = e
                    if pend.stream_q is not None:
                        # The SSE handler is blocked on the queue, not
                        # the done event — it needs the sentinel.
                        pend.stream_q.put(("error", e))
            finally:
                if self._metrics is not None:
                    self._metrics.inc(
                        "tokens_generated_total",
                        sum(
                            len(r)
                            for p in tick
                            if p.outputs is not None
                            for r in p.outputs
                        ),
                    )
                for pend in tick:
                    pend.done.set()


class _SlotJob:
    """One prompt ROW moving through the slot pool. Rows are the
    schedulable unit: a request's rows may join across chunk
    boundaries as slots free up, and each retires independently at
    its own EOS/max_new."""

    __slots__ = ("req", "prompt", "p_bucket", "max_new", "cache_len",
                 "tokens", "unflushed", "cp")

    def __init__(self, req, prompt, p_bucket, max_new, cache_len):
        self.req = req
        self.prompt = prompt
        self.p_bucket = p_bucket
        self.max_new = max_new
        self.cache_len = cache_len
        self.tokens: list[int] = []
        self.unflushed: list[int] = []
        # In-flight chunked prefill (pages.ChunkedPrefill) while this
        # row occupies a slot as a PREFILLING citizen; None once the
        # first token lands (or always, in monolithic admission mode).
        self.cp = None


class _SlotReq:
    """Request-level bookkeeping around a _Pending: the per-row jobs,
    the admission cursor (``next_job``), and completion accounting."""

    __slots__ = ("pend", "sampling", "jobs", "next_job", "rows_left",
                 "cache_len", "t_submit", "started", "error",
                 "batched_with", "overtaken")

    def __init__(self, pend, sampling, jobs):
        self.pend = pend
        self.sampling = sampling  # resolved (never None)
        self.jobs = jobs
        self.next_job = 0  # first not-yet-admitted job
        self.rows_left = len(jobs)
        # _make_req constructs the req first (jobs reference it), then
        # fills jobs and recomputes this.
        self.cache_len = max((j.cache_len for j in jobs), default=0)
        self.t_submit = time.time()
        self.started = False  # first row admitted (join latency mark)
        self.error: Exception | None = None
        self.batched_with = 1
        self.overtaken = 0  # admission rounds later arrivals ran ahead


class _SlotScheduler:
    """Continuous batching at decode-STEP granularity — the tick
    batcher's successor (``tpufw.infer.slots`` holds the device side).

    Requests enqueue as per-row jobs; ONE worker thread admits rows
    into a persistent S-slot KV pool and advances ALL occupied slots k
    tokens per device call. Rows join whenever a slot frees at a chunk
    boundary and retire at their own EOS/max_new — a short request
    admitted next to a long one completes mid-flight instead of
    waiting out the long tail, and streaming requests are ordinary
    slot occupants sharing decode chunks with everyone else (the tick
    batcher ran them as solo ticks).

    Static-shape discipline: occupancy is DATA, so joins/leaves never
    recompile. The pool is keyed (cache_len, sampling) — cache_len
    from the serving ``_cache_bucket`` ladder, sampling because it is
    a compiled-program parameter — and REKEYS only when it drains
    empty. Chunk length k is itself pow-2-laddered against the
    largest remaining budget, so at most log2(chunk) decode programs
    exist per pool key; greedy outputs are invariant to how the run
    is chunked (the per-step carry is identical).

    Fairness: FIFO holds within a pool key — once a compatible
    request misses the free-slot budget, no later compatible request
    overtakes it. Incompatible requests are diverted past, but each
    diversion is counted and admission CLOSES after ``n_slots``
    overtakes, so a mismatched head request drains the pool instead
    of starving behind a steady compatible stream.

    Knobs: TPUFW_SERVE_SLOTS (pool size; 0 restores the tick
    batcher), TPUFW_SERVE_CHUNK (tokens per device call, default
    TPUFW_STREAM_CHUNK), TPUFW_SERVE_CACHE_FLOOR (smallest cache
    rung), TPUFW_BATCH_WAIT_MS (idle coalescing window, shared with
    the tick batcher).
    """

    def __init__(
        self,
        model,
        params,
        *,
        eos_id: Optional[int] = None,
        default_sampling=None,
        metrics: Optional[_Metrics] = None,
        seed_base: int = 0,
        events=None,
        tracer=None,
        goodput=None,
        watchdog=None,
        page: Optional[int] = None,
        kv_quant: Optional[str] = None,
        prefix_cache: Optional[bool] = None,
        arena_pages: Optional[int] = None,
        perf=None,
        page_export=None,
        spec_k: Optional[int] = None,
        spec_draft: Optional[str] = None,
        spec_min_accept: Optional[float] = None,
        spec_draft_built=None,
        prefill_chunk_pages: Optional[int] = None,
    ):
        import jax
        import numpy as np

        from tpufw.infer import slots as slots_mod

        self._jax = jax
        self._np = np
        self._slots_mod = slots_mod
        self.model = model
        self.params = params
        self._eos = eos_id
        self._default_sampling = (
            default_sampling
            if default_sampling is not None
            else sampling_from_env()
        )
        self._metrics = metrics
        self._seed_base = seed_base
        self._events = events if events is not None else obs_events.NULL
        self._tracer = tracer if tracer is not None else obs_trace.NULL
        self._goodput = goodput if goodput is not None else obs_goodput.NULL
        self._watchdog = watchdog if watchdog is not None else NULL_WATCHDOG
        self._perf = perf if perf is not None else obs_perf.NULL
        # Disaggregated handoff hook: called with (job, state) for
        # every naturally-completing paged row, where ``state`` is the
        # slot's export_slot() dict taken BEFORE the slot is retired.
        self._page_export = page_export
        # Join-latency component split (queue_wait + prefill). Gated
        # OFF by default: registering the histograms adds scrape lines,
        # and the legacy exposition must stay byte-identical unless the
        # operator opts in.
        self.latency_breakdown = env_bool("serve_latency_breakdown", False)
        self.n_slots = max(1, env_int("serve_slots", 8))
        self.chunk = max(
            1, env_int("serve_chunk", 0) or env_int("stream_chunk", 16)
        )
        self.cache_floor = env_int("serve_cache_floor", 128)
        self.wait_s = env_int("batch_wait_ms", 5) / 1000.0
        self.prefill_chunk = env_int("prefill_chunk", 0) or None
        # Paged-KV knobs: ctor kwargs win over the env so bench can
        # run both modes in one process without mutating os.environ.
        # page=0 keeps the legacy contiguous SlotPool bit-for-bit.
        self.page = (
            env_int("serve_page", 0) if page is None else int(page)
        )
        self.kv_quant = (
            env_str("serve_kv_quant", "")
            if kv_quant is None
            else str(kv_quant)
        )
        self.prefix_enabled = (
            env_bool("serve_prefix_cache", True)
            if prefix_cache is None
            else bool(prefix_cache)
        )
        self.arena_pages = arena_pages
        # Page-aligned chunked prefill: admission acquires only the
        # first chunk's pages and the row prefills one chunk per
        # scheduler pass, interleaved with decoding slots — a long
        # prompt no longer head-of-line-blocks the queue. 0 keeps the
        # legacy monolithic admission byte-identical.
        self.prefill_chunk_pages = (
            env_int("serve_prefill_chunk", 0)
            if prefill_chunk_pages is None
            else int(prefill_chunk_pages)
        )
        if self.prefill_chunk_pages and not self.page:
            raise ValueError(
                f"TPUFW_SERVE_PREFILL_CHUNK="
                f"{self.prefill_chunk_pages}: chunked prefill is "
                "page-granular and needs TPUFW_SERVE_PAGE > 0"
            )
        # KV fabric: host-RAM spill tier behind the page arena.
        # TPUFW_KV_SPILL budgets it in PAGES (the arena's own unit);
        # TPUFW_KV_SPILL_DIR adds the directory overflow / session
        # store. Evicted prefix pages demote there instead of dying,
        # and a later prompt sharing the prefix restores them through
        # the normal splice path instead of re-prefilling.
        self.kv_spill_pages = max(0, env_int("kv_spill", 0))
        self.kv_spill_dir = env_str("kv_spill_dir", "")
        self._spill = None
        if self.kv_spill_pages or self.kv_spill_dir:
            if not self.page:
                raise ValueError(
                    f"TPUFW_KV_SPILL={self.kv_spill_pages}: the spill "
                    "tier is page-granular and needs "
                    "TPUFW_SERVE_PAGE > 0"
                )
            from tpufw.infer.spill import SpillTier

            self._spill = SpillTier(
                self.kv_spill_pages, self.kv_spill_dir
            )
        # Scrape-time delta cursor: the tier's byte total is monotonic
        # but registry counters only inc, so /metrics advances the
        # counter by the delta since the last scrape.
        self._spill_seen_bytes = 0
        if self.page:
            cap = model.cfg.max_seq_len
            # Every cache-ladder rung is a pow2 >= cache_floor or the
            # model cap, so "page is pow2 and page <= floor and page
            # divides cap" guarantees page | cache_len at every rung.
            if self.page & (self.page - 1) or self.page < 1:
                raise ValueError(
                    f"TPUFW_SERVE_PAGE={self.page}: page size must be "
                    "a power of two"
                )
            if self.page > self.cache_floor:
                raise ValueError(
                    f"TPUFW_SERVE_PAGE={self.page} exceeds the cache "
                    f"floor ({self.cache_floor}); pages must divide "
                    "every cache-ladder rung"
                )
            if cap % self.page:
                raise ValueError(
                    f"TPUFW_SERVE_PAGE={self.page} does not divide "
                    f"max_seq_len={cap}"
                )
            if self.kv_quant not in ("", "int8"):
                raise ValueError(
                    f"TPUFW_SERVE_KV_QUANT={self.kv_quant!r}: "
                    "expected '' or 'int8'"
                )
            from tpufw.infer import pages as pages_mod

            self._pages_mod = pages_mod
        # Speculative decoding on the slot pool: TPUFW_SERVE_SPEC_K > 0
        # drafts spec_k tokens per pass and verifies them in ONE target
        # call (tpufw.infer.speculative chunked path). Ctor kwargs win
        # over env (bench runs both modes in one process). spec_draft
        # "" = self-drafting (n-gram prompt lookup, no extra HBM); a
        # model preset name builds a draft pool sharing the target's
        # page arena budget. spec_draft_built short-circuits the preset
        # resolution with a pre-built (decode_cfg, params) pair — the
        # server passes its TPUFW_DRAFT_MODEL build through this.
        self.spec_k = (
            env_int("serve_spec_k", 0) if spec_k is None else int(spec_k)
        )
        self.spec_draft = (
            env_str("serve_spec_draft", "")
            if spec_draft is None
            else str(spec_draft)
        )
        self.spec_min_accept = (
            env_float("serve_spec_min_accept", 0.25)
            if spec_min_accept is None
            else float(spec_min_accept)
        )
        self._draft_cfg = None
        self._draft_params = None
        self._draft_n_params = 0
        self._draft_pool = None
        self._ema = None
        # Cumulative accept bookkeeping behind tpufw_spec_accept_rate.
        self._spec_accept_sum = 0.0
        self._spec_accept_rows = 0
        if self.spec_k:
            if self.spec_k < 1:
                raise ValueError(
                    f"TPUFW_SERVE_SPEC_K={self.spec_k}: need >= 1"
                )
            if self.page and self.spec_k + 1 > self.page:
                # Clamp safety: a done row's junk verify block must fit
                # inside the row's own last page (writes clamp to
                # max_seq_len - (k+1)), so the block can never spill
                # into a neighbour's page.
                raise ValueError(
                    f"TPUFW_SERVE_SPEC_K={self.spec_k}: the k+1 verify "
                    f"block must fit one KV page (page={self.page})"
                )
            from tpufw.infer import speculative as spec_mod

            self._spec_mod = spec_mod
            if spec_draft_built is not None:
                self._draft_cfg, self._draft_params = spec_draft_built
            elif self.spec_draft and self.spec_draft != "ngram":
                self._draft_cfg, self._draft_params = (
                    self._build_spec_draft(self.spec_draft)
                )
            if self._draft_params is not None:
                # Wasted-draft-FLOPs accounting (~2 * params per drafted
                # token, decode-side); 0 for self-drafting — n-gram
                # lookup costs no device FLOPs.
                self._draft_n_params = sum(
                    int(np.prod(leaf.shape))
                    for leaf in jax.tree_util.tree_leaves(
                        self._draft_params
                    )
                )
        if metrics is not None:
            metrics.register(
                "retired_rows_total",
                "wasted_slot_steps_total",
                "pool_switches_total",
            )
            if self.page:
                # Feature-gated (register = expose at 0): legacy-mode
                # /metrics stays byte-identical with paging off.
                metrics.register(
                    "prefix_hits_total",
                    "prefix_misses_total",
                    "pages_freed_total",
                )
            if self.prefill_chunk_pages:
                # Chunked-prefill series live OUTSIDE the tpufw_serve_
                # prefix (the disagg PrefillEngine reports the same
                # names through its signals); gated so a monolithic
                # server's exposition stays byte-identical.
                metrics.registry.counter("tpufw_prefill_chunks_total")
                metrics.registry.counter("tpufw_prefill_resumes_total")
                metrics.registry.gauge("tpufw_prefill_inflight")
            if self._spill is not None:
                # KV-fabric series also live OUTSIDE the prefix (the
                # disagg engines report the same spill tier); gated so
                # a spill-less exposition stays byte-identical.
                metrics.registry.counter("tpufw_kv_spill_bytes_total")
                metrics.registry.gauge("tpufw_kv_spill_pages")
                metrics.registry.histogram(
                    "tpufw_kv_restore_seconds",
                    "Spill-tier restore wall (host fetch + decode)",
                )
            if self.spec_k:
                # Speculation metrics live OUTSIDE the tpufw_serve_
                # prefix (they also serve the disagg DecodeEngine);
                # registered at 0/absent-series like the rest, gated so
                # non-spec servers keep a byte-identical exposition.
                metrics.registry.counter(
                    "tpufw_spec_wasted_draft_flops_total"
                )
                metrics.registry.gauge("tpufw_spec_accept_rate")
                metrics.registry.gauge("tpufw_spec_fallback_slots")
            metrics.registry.histogram(
                "tpufw_serve_join_latency_seconds",
                "Request submit-to-first-slot-insert latency",
            )
            if self.latency_breakdown:
                # Component split of the join latency: time queued
                # behind other requests vs. time inside the prefill
                # program itself.
                metrics.registry.histogram(
                    "tpufw_serve_queue_wait_seconds",
                    "Request submit-to-admission-start latency",
                )
                metrics.registry.histogram(
                    "tpufw_serve_prefill_seconds",
                    "Per-row prefill wall-clock",
                )
        self._pool = None  # tpufw.infer.slots.SlotPool (lazy, keyed)
        self._pool_key: Optional[tuple] = None
        self._slots: list[Optional[_SlotJob]] = [None] * self.n_slots
        self._n_active = 0  # resource: counter slots-occupied
        # Monotonic indices namespacing the rng streams (fold_in of
        # two DIFFERENT base seeds, so prefill and chunk draws never
        # collide); both restored by reset_after_warmup so warmup is
        # invisible to seed replay.
        self._job_index = 0
        self._chunk_index = 0
        self._queue: list[_SlotReq] = []
        self._cv = threading.Condition()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tpufw-serve-sched"
        )
        self._thread.start()

    # ---- client-facing interface (mirrors _Batcher) ----

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    @property
    def slots_total(self) -> int:
        return self.n_slots

    @property
    def slots_occupied(self) -> int:
        with self._cv:
            return self._n_active

    @property
    def pages_total(self) -> int:
        """Arena capacity of the CURRENT pool (0 before first build /
        in contiguous mode) — page 0 is the reserved junk sink and
        never allocatable, so it is excluded."""
        with self._cv:
            if not self.page or self._pool is None:
                return 0
            return self._pool.allocator.capacity

    @property
    def pages_in_use(self) -> int:
        with self._cv:
            if not self.page or self._pool is None:
                return 0
            return self._pool.allocator.in_use

    def submit(self, prompts: list[list[int]], max_new: int, sampling=None):
        pend = _Pending(prompts, max_new, sampling)
        self._enqueue(pend)
        pend.done.wait()
        if pend.error is not None:
            raise pend.error
        return pend.outputs, pend.batched_with

    def submit_stream(
        self, prompts: list[list[int]], max_new: int, sampling, q
    ) -> None:
        """Enqueue a streaming request and return immediately — the
        caller consumes per-chunk row outputs from ``q`` until the
        ("done", n)/("error", e) sentinel. Stream rows occupy slots
        like any other; their unflushed tokens are put once per decode
        chunk."""
        pend = _Pending(prompts, max_new, sampling, stream_q=q)
        self._enqueue(pend)

    def reset_after_warmup(self) -> None:
        """Restore the rng-stream indices so warmup prefills/chunks
        are invisible to seed replay (the compiled programs and the
        warm pool itself stay)."""
        with self._cv:
            self._job_index = 0
            self._chunk_index = 0

    def _enqueue(self, pend: _Pending) -> None:
        req = self._make_req(pend)  # raises ValueError -> HTTP 400
        with self._cv:
            self._queue.append(req)
            self._cv.notify()

    def _make_req(self, pend: _Pending) -> _SlotReq:
        cap = self.model.cfg.max_seq_len
        sampling = (
            pend.sampling
            if pend.sampling is not None
            else self._default_sampling
        )
        jobs = []
        req = _SlotReq(pend, sampling, [])
        # Speculative slack: a live row's verify block writes up to
        # spec_k slots past its final cursor before rolling back, so
        # spec rows size their cache rung / page grant for it.
        slack = self._spec_slack(sampling)
        for prompt in pend.prompts:
            if self.page:
                # Paged rows prefill at their EXACT width (no 64-token
                # bucket): padding would burn whole pages per row and
                # misalign the prompt's page-granular prefix chunks.
                pb = max(len(prompt), 1)
            else:
                pb = _bucket(len(prompt), 64)
            # Validate at submit (not mid-pool): prefill writes pb
            # slots, decode writes max_new - 1 more (the first token
            # comes out of prefill).
            if pb + pend.max_new - 1 + slack > cap:
                raise ValueError(
                    f"prompt ({len(prompt)}, bucketed to {pb}) + "
                    f"max_new_tokens ({pend.max_new})"
                    + (f" + spec slack ({slack})" if slack else "")
                    + f" exceeds the KV cache (max_seq_len={cap})"
                )
            if self.page and self.arena_pages is not None:
                need = -(-(pb + pend.max_new - 1 + slack) // self.page)
                if need > self.arena_pages - 1:
                    # Reject now, not in the admission loop: a row
                    # that can NEVER fit the arena would deadlock the
                    # FIFO forever (page 0 is reserved). This bound is
                    # already max-resident: an in-place row must hold
                    # its whole prompt+budget page set at finalize
                    # even under chunked admission, so chunking only
                    # relaxes it on the disagg PrefillEngine (which
                    # exports prompt-only bundles — see serve/roles).
                    raise ValueError(
                        f"row needs {need} KV pages but the arena "
                        f"holds {self.arena_pages - 1}"
                    )
            jobs.append(_SlotJob(
                req,
                prompt,
                pb,
                pend.max_new,
                _cache_bucket(
                    pb + pend.max_new - 1 + slack, cap, self.cache_floor
                ),
            ))
        req.jobs = jobs
        req.rows_left = len(jobs)
        req.cache_len = max(j.cache_len for j in jobs)
        return req

    # ---- worker loop ----

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._n_active:
                    self._cv.wait()
                idle = self._n_active == 0
            if idle and self.wait_s > 0:
                # Coalescing window: near-simultaneous arrivals land
                # in the same first admission round. Never slept while
                # the pool is running — joins happen at chunk
                # boundaries, which are the natural cadence.
                time.sleep(self.wait_s)
            # Watchdog window: one admit + one chunk. Both are a
            # bounded amount of device work (prefill / k decode
            # steps); if either wedges past TPUFW_HANG_TIMEOUT_S the
            # dump shows which. Idle waiting above stays disarmed.
            self._watchdog.arm()
            try:
                self._admit()
                if self._n_active:
                    self._run_chunk()
            except Exception as e:  # noqa: BLE001 — serving loop
                self._fail_active(e)
            finally:
                self._watchdog.disarm()

    def _row_model(self, cache_len: int):
        """CONTIGUOUS model variant with the pool's KV budget — built
        inline; flax modules hash structurally, so equal configs hit
        the jit caches without memoization (same trick as
        _Server._model_for). In paged mode this is the B=1 prefill
        model (prefill stays dense; paging starts at row insert)."""
        import dataclasses

        if cache_len == self.model.cfg.max_seq_len:
            return self.model
        return type(self.model)(
            dataclasses.replace(self.model.cfg, max_seq_len=cache_len)
        )

    def _pool_model(self, cache_len: int):
        """Model variant the POOL decodes with: contiguous rows by
        default; with TPUFW_SERVE_PAGE set, the paged-arena variant
        (kv_page/kv_pages/kv_quant route the models' cached-attention
        through the page table)."""
        import dataclasses

        if not self.page:
            return self._row_model(cache_len)
        per_row = cache_len // self.page
        n_pages = (
            self.arena_pages
            if self.arena_pages is not None
            # +1 for the reserved junk-sink page 0: the default arena
            # holds exactly n_slots full rows, same HBM working set
            # as the contiguous pool it replaces.
            else self.n_slots * per_row + 1
        )
        return type(self.model)(
            dataclasses.replace(
                self.model.cfg,
                max_seq_len=cache_len,
                kv_page=self.page,
                kv_pages=n_pages,
                kv_quant=self.kv_quant,
            )
        )

    def _spec_slack(self, sampling) -> int:
        """Extra KV slots a speculative row needs past max_new - 1 (0
        when speculation is off or ineligible for this sampling)."""
        if not self.spec_k:
            return 0
        if self._slots_mod._track_seen(sampling):
            return 0
        return self.spec_k

    def _build_spec_draft(self, name: str):
        """Resolve TPUFW_SERVE_SPEC_DRAFT as a model preset: weights
        from TPUFW_DRAFT_PARAMS_CHECKPOINT, else random init (wiring
        tests only — proposals rarely match, acceptance collapses and
        the EMA falls the pool back to plain decode). Returns the
        (decode_cfg, params) pair the per-pool variants derive from."""
        import dataclasses

        jax = self._jax
        from tpufw.configs.loader import resolve_model_preset
        from tpufw.models import model_for_config

        base = resolve_model_preset(name)
        cfg = dataclasses.replace(
            base, max_seq_len=env_int("max_seq_len", base.max_seq_len)
        )
        ckpt = env_str("draft_params_checkpoint", "")
        if ckpt:
            params = _restore_bare_params(cfg, ckpt)
        else:
            model = model_for_config(cfg)
            params = jax.jit(model.init)(
                jax.random.key(self._seed_base + 1),
                self._jax.numpy.zeros(
                    (1, min(8, cfg.max_seq_len)), self._jax.numpy.int32
                ),
            )["params"]
        return cfg.decode_config(), params

    def _draft_pool_models(self, cache_len: int):
        """Per-pool draft model variants (pool + contiguous prefill
        twin) mirroring _pool_model/_row_model's replace() trick, with
        the SAME page/arena geometry as the target so one shared
        PageAllocator id space covers both physical arenas."""
        import dataclasses

        from tpufw.models import model_for_config

        row_cfg = dataclasses.replace(
            self._draft_cfg, max_seq_len=cache_len
        )
        if not self.page:
            return model_for_config(row_cfg), model_for_config(row_cfg)
        per_row = cache_len // self.page
        n_pages = (
            self.arena_pages
            if self.arena_pages is not None
            else self.n_slots * per_row + 1
        )
        pool_cfg = dataclasses.replace(
            row_cfg,
            kv_page=self.page,
            kv_pages=n_pages,
            kv_quant=self.kv_quant,
        )
        return model_for_config(pool_cfg), model_for_config(row_cfg)

    def _build_pool(self, key) -> None:
        cache_len, sampling = key
        with self._tracer.span(
            "serve_pool_build", cache_len=cache_len, slots=self.n_slots
        ):
            if self.page:
                self._pool = self._pages_mod.PagedSlotPool.create_paged(
                    self._pool_model(cache_len),
                    self._row_model(cache_len),
                    self.params,
                    self.n_slots,
                    sampling=sampling,
                    pad_id=0,
                    eos_id=self._eos,
                    prefix_cache=self.prefix_enabled,
                )
            else:
                self._pool = self._slots_mod.SlotPool.create(
                    self._pool_model(cache_len),
                    self.params,
                    self.n_slots,
                    sampling=sampling,
                    pad_id=0,
                    eos_id=self._eos,
                )
        if self.page and self._spill is not None:
            # Re-wired on every pool rebuild: the spill closures close
            # over the pool they serialize for. The tier itself (and
            # its contents) survives rebuilds — a cache-ladder switch
            # does not forget spilled KV.
            from tpufw.serve import bundle as serve_bundle

            serve_bundle.attach_spill(
                self._pool,
                self._spill,
                events=self._events,
                on_restore=(
                    self._metrics.registry.histogram(
                        "tpufw_kv_restore_seconds"
                    ).observe
                    if self._metrics is not None
                    else None
                ),
            )
        if self._perf.enabled:
            # Mount the cost observatory on the pool (dynamic attr:
            # SlotPool/PagedSlotPool read it via getattr) so insert /
            # decode programs harvest their XLA cost analysis.
            self._pool.perf = self._perf
        self._draft_pool = None
        self._ema = None
        if self.spec_k:
            track = self._slots_mod._track_seen(sampling)
            if track:
                # Acceptance at position j would change the penalized
                # distribution at j+1 — the one-pass verify cannot
                # compose with a repetition penalty, so this pool stays
                # on plain chunked decode.
                self._events.emit(
                    "serve_spec",
                    level="warn",
                    k=self.spec_k,
                    mode="plain_fallback",
                    reason="repetition_penalty",
                )
            else:
                if self._draft_cfg is not None:
                    d_pool, d_row = self._draft_pool_models(cache_len)
                    if self.page:
                        self._draft_pool = (
                            self._pages_mod.PagedSlotPool.create_paged(
                                d_pool,
                                d_row,
                                self._draft_params,
                                self.n_slots,
                                sampling=sampling,
                                pad_id=0,
                                eos_id=None,
                                prefix_cache=False,
                                allocator=self._pool.allocator,
                            )
                        )
                    else:
                        self._draft_pool = self._slots_mod.SlotPool.create(
                            d_pool,
                            self._draft_params,
                            self.n_slots,
                            sampling=sampling,
                            pad_id=0,
                            eos_id=None,
                        )
                self._ema = self._spec_mod.AcceptEMA(
                    self.n_slots,
                    min_accept=self.spec_min_accept,
                    # Plain chunks leave a draft pool's KV stale (only
                    # the target advances), so a probe there would
                    # measure a stale-context draft: draft-pool
                    # fallback is sticky until the pool drains.
                    probe_every=0 if self._draft_pool is not None else 8,
                )
        self._pool_key = key
        self._slots = [None] * self.n_slots
        self._n_active = 0
        if self._metrics is not None:
            self._metrics.inc("pool_switches_total")
        self._events.emit(
            "serve_pool_switch", cache_len=cache_len, slots=self.n_slots
        )

    def _admit(self) -> None:
        with self._cv:
            queue = list(self._queue)
        if not queue:
            return
        # The pool rekeys ONLY when empty: the head request defines
        # the (cache_len, sampling) every later admission must match.
        if self._n_active == 0:
            head = queue[0]
            key = (head.cache_len, head.sampling)
            if self._pool is None or self._pool_key != key:
                try:
                    self._build_pool(key)
                except Exception as e:  # noqa: BLE001 — serving loop
                    self._fail_req(head, e)
                    return
        if self._pool is None:
            return
        cache_cap = self._pool.cache_len
        pool_sampling = self._pool.sampling
        free = [i for i, j in enumerate(self._slots) if j is None]
        budget_closed = False
        blocked: Optional[_SlotReq] = None
        with self._tracer.span("serve_admit", queued=len(queue)):
            for req in queue:
                if req.error is not None:
                    continue
                if (
                    req.sampling != pool_sampling
                    or req.cache_len > cache_cap
                ):
                    if blocked is None:
                        blocked = req
                        if req.overtaken >= self.n_slots:
                            # Fairness valve: this head has been
                            # diverted past enough times — stop
                            # feeding the pool and let it drain so
                            # the head can rekey it.
                            break
                    continue
                if budget_closed:
                    continue  # FIFO within a pool key: no overtaking
                if not free:
                    budget_closed = True
                    continue
                if self._admit_req(req, free) and blocked is not None:
                    blocked.overtaken += 1
                if req.next_job < len(req.jobs) and req.error is None:
                    budget_closed = True
            with self._cv:
                # tpulint: disable=TPU020 — consumer-side sweep of
                # finished/failed requests: removal only makes the
                # scheduler's own "queue non-empty" predicate falser;
                # completion waiters watch req.done events, not this
                # list, so there is nobody to notify.
                self._queue = [
                    r
                    for r in self._queue
                    if r.error is None and r.next_job < len(r.jobs)
                ]
        # batched_with: how many distinct requests share the pool now.
        reqs = {
            id(j.req): j.req for j in self._slots if j is not None
        }
        for req in reqs.values():
            req.batched_with = max(req.batched_with, len(reqs))

    def _admit_req(self, req: _SlotReq, free: list[int]) -> bool:
        """Admit as many of ``req``'s remaining rows as fit; returns
        True if at least one row ran (prefilled), slot-consuming or
        not."""
        t_admit0 = time.time()
        admitted = False
        while free and req.next_job < len(req.jobs):
            job = req.jobs[req.next_job]
            if self.page and self.prefill_chunk_pages:
                # Chunked admission: the row takes a slot immediately
                # as a PREFILLING citizen and acquires pages chunk by
                # chunk inside the pool passes — no whole-prompt page
                # grant, no monolithic prefill blocking this loop. The
                # reservation guard keeps part-admitted rows deadlock-
                # free (their summed outstanding need always fits).
                if not self._can_admit_chunked(job):
                    break
                try:
                    self._admit_chunked(req, job, free[0])
                except Exception as e:  # noqa: BLE001 — isolate req
                    self._fail_req(req, e)
                    return admitted
                req.next_job += 1
                admitted = True
                free.pop(0)
                continue
            grant = None
            if self.page:
                # Page-budget admission: the row needs every page of
                # its prompt+budget up front (writes may land anywhere
                # in that window). None = arena full even after trie
                # eviction — stop admitting and let retires free pages
                # (FIFO holds: nothing overtakes within the pool key).
                grant = self._pool.acquire_pages(
                    job.prompt,
                    len(job.prompt) + job.max_new - 1
                    + self._spec_slack(self._pool.sampling),
                )
                if grant is None:
                    break
            try:
                # Legacy mode keeps the historical 3-arg call (tests
                # spy on _admit_job with that arity).
                used_slot = (
                    self._admit_job(req, job, free[0], grant)
                    if grant is not None
                    else self._admit_job(req, job, free[0])
                )
            except Exception as e:  # noqa: BLE001 — isolate request
                if grant is not None:
                    self._free_pages(self._pool.release_pages(grant[0]))
                self._fail_req(req, e)
                return admitted
            req.next_job += 1
            admitted = True
            if used_slot:
                free.pop(0)
        if admitted and not req.started:
            req.started = True
            if self._metrics is not None:
                self._metrics.registry.histogram(
                    "tpufw_serve_join_latency_seconds"
                ).observe(time.time() - req.t_submit)
                if self.latency_breakdown:
                    self._metrics.registry.histogram(
                        "tpufw_serve_queue_wait_seconds"
                    ).observe(max(0.0, t_admit0 - req.t_submit))
        if admitted and req.pend.stream_q is not None:
            # First tokens reach the stream at admission, not a chunk
            # later — and every flush stays <= chunk-size tokens/row.
            self._flush_stream(req)
        if req.rows_left == 0 and req.next_job == len(req.jobs):
            self._finish(req)
        return admitted

    def _cp_deficit(self) -> int:
        """Pages still owed to in-flight chunked prefills — the gap
        between what they will hold at finalize and what they hold
        now. Admission and draft grants reserve around this sum so
        two part-admitted rows can never deadlock on the arena."""
        return sum(
            j.cp.deficit
            for j in self._slots
            if j is not None and j.cp is not None
        )

    def _can_admit_chunked(self, job: _SlotJob) -> bool:
        """Deadlock-free reservation: admit a new chunked prefill only
        when free + trie-evictable pages cover every in-flight
        prefill's remaining need PLUS this row's whole need. Chunk
        grabs are all-or-nothing per chunk, so under this invariant
        every admitted prefill eventually reaches its full grant."""
        a = self._pool.allocator
        evictable = sum(1 for i in a.held if not a.refs.get(i, 0))
        n_total = self._pool.n_pages_for(
            len(job.prompt) + job.max_new - 1
            + self._spec_slack(self._pool.sampling)
        )
        return self._cp_deficit() + n_total <= a.n_free + evictable

    def _admit_chunked(
        self, req: _SlotReq, job: _SlotJob, slot: int
    ) -> None:
        """Open a chunked prefill and seat it in ``slot`` WITHOUT any
        device call: the slot's pool state stays born-done (its junk
        decode writes land in reserved page 0), so the occupied slot
        pins the pool key while ``_run_prefill_chunks`` advances the
        row one page-aligned chunk per pass."""
        jax = self._jax
        with self._cv:
            job_index = self._job_index
            self._job_index += 1
        rng = jax.random.fold_in(
            jax.random.key(self._seed_base), job_index
        )
        need = (
            len(job.prompt) + job.max_new - 1
            + self._spec_slack(self._pool.sampling)
        )
        cp = self._pool.start_chunked(
            job.prompt, need, rng, self.prefill_chunk_pages
        )
        try:
            if self.prefix_enabled:
                hit = cp.shared_n > 0
                if self._metrics is not None:
                    self._metrics.inc(
                        "prefix_hits_total" if hit
                        else "prefix_misses_total"
                    )
                    if hit:
                        # Trie hits ARE the resume path: a preempted
                        # prefill's checkpointed pages come back here.
                        self._metrics.registry.counter(
                            "tpufw_prefill_resumes_total"
                        ).inc()
                self._events.emit(
                    "serve_prefix",
                    hit=hit,
                    shared_pages=cp.shared_n,
                    prompt_tokens=len(job.prompt),
                )
        except BaseException:
            # The caller's isolate-req handler swallows this raise
            # (_fail_req): the cursor's page refs would leak silently
            # if the metrics/event plumbing failed here (TPU019).
            self._free_pages(self._pool.abandon_chunked(cp))
            raise
        job.cp = cp  # resource: transfers pages
        self._slots[slot] = job
        self._n_active += 1
        self._set_prefill_inflight()

    def _set_prefill_inflight(self) -> None:
        if self._metrics is None or not self.prefill_chunk_pages:
            return
        self._metrics.registry.gauge("tpufw_prefill_inflight").set(
            float(sum(
                1 for j in self._slots
                if j is not None and j.cp is not None
            ))
        )

    def _admit_job(
        self, req: _SlotReq, job: _SlotJob, slot: int, grant=None
    ) -> bool:
        """Prefill one row and (unless it finishes at its first
        token) insert it into ``slot``. Returns True iff the slot was
        consumed. ``grant`` is the paged mode's (page_ids, shared_n)
        from acquire_pages — this method owns releasing it on the
        early-finish path (the caller releases on exceptions)."""
        # resource: transfers pages
        jax = self._jax
        # Namespaced, replayable prefill stream: a fresh base key per
        # call, folded with the monotonic job index. The paged shared
        # path draws the SAME per-token streams (split_prefill_keys),
        # so a prefix hit never perturbs sampled outputs.
        with self._cv:
            # _job_index is also reset from the caller side
            # (reset_after_warmup), so the bump must hold the monitor.
            job_index = self._job_index
            self._job_index += 1
        rng = jax.random.fold_in(
            jax.random.key(self._seed_base), job_index
        )
        if grant is not None:
            page_ids, shared_n = grant
            if self.prefix_enabled:
                hit = shared_n > 0
                if self._metrics is not None:
                    self._metrics.inc(
                        "prefix_hits_total"
                        if hit
                        else "prefix_misses_total"
                    )
                self._events.emit(
                    "serve_prefix",
                    hit=hit,
                    shared_pages=shared_n,
                    prompt_tokens=len(job.prompt),
                )
        prefill_t0 = time.perf_counter()
        with self._tracer.span(
            "serve_prefill", prompt=len(job.prompt), width=job.p_bucket
        ):
            if grant is not None and shared_n > 0:
                cache, _first, first_int, _done, seen = (
                    self._pool.prefill_shared(
                        job.prompt, page_ids[:shared_n], rng
                    )
                )
            else:
                cache, _first, first_int, _done, seen = (
                    # tpulint: disable=TPU003 — exclusive if/else arms:
                    # exactly ONE of prefill_shared/prefill_row consumes
                    # this job's rng.
                    self._slots_mod.prefill_row(
                        getattr(
                            self._pool, "row_model", self._pool.model
                        ),
                        self.params,
                        job.prompt,
                        rng,
                        sampling=self._pool.sampling,
                        eos_id=self._eos,
                        pad_to=job.p_bucket,
                        prefill_chunk_size=self.prefill_chunk,
                    )
                )
        if self.latency_breakdown and self._metrics is not None:
            self._metrics.registry.histogram(
                "tpufw_serve_prefill_seconds"
            ).observe(time.perf_counter() - prefill_t0)
        job.tokens.append(first_int)
        job.unflushed.append(first_int)
        if self._metrics is not None:
            self._metrics.inc("tokens_generated_total")
        if job.max_new == 1 or (
            self._eos is not None and first_int == self._eos
        ):
            # Finished at its first token: the row never occupies a
            # slot (the prefilled cache is dropped).
            if grant is not None:
                self._free_pages(self._pool.release_pages(page_ids))
            if self._metrics is not None:
                self._metrics.inc("retired_rows_total")
            req.rows_left -= 1
            return False
        if grant is not None:
            self._pool.insert_paged(
                slot,
                cache,
                first_int,
                len(job.prompt),
                job.max_new - 1,
                page_ids,
                shared_n,
                row_seen=seen,
            )
            if self.prefix_enabled:
                # Register AFTER insert: the pages now hold the full
                # prompt's K/V. The trie holds its adopted ids so they
                # outlive this row.
                self._pool.register_prefix(job.prompt, page_ids)
        else:
            self._pool.insert(
                slot,
                cache,
                first_int,
                len(job.prompt),
                job.max_new - 1,
                row_seen=seen,
            )
        if self._draft_pool is not None:
            self._admit_draft(job, slot, rng)
        if self._ema is not None:
            self._ema.occupy(slot)
        self._slots[slot] = job
        self._n_active += 1
        return True

    def _admit_draft(self, job: _SlotJob, slot: int, rng) -> None:
        """Prefill ``job``'s prompt through the draft model into the
        draft pool's matching slot. Draft pages come from the SHARED
        allocator but are granted strictly AFTER the target's, and a
        failed draft grant degrades the slot (its proposals verify as
        junk, acceptance collapses, the EMA routes the pool to plain
        decode) instead of blocking admission — speculation never
        starves target-page admission."""
        d_grant = None
        if self.page:
            d_need = self._draft_pool.n_pages_for(
                len(job.prompt) + job.max_new - 1 + self.spec_k
            )
            if (
                self._cp_deficit()
                and self._draft_pool.allocator.n_free
                < self._cp_deficit() + d_need
            ):
                # Draft pages would eat into the reservation in-flight
                # chunked prefills count on — degrade this slot rather
                # than stall prefill progress.
                self._events.emit(
                    "serve_spec",
                    level="warn",
                    k=self.spec_k,
                    mode="draft_starved",
                    slot=slot,
                )
                return
            d_grant = self._draft_pool.acquire_pages(
                job.prompt,
                len(job.prompt) + job.max_new - 1 + self.spec_k,
            )
            if d_grant is None:
                self._events.emit(
                    "serve_spec",
                    level="warn",
                    k=self.spec_k,
                    mode="draft_starved",
                    slot=slot,
                )
                return
        try:
            d_cache, _f, d_first, _d, d_seen = self._slots_mod.prefill_row(
                getattr(
                    self._draft_pool, "row_model", self._draft_pool.model
                ),
                self._draft_params,
                job.prompt,
                # Disjoint from the job's sampling stream (the drawn
                # first token is discarded; drafting re-proposes from
                # the target's actual last token each pass).
                self._jax.random.fold_in(rng, 11),
                sampling=self._draft_pool.sampling,
                eos_id=None,
                pad_to=(
                    len(job.prompt) if self.page else job.p_bucket
                ),
                prefill_chunk_size=self.prefill_chunk,
            )
            if d_grant is not None:
                self._draft_pool.insert_paged(
                    slot,
                    d_cache,
                    d_first,
                    len(job.prompt),
                    job.max_new - 1 + self.spec_k,
                    d_grant[0],
                    0,
                    row_seen=d_seen,
                )
            else:
                self._draft_pool.insert(
                    slot,
                    d_cache,
                    d_first,
                    len(job.prompt),
                    job.max_new - 1 + self.spec_k,
                    row_seen=d_seen,
                )
        except Exception as e:  # noqa: BLE001 — degrade, don't fail
            if d_grant is not None:
                self._free_pages(
                    self._draft_pool.release_pages(d_grant[0])
                )
            self._events.emit(
                "serve_spec",
                level="warn",
                k=self.spec_k,
                mode="draft_starved",
                slot=slot,
                reason=str(e),
            )

    def _free_pages(self, freed: int) -> None:
        if freed and self._metrics is not None:
            self._metrics.inc("pages_freed_total", freed)

    def _retire_slot(self, slot: int, *, device: bool) -> None:
        """Vacate ``slot``. ``device=True`` also freezes the row's
        done/remaining masks (error paths); natural completions
        already froze themselves inside the decode step. Paged pools
        always take the device path — it zeroes the slot's page-table
        row before the pages go back on the free list."""
        job = self._slots[slot]
        if job is not None and job.cp is not None:
            # Preempted chunked prefill: drop its page refs. The trie
            # keeps every checkpointed full page, so a re-submission
            # resumes from the last committed page, never restarts.
            self._free_pages(self._pool.abandon_chunked(job.cp))
            job.cp = None
            self._set_prefill_inflight()
        if self.page:
            self._free_pages(self._pool.release_slot(slot))
        elif device:
            self._pool.retire(slot)
        if self._draft_pool is not None:
            # Draft KV pages retire through the same allocator/refcount
            # path as the target's (a slot that never got a draft grant
            # releases an empty list — no-op).
            if self.page:
                self._free_pages(self._draft_pool.release_slot(slot))
            elif device:
                self._draft_pool.retire(slot)
        if self._ema is not None:
            self._ema.vacate(slot)
        self._slots[slot] = None
        self._n_active -= 1

    def _use_spec(self, active) -> bool:
        """Acceptance-aware scheduling: spec while the active slots'
        mean accept-EMA clears the threshold (None = spec off or this
        pool is penalty-ineligible)."""
        if self._ema is None:
            return False
        return self._ema.use_spec([slot for slot, _ in active])

    def _run_spec_chunk(self, active) -> None:
        """One speculative pass over every occupied slot: draft
        spec_k tokens (n-gram self-draft or the draft pool), verify
        them in ONE target call, advance each slot by its own accept
        count. Mirrors _run_chunk's retire/flush/accounting with the
        chunk length replaced by the per-slot emit counts."""
        k = self.spec_k
        with self._cv:
            chunk_index = self._chunk_index
            self._chunk_index += 1
        key = self._jax.random.fold_in(
            self._jax.random.key(self._seed_base + 1), chunk_index
        )
        page_snap: dict[int, list[int]] = {}
        if self.page and self._page_export is not None:
            page_snap = {
                slot: list(self._pool.slot_pages[slot])
                for slot, _ in active
            }
        chunk_t0 = time.perf_counter()
        with self._tracer.span(
            "serve_spec_chunk", k=k, rows=len(active)
        ):
            if self._draft_pool is not None:
                out, n_emit, accept = self._pool.spec_draft_steps(
                    self._draft_pool, key, k
                )
            else:
                props = self._np.zeros(
                    (self.n_slots, k), self._np.int32
                )
                for slot, job in active:
                    props[slot] = self._spec_mod.ngram_propose(
                        list(job.prompt) + job.tokens, k
                    )
                # tpulint: disable=TPU003 — exclusive if/else arms:
                # exactly ONE of spec_draft_steps/spec_steps consumes
                # this chunk's key.
                out, n_emit, accept = self._pool.spec_steps(props, key)
            out = self._np.asarray(out)
            n_emit = self._np.asarray(n_emit)
            accept = self._np.asarray(accept)
        chunk_s = time.perf_counter() - chunk_t0
        self._perf.record_wall(
            f"serve_spec_draft_k{k}"
            if self._draft_pool is not None
            else f"serve_spec_k{k}",
            chunk_s,
        )
        live_tokens = 0
        flush: list[_SlotReq] = []
        finished: list[_SlotReq] = []
        accept_frac = 0.0
        for slot, job in active:
            req = job.req
            take = min(int(n_emit[slot]), job.max_new - len(job.tokens))
            row = out[slot, :take].tolist()
            # The program already masks past the first EOS; this trim
            # is the same belt-and-braces as the plain path.
            if self._eos is not None and self._eos in row:
                row = row[: row.index(self._eos) + 1]
            job.tokens.extend(row)
            job.unflushed.extend(row)
            live_tokens += len(row)
            self._ema.update(slot, int(accept[slot]) / k)
            accept_frac += int(accept[slot]) / k
            if req.pend.stream_q is not None and req not in flush:
                flush.append(req)
            if len(job.tokens) >= job.max_new or (
                self._eos is not None and row and row[-1] == self._eos
            ):
                if self.page and self._page_export is not None:
                    self._page_export(
                        job,
                        self._pool.export_slot(
                            slot, page_ids=page_snap[slot]
                        ),
                    )
                self._retire_slot(slot, device=False)
                if self._metrics is not None:
                    self._metrics.inc("retired_rows_total")
                req.rows_left -= 1
                if req.rows_left == 0 and req.next_job == len(req.jobs):
                    finished.append(req)
        rate = accept_frac / max(len(active), 1)
        self._spec_accept_sum += accept_frac
        self._spec_accept_rows += len(active)
        if self._metrics is not None:
            self._metrics.inc("ticks_total")
            self._metrics.inc("tick_rows_total", len(active))
            self._metrics.inc("tokens_generated_total", live_tokens)
            # Device work this pass = S * (k+1) verify token-positions
            # (the capacity denominator goodput splits below); rejected
            # draft work is tracked separately as wasted draft FLOPs.
            self._metrics.inc(
                "wasted_slot_steps_total",
                self.n_slots * (k + 1) - live_tokens,
            )
            reg = self._metrics.registry
            # Cumulative mean, not last-pass: a scrape after traffic
            # drains must still report what the server accepted.
            reg.gauge("tpufw_spec_accept_rate").set(
                self._spec_accept_sum / max(self._spec_accept_rows, 1)
            )
            reg.gauge("tpufw_spec_fallback_slots").set(
                float(
                    self._ema.fallback_slots([s for s, _ in active])
                )
            )
            reg.counter("tpufw_spec_wasted_draft_flops_total").inc(
                sum(k - int(accept[s]) for s, _ in active)
                * 2.0
                * self._draft_n_params
            )
        self._events.emit(
            "serve_spec",
            k=k,
            mode="pass",
            rows=len(active),
            accept_rate=round(rate, 4),
        )
        live_frac = live_tokens / (self.n_slots * (k + 1))
        self._goodput.add("busy", chunk_s * live_frac)
        self._goodput.add("wasted_slot", chunk_s * (1.0 - live_frac))
        for req in flush:
            if req not in finished:
                self._flush_stream(req)
        for req in finished:
            self._finish(req)

    def _run_prefill_chunks(self) -> bool:
        """Advance every PREFILLING slot by one page-aligned chunk —
        the prefill citizens of the same scheduler pass the decoding
        slots share (no separate tick). A row whose final chunk lands
        here is finalized immediately, so it decodes in THIS pass's
        chunk ladder. Returns True iff any chunk ran."""
        if not self.prefill_chunk_pages:
            return False
        progressed = False
        for slot, job in [
            (i, j)
            for i, j in enumerate(self._slots)
            if j is not None and j.cp is not None
        ]:
            cp = job.cp
            t0 = time.perf_counter()
            with self._tracer.span(
                "serve_prefill_chunk",
                slot=slot,
                cursor=cp.cursor,
                prompt=len(job.prompt),
            ):
                status = self._pool.chunk_step(cp)
            if status == "stalled":
                # Arena momentarily full: the row keeps its slot and
                # retries next pass (retires/evictions free pages; the
                # admission reservation guarantees eventual progress).
                continue
            progressed = True
            if self._metrics is not None:
                self._metrics.registry.counter(
                    "tpufw_prefill_chunks_total"
                ).inc()
            self._events.emit(
                "serve_prefill_chunk",
                prompt_tokens=len(job.prompt),
                cursor=cp.cursor,
                chunk_s=round(time.perf_counter() - t0, 6),
                final=status == "done",
                slot=slot,
            )
            if status == "done":
                self._finalize_chunked(slot, job)
        self._set_prefill_inflight()
        return progressed

    def _finalize_chunked(self, slot: int, job: _SlotJob) -> None:
        """A chunked prefill sampled its first token: either finish
        the row outright (max_new == 1 / EOS-first — checkpointed
        pages stay trie-held, the rest free; the slot never saw a
        device call) or install it as a decoding citizen of its
        slot."""
        cp = job.cp
        req = job.req
        job.cp = None
        first_int = cp.first_int
        job.tokens.append(first_int)
        job.unflushed.append(first_int)
        if self._metrics is not None:
            self._metrics.inc("tokens_generated_total")
        if job.max_new == 1 or (
            self._eos is not None and first_int == self._eos
        ):
            self._free_pages(self._pool.abandon_chunked(cp))
            self._slots[slot] = None
            self._n_active -= 1
            if self._metrics is not None:
                self._metrics.inc("retired_rows_total")
            req.rows_left -= 1
        else:
            self._pool.finalize_chunked(slot, cp, job.max_new - 1)
            if self._draft_pool is not None:
                self._admit_draft(job, slot, cp.rng)
            if self._ema is not None:
                self._ema.occupy(slot)
        if req.pend.stream_q is not None:
            self._flush_stream(req)
        if req.rows_left == 0 and req.next_job == len(req.jobs):
            self._finish(req)

    def _run_chunk(self) -> None:
        progressed = self._run_prefill_chunks()
        active = [
            (i, j)
            for i, j in enumerate(self._slots)
            if j is not None and j.cp is None
        ]
        if not active:
            if self._n_active and not progressed:
                # Every occupied slot is a prefill stalled on pages
                # and nothing is decoding: yield briefly so the loop
                # doesn't spin hot waiting for a release/eviction.
                time.sleep(0.001)
            return
        if self._use_spec(active):
            self._run_spec_chunk(active)
            return
        # Pow-2 ladder on the chunk length: the scan length is a
        # compiled-shape dimension, so the tail of a nearly-done pool
        # shrinks k in big steps (at most log2(chunk) programs), never
        # per-value.
        max_left = max(j.max_new - len(j.tokens) for _, j in active)
        k = min(self.chunk, _pow2_ceil(max_left))
        with self._cv:
            # Reset from the caller side in reset_after_warmup; bump
            # under the monitor so neither side loses an update.
            chunk_index = self._chunk_index
            self._chunk_index += 1
        key = self._jax.random.fold_in(
            self._jax.random.key(self._seed_base + 1), chunk_index
        )
        keys = self._jax.random.split(key, k)
        # Chunk-boundary page-table snapshot for the export hook: a
        # row that finishes mid-chunk keeps absorbing the junk-sink
        # (page 0) writes for the chunk's remaining steps, and once it
        # retires its freed pages can be re-granted to a queued
        # admission within this same scheduler pass. Exports therefore
        # read THIS snapshot — the ids the row actually owned when the
        # chunk launched — never the post-retire allocator state.
        page_snap: dict[int, list[int]] = {}
        if self.page and self._page_export is not None:
            page_snap = {
                slot: list(self._pool.slot_pages[slot])
                for slot, _ in active
            }
        chunk_t0 = time.perf_counter()
        with self._tracer.span(
            "serve_decode_chunk", k=k, rows=len(active)
        ):
            out = self._np.asarray(self._pool.decode_steps(keys))
        chunk_s = time.perf_counter() - chunk_t0
        # Publishes tpufw_program_mfu{program="serve_decode_k<k>"}
        # from the chunk's wall-clock + harvested FLOPs (no-op on the
        # null observatory / before the program's cost harvest).
        self._perf.record_wall(f"serve_decode_k{k}", chunk_s)
        if self._metrics is not None:
            self._metrics.inc("ticks_total")
            self._metrics.inc("tick_rows_total", len(active))
        live_tokens = 0
        flush: list[_SlotReq] = []
        finished: list[_SlotReq] = []
        for slot, job in active:
            req = job.req
            take = min(k, job.max_new - len(job.tokens))
            row = out[slot, :take].tolist()
            if self._eos is not None and self._eos in row:
                row = row[: row.index(self._eos) + 1]
            job.tokens.extend(row)
            job.unflushed.extend(row)
            live_tokens += len(row)
            if req.pend.stream_q is not None and req not in flush:
                flush.append(req)
            if len(job.tokens) >= job.max_new or (
                self._eos is not None and row and row[-1] == self._eos
            ):
                # Retire: host-side in contiguous mode — the device
                # row froze itself via the done/remaining masks. Paged
                # mode also clears the page table and frees the pages.
                if self.page and self._page_export is not None:
                    self._page_export(
                        job,
                        self._pool.export_slot(
                            slot, page_ids=page_snap[slot]
                        ),
                    )
                self._retire_slot(slot, device=False)
                if self._metrics is not None:
                    self._metrics.inc("retired_rows_total")
                req.rows_left -= 1
                if req.rows_left == 0 and req.next_job == len(req.jobs):
                    finished.append(req)
        if self._metrics is not None:
            self._metrics.inc("tokens_generated_total", live_tokens)
            # Capacity accounting: S * k device-steps ran; everything
            # not delivering a live token (empty slots, done rows
            # inside the chunk) is the batching overhead to tune
            # TPUFW_SERVE_SLOTS / _CHUNK against.
            self._metrics.inc(
                "wasted_slot_steps_total",
                self.n_slots * k - live_tokens,
            )
        # Goodput: the chunk's wall-clock split by the same capacity
        # accounting — the live-token fraction was busy, the rest was
        # wasted slot-steps (time the gap between them and true idle
        # is what TPUFW_SERVE_SLOTS / _CHUNK tuning reclaims).
        live_frac = live_tokens / (self.n_slots * k)
        self._goodput.add("busy", chunk_s * live_frac)
        self._goodput.add("wasted_slot", chunk_s * (1.0 - live_frac))
        for req in flush:
            if req not in finished:
                self._flush_stream(req)
        for req in finished:
            self._finish(req)

    # ---- completion / failure ----

    def _flush_stream(self, req: _SlotReq) -> None:
        rows = [list(j.unflushed) for j in req.jobs]
        if not any(rows):
            return
        for j in req.jobs:
            j.unflushed = []
        req.pend.stream_q.put(("chunk", rows))

    def _finish(self, req: _SlotReq) -> None:
        with self._cv:
            if req in self._queue:
                self._queue.remove(req)
        pend = req.pend
        outs = [list(j.tokens[: j.max_new]) for j in req.jobs]
        n_tokens = sum(len(o) for o in outs)
        self._events.emit(
            "serve_request",
            rows=len(req.jobs),
            new_tokens=n_tokens,
            latency_s=round(time.time() - req.t_submit, 6),
        )
        if pend.stream_q is not None:
            self._flush_stream(req)
            pend.stream_q.put(("done", n_tokens))
        else:
            pend.outputs = outs
        pend.batched_with = req.batched_with
        pend.done.set()

    def _fail_req(self, req: _SlotReq, e: Exception) -> None:
        """Fail ONE request (admission-time errors): its active slots
        retire, everything else keeps running."""
        req.error = e
        with self._cv:
            if req in self._queue:
                self._queue.remove(req)
        for i, job in enumerate(self._slots):
            if job is not None and job.req is req:
                self._retire_slot(i, device=True)
        pend = req.pend
        pend.error = e
        if pend.stream_q is not None:
            pend.stream_q.put(("error", e))
        pend.done.set()

    def _fail_active(self, e: Exception) -> None:
        """A decode chunk failed: every ACTIVE request shares that
        fate (their pool state is gone — the jit donated it), but
        queued requests survive and the pool rebuilds on the next
        admission."""
        reqs = {
            id(j.req): j.req for j in self._slots if j is not None
        }
        self._slots = [None] * self.n_slots
        self._n_active = 0
        self._pool = None  # donated buffers are suspect after a failure
        self._pool_key = None
        self._draft_pool = None  # rides the pool's allocator — same fate
        self._ema = None
        for req in reqs.values():
            req.error = e
            with self._cv:
                if req in self._queue:
                    self._queue.remove(req)
            pend = req.pend
            pend.error = e
            if pend.stream_q is not None:
                pend.stream_q.put(("error", e))
            pend.done.set()


class _Server:
    """Minimal HTTP serving loop over the jitted generator."""

    def __init__(self, port: int, max_new_tokens: int):
        from tpufw.infer import generate_text

        self._generate_text = generate_text
        self._sampling = sampling_from_env()
        (
            self.model,
            self.params,
            self.cfg,
            self.restored,
        ) = build_generator()
        # Serving-precision cast (TPUFW_DECODE_DTYPE=bfloat16): decode
        # is HBM-bound and fp32 master weights double the bytes per
        # token. Off by default — bf16 weights perturb logits, and the
        # parity tests pin exact fp32 serving. The draft's weight
        # streaming (k autoregressive steps per tick) matters as much
        # as the target's, so it casts too.
        self.params = _maybe_cast_decode(self.params)
        self.default_new = max_new_tokens
        self._eos_id = eos_from_env()
        self.metrics = _Metrics()
        self._draft = build_draft_generator(self._sampling)
        if self._draft is not None:
            dm, dp, k = self._draft
            self._draft = (dm, _maybe_cast_decode(dp), k)
            self.metrics.register(
                "spec_iterations_total", "spec_emitted_total"
            )
        self.port = port
        self._codec = None
        # Distinct per-request sampling configs admitted so far:
        # sampling is a compiled-program parameter, so an unbounded
        # variety would compile (and cache) unboundedly many programs.
        self._sampling_seen: set = set()
        self._sampling_cap = env_int("max_sampling_configs", 32)
        self._sampling_lock = threading.Lock()
        # Sampled requests must be able to differ across ticks (best-of
        # -n would otherwise return n identical copies): each tick's rng
        # seed is TPUFW_SEED + a monotonic tick index. Within a tick the
        # seed is shared — coalesced rows stay mutually deterministic —
        # and the whole server replays exactly given the same request
        # arrival order and TPUFW_SEED. Only the batcher thread runs
        # _run_tick, so the counter needs no lock. Greedy decode ignores
        # the rng entirely, so default traffic is unaffected. (The slot
        # scheduler keeps the same replay contract with its own pair of
        # namespaced monotonic streams.)
        self._seed_base = env_int("seed", 0)
        self._tick_index = 0
        # Optional serving telemetry (TPUFW_TELEMETRY_DIR): the full
        # Telemetry handle mounted on the server's own registry (so
        # /metrics and the telemetry snapshot render one truth) —
        # event log, capped scheduler span trace (a server runs
        # indefinitely; the interesting spans are at the head), plus
        # the run-health layer: goodput ledger (busy vs. wasted-slot
        # vs. idle), crash flight recorder (role="serve" terminates
        # on SIGTERM after flushing — no GracefulShutdown above us),
        # and the TPUFW_HANG_TIMEOUT_S watchdog around each chunk.
        from tpufw.obs import Telemetry

        self._tel = Telemetry.disabled()
        tdir = env_str("telemetry_dir", "")
        if tdir:
            import atexit

            self._tel = Telemetry.create(
                telemetry_dir=tdir,
                role="serve",
                registry=self.metrics.registry,
                trace_name="trace-serve.json",
                trace_max_events=100_000,
            )
            self._tel.set_run_info(
                backend=_backend_name(),
                model=type(self.model).__name__,
                mesh="serve",
            )
            self._tel.record_config(
                {
                    "serve": {
                        "port": port,
                        "max_new_tokens": max_new_tokens,
                        "slots": env_int("serve_slots", 8),
                        "chunk": env_int("serve_chunk", 0)
                        or env_int("stream_chunk", 16),
                        "page": env_int("serve_page", 0),
                        "kv_quant": env_str("serve_kv_quant", ""),
                    }
                }
            )
            atexit.register(self._tel.close)
        self._events = self._tel.events
        self._tracer: object = self._tel.tracer
        # Scheduler backend: the slot scheduler (decode-step-granular
        # continuous batching) is the default; TPUFW_SERVE_SLOTS=0 opts
        # back into the tick batcher. Speculation COMPOSES with slots
        # now — a TPUFW_DRAFT_MODEL build seeds the scheduler's chunked
        # verify path (unless the TPUFW_SERVE_SPEC_* knobs claim it),
        # instead of silently rerouting all traffic through the tick
        # path as it used to.
        if env_int("serve_slots", 8) > 0:
            spec_kw = {}
            if (
                self._draft is not None
                and env_int("serve_spec_k", 0) == 0
                and not env_str("serve_spec_draft", "")
            ):
                dm, dp, dk = self._draft
                spec_kw = dict(
                    spec_k=dk, spec_draft_built=(dm.cfg, dp)
                )
            self._batcher = _SlotScheduler(
                self.model,
                self.params,
                eos_id=self._eos_id,
                default_sampling=self._sampling,
                metrics=self.metrics,
                seed_base=self._seed_base,
                events=self._events,
                tracer=self._tracer,
                goodput=self._tel.goodput,
                watchdog=self._tel.watchdog,
                perf=self._tel.perf,
                **spec_kw,
            )
        else:
            if self._draft is not None:
                # Legacy whole-batch speculative ticking: only reachable
                # by explicit TPUFW_SERVE_SLOTS=0 opt-out now. Schema'd
                # warn so operators notice the downgrade.
                self._events.emit(
                    "serve_spec",
                    level="warn",
                    k=self._draft[2],
                    mode="tick_fallback",
                    reason="TPUFW_SERVE_SLOTS=0 legacy tick batcher",
                )
            self._batcher = _Batcher(
                self._run_tick, self.metrics, run_stream=self._run_stream
            )
        if env_int("warmup", 1):
            self._warmup()

    def _warmup(self) -> None:
        """Compile serving shape buckets BEFORE the listener binds.
        Decode is unrolled by default, which costs a fresh compile per
        (batch bucket, prompt bucket, max_new bucket) program — ~38 s
        cold on the v5e chip (vs ~4 s scanned) — and without warmup
        that stall lands on the FIRST LIVE REQUEST of each bucket,
        well past typical client timeouts. Each warmup tick runs
        through _run_tick, compiling prefill + decode (+ the draft,
        when speculation is on) at the shortest prompt bucket and the
        default max_new.

        TPUFW_WARMUP_BUCKETS (comma-separated row counts, default
        "1") selects which BATCH buckets to pre-compile — e.g.
        "1,4,16" for a server expecting coalesced concurrent traffic
        (measured on the v5e chip: each un-warmed batch bucket costs
        ~6-35 s on its first live tick; docs/evidence/
        SERVE_TPU_r5.jsonl). Counts are pow2-bucketed like live
        traffic, deduplicated, compiled smallest first. The tick
        counter and speculative counters are restored afterwards so
        warmup is invisible to seed replay and metrics — safe because
        the listener is not up yet, so nothing can scrape or enqueue
        during the window. Disable entirely with TPUFW_WARMUP=0."""
        import sys

        run_new = _pow2_ceil(self.default_new)
        if isinstance(self._batcher, _SlotScheduler):
            # Slot mode: the pool batch is ALWAYS n_slots, so there is
            # no batch-bucket ladder to walk — one tiny request
            # compiles the whole serving path (prefill + insert +
            # decode chunks, including the shrinking tail-k programs)
            # and leaves the default pool warm. The counters it moved
            # and the rng-stream indices are restored so warmup stays
            # invisible to scrapes and to seed replay.
            try:
                self._batcher.submit([[1]], self.default_new, None)
            except Exception as e:  # noqa: BLE001
                print(f"serve: warmup skipped: {e}", file=sys.stderr)
            finally:
                self._batcher.reset_after_warmup()
                self.metrics.reset(
                    "ticks_total",
                    "tick_rows_total",
                    "tokens_generated_total",
                    "retired_rows_total",
                    "wasted_slot_steps_total",
                    "pool_switches_total",
                )
                if self._batcher.page:
                    # Paged-only names: resetting in contiguous mode
                    # would CREATE them (reset = zero the counter),
                    # leaking paged series into legacy /metrics.
                    self.metrics.reset(
                        "prefix_hits_total",
                        "prefix_misses_total",
                        "pages_freed_total",
                    )
                if self._batcher.spec_k:
                    # Gated like the registration: the warmup request's
                    # speculative passes must stay invisible to scrapes.
                    reg = self.metrics.registry
                    reg.counter(
                        "tpufw_spec_wasted_draft_flops_total"
                    ).reset()
                    self._batcher._spec_accept_sum = 0.0
                    self._batcher._spec_accept_rows = 0
                    reg.gauge("tpufw_spec_accept_rate").set(0.0)
                    reg.gauge("tpufw_spec_fallback_slots").set(0.0)
                self.metrics.registry.histogram(
                    "tpufw_serve_join_latency_seconds"
                ).reset()
                if self._batcher.latency_breakdown:
                    # Gated like the registration: reset() would CREATE
                    # the histograms, leaking the breakdown series into
                    # the legacy scrape when the gate is off.
                    self.metrics.registry.histogram(
                        "tpufw_serve_queue_wait_seconds"
                    ).reset()
                    self.metrics.registry.histogram(
                        "tpufw_serve_prefill_seconds"
                    ).reset()
            return
        tick0 = self._tick_index
        try:
            # Parse inside the try: a malformed env value must degrade
            # to a warning, not keep the server from binding its port.
            # Buckets clamp to the batcher's row cap — a bigger program
            # would compile but never be hit by live coalescing.
            max_rows = env_int("batch_max_rows", 64)
            buckets = sorted({
                min(_pow2_ceil(int(b)), _pow2_ceil(max_rows))
                for b in env_str("warmup_buckets", "1").split(",")
                if b.strip()
            })
            for rows in buckets:
                self._run_tick([[1]] * rows, run_new, None)
        except Exception as e:  # noqa: BLE001
            # Warmup is an optimization; never block serving on it.
            print(f"serve: warmup skipped: {e}", file=sys.stderr)
        finally:
            self._tick_index = tick0
            if self._draft is not None:
                self.metrics.reset(
                    "spec_iterations_total", "spec_emitted_total"
                )

    def admit_sampling(self, sampling) -> bool:
        """True if this non-default config is within the server's
        distinct-config budget (TPUFW_MAX_SAMPLING_CONFIGS, default
        32); known configs are always admitted."""
        with self._sampling_lock:
            if sampling in self._sampling_seen:
                return True
            if len(self._sampling_seen) >= self._sampling_cap:
                return False
            self._sampling_seen.add(sampling)
            return True

    def _model_for(self, longest: int, max_new: int):
        """KV cache sized to the request, not the model max: the
        smallest pow-2 cache variant covering this tick (plus the
        speculative path's k+1 bonus slack), capped at the model max.
        Attention/update traffic per step scales with cache length —
        a 256-token chat on an 8k-cache model would otherwise pay 32x
        the KV bytes; masking makes the result bit-identical
        (never-written slots carry segment 0, tests/test_infer.py).
        Variants are built inline: flax modules hash structurally, so
        equal configs hit the generate jit cache without memoization."""
        import dataclasses

        slack = (self._draft[2] + 1) if self._draft else 0
        n = _cache_bucket(
            longest + max_new + slack, self.model.cfg.max_seq_len
        )
        if n == self.model.cfg.max_seq_len:
            return self.model
        return type(self.model)(
            dataclasses.replace(self.model.cfg, max_seq_len=n)
        )

    def codec(self):
        if self._codec is None:
            self._codec = text_codec()
        return self._codec

    def _gauge_values(self) -> dict:
        """Point-in-time gauges for /metrics — one source of truth in
        the scheduler, refreshed at scrape time. Slot mode adds the
        occupancy pair (occupied/total IS the continuous-batching
        utilization a dashboard divides)."""
        g = {
            "queue_depth": float(self._batcher.queue_depth),
            "uptime_seconds": time.time() - _T0,
        }
        # Refresh goodput at scrape time too (the ledger otherwise
        # publishes only at close, and a server rarely closes).
        self._tel.goodput.publish()
        if isinstance(self._batcher, _SlotScheduler):
            g["slots_occupied"] = float(self._batcher.slots_occupied)
            g["slots_total"] = float(self._batcher.slots_total)
            if self._batcher.page:
                g["pages_in_use"] = float(self._batcher.pages_in_use)
                g["pages_total"] = float(self._batcher.pages_total)
            spill = getattr(self._batcher, "_spill", None)
            if spill is not None:
                # Unprefixed KV-fabric series refresh here too (same
                # scrape-time single-source-of-truth contract as the
                # gauges dict; the tier owns the numbers).
                st = spill.stats()
                reg = self.metrics.registry
                reg.gauge("tpufw_kv_spill_pages").set(
                    float(st["ram_pages"]), tier="ram"
                )
                reg.gauge("tpufw_kv_spill_pages").set(
                    float(st["dir_pages"]), tier="dir"
                )
                delta = (
                    st["spilled_bytes_total"]
                    - self._batcher._spill_seen_bytes
                )
                if delta > 0:
                    reg.counter("tpufw_kv_spill_bytes_total").inc(delta)
                    self._batcher._spill_seen_bytes = st[
                        "spilled_bytes_total"
                    ]
        return g

    def _run_tick(
        self, prompts: list[list[int]], max_new: int, sampling=None
    ):
        """One device call for one coalesced tick — only the batcher
        thread runs this, so device work is serialized by construction
        (the old per-request lock is gone). ``sampling`` is a
        per-request override (None = the env default); the batcher
        guarantees every request in the tick shares it.

        Bucket prompt length and batch size so the jitted generate
        specializes on few shapes. The length bucket rides
        pad_prompts' OWN left padding (a max-length filler row forces
        it), so bucketing zeros are real padding — pad_lens masks
        them, and the repetition penalty's seen-set never counts them
        (literal [0]*k prefixes would look like real tokens).
        """
        sampling, seed, padded, real_n, live, model = self._tick_prep(
            prompts, max_new, sampling
        )
        if self._draft is not None:
            import dataclasses

            from tpufw.infer import speculative_generate_text

            draft_model, draft_params, k = self._draft
            if model.cfg.max_seq_len != self.model.cfg.max_seq_len:
                draft_model = type(draft_model)(
                    dataclasses.replace(
                        draft_model.cfg,
                        max_seq_len=model.cfg.max_seq_len,
                    )
                )
            outs, stats = speculative_generate_text(
                draft_model,
                draft_params,
                model,
                self.params,
                padded,
                max_new_tokens=max_new,
                k=k,
                eos_id=self._eos_id,
                # Filler rows (pow-2 + length bucket) must not drag the
                # batch-min acceptance to zero; their outputs are
                # sliced off below anyway.
                live_rows=live,
                sampling=sampling,
                seed=seed,
                prefill_chunk_size=env_int("prefill_chunk", 0) or None,
            )
            # Draft-quality observability: emitted/iterations is the
            # mean accepted tokens per verify pass (k+1 max) — THE
            # number that says whether the draft is paying for itself.
            # rate(spec_emitted)/rate(spec_iterations) gives the live
            # acceptance from the same two counters.
            self.metrics.inc(
                "spec_iterations_total", stats["iterations"]
            )
            self.metrics.inc("spec_emitted_total", stats["emitted"])
            return outs[:real_n]
        outs = self._generate_text(
            model,
            self.params,
            padded,
            max_new_tokens=max_new,
            sampling=sampling,
            seed=seed,
            eos_id=self._eos_id,
            live_rows=live,
            prefill_chunk_size=env_int("prefill_chunk", 0) or None,
        )
        return outs[:real_n]

    def _tick_prep(self, prompts, max_new, sampling):
        """ONE copy of the per-tick preamble shared by the coalesced
        and streaming paths: env-default sampling resolution, the
        monotonic tick seed (batcher thread only — no lock), prompt
        length bucketing with the filler row, and the request-sized
        cache variant. Returns (sampling, seed, padded, real_n, live,
        model) — ``live`` masks the pow-2 fillers AND the length-bucket
        row so generate's done-mask freezes them at step 1."""
        if sampling is None:
            sampling = self._sampling
        seed = self._seed_base + self._tick_index
        self._tick_index += 1
        longest = _bucket(max(len(p) for p in prompts), 64)
        fill = self._eos_id if self._eos_id is not None else 0
        padded, real_n = _pad_batch(prompts, fill)
        padded = padded + [[fill] * longest]  # length-bucket filler row
        live = [i < real_n for i in range(len(padded))]
        model = self._model_for(longest, max_new)
        return sampling, seed, padded, real_n, live, model

    def _run_stream(self, pend) -> None:
        """Streaming tick (batcher thread only): the ``_tick_prep``
        preamble, then ``generate_text_stream``'s chunk loop — each
        chunk's per-row new tokens go onto the pending's queue the
        moment they exist. ``max_new`` runs at the same pow-2 bucket
        the coalesced path compiles (arbitrary per-request values
        would each compile fresh prefill/tail programs); emission is
        truncated to the REQUESTED length on the way out. One compiled
        chunk program serves every full chunk (and every later stream
        with the same shapes), so time-to-first-token is prefill + one
        chunk instead of the whole completion."""
        from tpufw.infer import generate_text_stream

        run_new = 1
        while run_new < pend.max_new:
            run_new *= 2
        sampling, seed, padded, real_n, live, model = self._tick_prep(
            pend.prompts, run_new, pend.sampling
        )
        emitted = 0  # live rows advance in lockstep; eos rows yield []
        n_tokens = 0  # total across rows (the metric the batch path counts)
        for chunk in generate_text_stream(
            model,
            self.params,
            padded,
            max_new_tokens=run_new,
            chunk_size=env_int("stream_chunk", 16),
            sampling=sampling,
            seed=seed,
            eos_id=self._eos_id,
            live_rows=live,
            prefill_chunk_size=env_int("prefill_chunk", 0) or None,
        ):
            budget = pend.max_new - emitted
            rows = [r[:budget] for r in chunk[:real_n]]
            emitted += max((len(r) for r in rows), default=0)
            n_tokens += sum(len(r) for r in rows)
            pend.stream_q.put(("chunk", rows))
            if emitted >= pend.max_new:
                break  # bucketed tail beyond the request: stop early
        self.metrics.inc("tokens_generated_total", n_tokens)
        pend.stream_q.put(("done", n_tokens))

    def generate(
        self, prompts: list[list[int]], max_new: int, sampling=None
    ):
        """Returns (outputs, batched_with): how many requests shared
        this device tick — surfaced in the response for observability
        (and the concurrency test pins coalescing actually happens)."""
        return self._batcher.submit(prompts, max_new, sampling)

    def generate_stream(
        self, prompts: list[list[int]], max_new: int, sampling=None
    ):
        """Queue-backed streaming: yields per-chunk row outputs as the
        batcher produces them; raises the tick's error if it failed."""
        import queue as _queue

        q: _queue.Queue = _queue.Queue()
        self._batcher.submit_stream(prompts, max_new, sampling, q)
        while True:
            kind, payload = q.get()
            if kind == "chunk":
                yield payload
            elif kind == "done":
                return
            else:
                raise payload

    def serve_forever(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet access log
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(
                        200,
                        {
                            "ok": True,
                            "restored_checkpoint": outer.restored,
                            "uptime_s": round(time.time() - _T0, 1),
                        },
                    )
                elif self.path == "/metrics":
                    # Prometheus text exposition — same scrape contract
                    # as the device plugin's shim endpoint.
                    body = outer.metrics.render(
                        outer._gauge_values()
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.split("?", 1)[0] == "/debug/profile":
                    # On-demand jax.profiler capture (same contract as
                    # the training metrics server's endpoint).
                    profiler = getattr(outer._tel, "profiler", None)
                    if profiler is None:
                        self._reply(
                            404, {"error": "profiler not configured"}
                        )
                        return
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        seconds = float(q.get("seconds", ["2.0"])[0])
                    except ValueError:
                        seconds = 2.0
                    result = profiler.trigger(seconds)
                    code = 409 if "error" in result else 200
                    self._reply(code, result)
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                oai = self.path == "/v1/completions"
                if self.path != "/generate" and not oai:
                    self._reply(404, {"error": "unknown path"})
                    return
                outer.metrics.inc("requests_total")
                t_req = time.time()
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if oai:
                        req = _oai_to_native(req)
                    as_text = "texts" in req
                    if as_text:
                        texts = req["texts"]
                        if (
                            not isinstance(texts, list)
                            or not texts
                            or not all(
                                isinstance(t, str) and t for t in texts
                            )
                        ):
                            raise ValueError(
                                "texts must be a non-empty list of "
                                "non-empty strings"
                            )
                        encode, decode = outer.codec()
                        prompts = [encode(t) for t in texts]
                    else:
                        prompts = req["prompts"]
                        if not prompts or not all(
                            isinstance(p, list) and all(
                                isinstance(t, int) for t in p
                            )
                            for p in prompts
                        ):
                            raise ValueError(
                                "prompts must be a non-empty list of "
                                "token-id lists"
                            )
                    max_new = int(
                        req.get("max_new_tokens", outer.default_new)
                    )
                    if max_new < 1:
                        # Validate BEFORE the batcher: the tick's
                        # pow2-bucketed run length would bypass
                        # generate()'s own >= 1 check and a negative
                        # per-request slice would return
                        # batch-composition-dependent output.
                        raise ValueError("max_new_tokens must be >= 1")
                    # Per-request sampling overrides layered on the env
                    # defaults, through the SAME make_sampling rules
                    # (validation + quantization); the batcher only
                    # coalesces same-config requests.
                    sampling = None
                    knobs = (
                        "temperature", "top_k", "top_p", "min_p",
                        "repetition_penalty",
                    )
                    if any(kb in req for kb in knobs):
                        base = outer._sampling
                        sampling = make_sampling(
                            temperature=req.get(
                                "temperature", base.temperature
                            ),
                            top_k=req.get("top_k", base.top_k),
                            top_p=req.get("top_p", base.top_p),
                            min_p=req.get("min_p", base.min_p),
                            repetition_penalty=req.get(
                                "repetition_penalty",
                                base.repetition_penalty,
                            ),
                        )
                        if sampling == base:
                            # Explicit values equal to the env defaults
                            # coalesce with default-sampling traffic.
                            sampling = None
                        elif not outer.admit_sampling(sampling):
                            raise ValueError(
                                "too many distinct sampling configs "
                                "(each compiles a program); reuse an "
                                "earlier configuration"
                            )
                    if bool(req.get("stream", False)):
                        # SSE streaming: chunks of per-row NEW token
                        # ids as the device produces them, then a done
                        # event (with full texts for "texts" requests —
                        # partial-sequence decodes can split multibyte
                        # characters, so text rides the final event).
                        # With a draft model configured the request
                        # degrades gracefully: the speculative path has
                        # no chunk loop, so the whole completion
                        # arrives as ONE chunk event — same wire
                        # format, no 400.
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "text/event-stream"
                        )
                        self.send_header("Cache-Control", "no-cache")
                        self.end_headers()
                        # Headers are OUT: from here every failure must
                        # end as an SSE event (or a silent stop on a
                        # dead socket) — a second HTTP status line would
                        # corrupt the stream, so nothing below may
                        # escape to the outer 400 handler.
                        dead = False

                        def event(obj) -> None:
                            nonlocal dead
                            if dead:
                                return
                            try:
                                self.wfile.write(
                                    b"data: "
                                    + json.dumps(obj).encode()
                                    + b"\n\n"
                                )
                                self.wfile.flush()
                            except OSError:
                                # Client left mid-stream — the normal
                                # way SSE consumers disconnect. Stop
                                # writing; the generator loop below
                                # still drains the batcher's queue.
                                dead = True

                        rows_acc = [[] for _ in prompts]
                        try:
                            if outer._draft is not None and not isinstance(
                                outer._batcher, _SlotScheduler
                            ):
                                outs, _bw = outer.generate(
                                    prompts, max_new, sampling
                                )
                                rows_acc = outs
                                event({"outputs": outs})
                            else:
                                for rows in outer.generate_stream(
                                    prompts, max_new, sampling
                                ):
                                    for acc, r in zip(rows_acc, rows):
                                        acc.extend(r)
                                    event({"outputs": rows})
                            final = {"done": True}
                            if as_text:
                                # Inside the try: a decode failure must
                                # surface as an error EVENT, not a 400
                                # line spliced into the stream body.
                                final["texts"] = [
                                    decode(o) for o in rows_acc
                                ]
                            event(final)
                        except Exception as e:  # noqa: BLE001
                            outer.metrics.inc("request_errors_total")
                            event(
                                {"error": f"{type(e).__name__}: {e}"}
                            )
                        return
                    outs, batched_with = outer.generate(
                        prompts, max_new, sampling
                    )
                    if oai:
                        # OpenAI responses carry text for token-id
                        # prompts too — decode through the codec.
                        self._reply(
                            200,
                            _oai_response(
                                outs,
                                [outer.codec()[1](o) for o in outs],
                                prompts,
                                max_new,
                                model=str(req.get("_oai_model", "")),
                            ),
                        )
                        return
                    payload = {
                        "outputs": outs,
                        "batched_with": batched_with,
                    }
                    if as_text:
                        payload["texts"] = [decode(o) for o in outs]
                    self._reply(200, payload)
                except Exception as e:  # noqa: BLE001 — serving loop
                    outer.metrics.inc("request_errors_total")
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                finally:
                    outer.metrics.inc(
                        "request_seconds_total", time.time() - t_req
                    )

        httpd = ThreadingHTTPServer(("0.0.0.0", self.port), Handler)
        self.port = httpd.server_address[1]  # resolve port 0 -> actual
        self.httpd = httpd
        print(
            json.dumps(
                {
                    "serving": True,
                    "port": self.port,
                    "model_params": self.cfg.n_params(),
                    "restored_checkpoint": self.restored,
                    "startup_s": round(time.time() - _T0, 1),
                }
            ),
            flush=True,
        )
        httpd.serve_forever()


def main() -> int:
    from tpufw.utils.profiling import enable_compile_cache

    role = env_str("serve_role", "")
    if role:
        # Disaggregated serving: this container is one replica role
        # (prefill/decode page-bundle server, or the front-door
        # router) instead of the monolithic endpoint below.
        from tpufw.serve.roles import main_role

        return main_role(role)
    enable_compile_cache()
    max_new = env_int("max_new_tokens", 16)
    port = env_int("serve_port", 0)
    if port:
        _Server(port, max_new).serve_forever()
        return 0

    prompts_file = env_str("prompts_file", "")
    if prompts_file:
        with open(prompts_file) as f:
            prompts = json.load(f)
    else:
        prompts = DEMO_PROMPTS
    for result in run_batch(prompts, max_new):
        print(json.dumps(result), flush=True)
    print(
        json.dumps(
            {
                "generate_ok": True,
                "n_prompts": len(prompts),
                "max_new_tokens": max_new,
                "total_s": round(time.time() - _T0, 1),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
