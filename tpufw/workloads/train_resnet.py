"""Vision training workload (BASELINE config 2): ResNet-50 on one v5e chip.

The first *real* training proof in the recipe ladder: one `kubectl apply`
of deploy/manifests/03-resnet50-v5e1.yaml runs this on a google.com/tpu: 1
pod; images/sec and loss stream to the pod logs.
"""

from __future__ import annotations

import json

from tpufw.workloads.env import env_bool, env_int, env_str


def main() -> int:
    from tpufw.cluster import initialize_cluster
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()
    cluster = initialize_cluster()

    import jax

    from tpufw.models import ResNetConfig, resnet50
    from tpufw.train import (
        VisionTrainer,
        VisionTrainerConfig,
        synthetic_images,
    )

    # BatchNorm compute dtype (stats stay f32 either way). bfloat16 is
    # the TPU-first default: the early high-resolution stages are
    # HBM-bandwidth-bound and f32 BN doubles their activation traffic
    # (measured on v5e: 1906 -> 2524 img/s at batch 256).
    norm_dtype = env_str("norm_dtype", "bfloat16")
    cfg = VisionTrainerConfig(
        batch_size=env_int("batch_size", 256),
        image_size=env_int("image_size", 224),
        num_classes=env_int("num_classes", 1000),
        total_steps=env_int("total_steps", 50),
        checkpoint_dir=env_str("checkpoint_dir", "") or None,
        checkpoint_every=env_int("checkpoint_every", 100),
        handle_preemption=env_bool("handle_preemption", True),
        preemption_sync_every=env_int("preemption_sync_every", 1),
    )
    print(
        f"tpufw train_resnet: process {cluster.process_id}/"
        f"{cluster.num_processes} devices={jax.devices()}"
    )
    import jax.numpy as jnp

    trainer = VisionTrainer(
        resnet50(cfg.num_classes, norm_dtype=getattr(jnp, norm_dtype)),
        cfg,
    )
    if trainer.maybe_restore():
        print(f"resumed from checkpoint at step {int(trainer.state.step)}")
    else:
        trainer.init_state(seed=env_int("seed", 0))

    flops = ResNetConfig().flops_per_image(cfg.image_size)
    history = trainer.run(
        synthetic_images(cfg.batch_size, cfg.image_size, cfg.num_classes),
        flops_per_image=flops,
        on_metrics=lambda m: print(json.dumps(m.as_dict()), flush=True),
    )
    from tpufw.workloads._common import report_preemption

    report_preemption(trainer)
    if history:
        last = history[-1]
        imgs_per_sec = last.tokens_per_sec_per_chip  # tokens == images
        print(
            f"TRAIN OK: {len(history)} steps, final loss {last.loss:.4f}, "
            f"{imgs_per_sec:.1f} images/s/chip, MFU {last.mfu:.1%}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
