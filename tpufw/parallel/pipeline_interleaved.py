"""Interleaved virtual-stage 1F1B: bubble / v, activations O(v*S).

Vanilla 1F1B (``pipeline_1f1b``) gives each device ONE contiguous
stage, so the pipeline fills and drains in S-1 ticks — the bubble
fraction (S-1)/(M+S-1) is fixed by the device count. This module
implements the Megatron-LM interleaved schedule instead: each device
owns ``v`` NON-contiguous virtual stages ("chunks"), a chunk being
1/v-th of the old stage's layers, so a tick's unit of work shrinks by
v while the fill still takes S-1 (now v-times-smaller) ticks — the
bubble drops to (S-1)/(v*M+S-1) at the cost of v-times more handoffs
per microbatch. Activation stash is a ring of 2*v*S chunk inputs:
O(v*S), independent of M, same bound as the 1F1B ring times v.

Layout: the stage stack is ``[v, S, layers_per_chunk, ...]`` with the
pipe axis on dim 1 (``stage_partition_specs(virtual=True)``); chunk
c = k*S + d holds layers [c*lpc, (c+1)*lpc) and lives on device
d = c mod S — the round-robin assignment that makes the wrap-around
dependency (chunk k on device 0 needs chunk k-1 from device S-1) line
up in lockstep.

Schedule algebra (S stages, v chunks/device, M microbatches with
M % S == 0, G = M/S groups; microbatch j = g*S + r):
  - FORWARD of chunk k, mb (g, r) on device d at tick
      t = d + g*v*S + k*S + r
    i.e. device d's forward sub-ticks are the contiguous window
    [d, d + v*M) and the offset tau = t - d decomposes uniquely as
    g*(v*S) + k*S + r — groups outermost, then chunks, then the S
    microbatches of the group.
  - BACKWARD of chunk k, mb (g, r) on device d at tick
      t = (v*S - 1) + (S-1-d) + g*v*S + (v-1-k)*S + r
    (mirror order: last chunk first). The LAST chunk's forward and
    backward of a microbatch land on device S-1 at the SAME tick, so
    the in-region loss epilogue feeds the cotangent ring directly,
    exactly like 1F1B.
  - total ticks T = v*M + (v+1)*S - 2 (equals 1F1B's M + 2S - 2 at
    v = 1); each device is forward-busy v*M contiguous ticks inside a
    global span of v*M + S - 1, which is the (S-1)/(v*M+S-1) bubble
    accounting pinned by tests.
  - handoffs are the SAME two ppermutes per tick as 1F1B (fwd to d+1,
    cotangent to d-1, consumed next tick), issued early so they
    overlap the tick's compute — v times MORE total handoffs per
    microbatch, each 1x activation size, is the price of the smaller
    bubble (PERF.md quantifies when it pays).
  - a stash written at offset tau_f is read when its chunk's backward
    comes up; lifetime <= 2*v*S - 2 ticks, so ``tau_f mod 2*v*S``
    slots never collide.

Gradient exactness: same manual-VJP discipline as 1F1B (full remat of
the chunk forward from the stash, Megatron f/g custom collectives for
tensor parallelism, masked accumulation + one epilogue reduction).
Parity with the GPipe autodiff path is pinned by
tests/test_pipeline_interleaved.py at the tests/test_pipeline_1f1b.py
tolerance.

Scope: Llama-family dense blocks (incl. Qwen qkv biases), data/fsdp x
tensor composition — the ``_check_1f1b`` envelope. Requires
M % S == 0 and n_layers % (v*S) == 0 (``PipelineConfig.validate``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from tpufw.parallel.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpufw.mesh import AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_TENSOR
from tpufw.models.llama import LlamaConfig
from tpufw.parallel.pipeline import (
    PipelineConfig,
    stage_partition_specs,
)
from tpufw.parallel.pipeline_1f1b import (
    _VOCAB_REDUCE_AXES,
    _check_1f1b,
    _embed_fwd,
    _epilogue_loss,
    _stage_1f1b,
    vocab_scatter_plan,
)

#: Trace-time counters (bumped when the chunk forward is TRACED, not
#: when it runs). tests/test_pipeline_interleaved.py pins that a
#: compile traces the chunk body O(1) times regardless of M — the
#: schedule lives in scan indices, not in unrolled Python.
TRACE_COUNTS = {"chunk_fwd": 0}


def _interleaved_local(
    stage_params,
    head_leaves,
    x_mb,
    tok_mb,
    tgt_mb,
    mask_mb,
    *seg_mb,
    cfg,
    backend,
    n_microbatches,
    n_virtual,
    loss_chunk_size,
    loss_chunk_dtype,
    vocab_scatter=False,
):
    """Per-device schedule body (inside shard_map). Mirrors
    ``_1f1b_local`` with the tick maps generalized to v chunks; see
    the module docstring for the algebra."""
    s = axis_size(AXIS_PIPE)
    sidx = jax.lax.axis_index(AXIS_PIPE)
    tp = axis_size(AXIS_TENSOR) > 1
    # [v, 1, lpc, ...] local shard -> [v, lpc, ...]
    stage_params = jax.tree.map(lambda a: a[:, 0], stage_params)
    m = n_microbatches
    v = n_virtual
    d_model = x_mb.shape[-1]
    mb_shape = x_mb.shape[1:]  # [mb, T, D]
    fwd_perm = [(i, (i + 1) % s) for i in range(s)]
    bwd_perm = [(i, (i - 1) % s) for i in range(s)]
    has_seg = bool(seg_mb)
    seg_all = seg_mb[0] if has_seg else None
    n_slots = 2 * v * s
    vm = v * m

    def chunk_fwd(p, x, seg):
        TRACE_COUNTS["chunk_fwd"] += 1
        return _stage_1f1b(p, x, cfg, backend, seg, tp)

    def pick(tree, k):
        return jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, k, 0, keepdims=False
            ),
            tree,
        )

    vocab = head_leaves["head"].shape[-1]

    def tick(carry, t):
        (
            f_recv, dx_prev, stash, loss_sum,
            g_stage, g_embed, g_fnorm, g_head,
        ) = carry
        # ---- tick -> (group, chunk, rank-in-group) maps -----------
        tau_f = t - sidx
        f_on = (tau_f >= 0) & (tau_f < vm)
        tau_fc = jnp.clip(tau_f, 0, vm - 1)
        kf = (tau_fc % (v * s)) // s
        jf = (tau_fc // (v * s)) * s + tau_fc % s  # g*S + r
        tau_b = t - (v * s - 1) - (s - 1 - sidx)
        b_on = (tau_b >= 0) & (tau_b < vm)
        tau_bc = jnp.clip(tau_b, 0, vm - 1)
        kb = (v - 1) - (tau_bc % (v * s)) // s
        gb = tau_bc // (v * s)
        rb = tau_bc % s
        jb = gb * s + rb

        # Cotangent handoff issued first — overlaps the forward math.
        b_recv = jax.lax.ppermute(dx_prev, AXIS_PIPE, bwd_perm)

        # ---- forward sub-tick (chunk kf, microbatch jf) -----------
        x_in = jnp.where(
            (sidx == 0) & (kf == 0), x_mb[jf], f_recv
        )
        seg_f = seg_all[jf] if has_seg else None
        y = chunk_fwd(pick(stage_params, kf), x_in, seg_f)
        f_send = jax.lax.ppermute(y, AXIS_PIPE, fwd_perm)
        # Stash ring write (guarded like 1F1B: clipped inactive ticks
        # must not clobber a live slot).
        slot_f = tau_fc % n_slots
        old_slot = jax.lax.dynamic_index_in_dim(
            stash, slot_f, 0, keepdims=False
        )
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_on, x_in, old_slot), slot_f, 0
        )

        # Loss epilogue: only the LAST chunk on the LAST device ends
        # the model; same lax.cond economics as 1F1B.
        def head_loss(hl, hidden):
            return _epilogue_loss(
                hl, hidden, tgt_mb[jf], mask_mb[jf], cfg,
                loss_chunk_size, loss_chunk_dtype,
            )

        is_last = sidx == s - 1
        take_loss = is_last & (kf == v - 1) & f_on

        def run_epilogue(hl, hidden):
            return jax.value_and_grad(head_loss, argnums=(0, 1))(
                hl, hidden
            )

        def skip_epilogue(hl, hidden):
            return (
                jnp.zeros((), jnp.float32),
                (
                    jax.tree.map(jnp.zeros_like, hl),
                    jnp.zeros_like(hidden),
                ),
            )

        loss_j, (g_hl_j, dy_j) = jax.lax.cond(
            take_loss, run_epilogue, skip_epilogue, head_leaves, y
        )
        loss_sum = loss_sum + loss_j
        g_fnorm = g_fnorm + g_hl_j["final_norm"]
        g_head = g_head + g_hl_j["head"]

        # ---- backward sub-tick (chunk kb, microbatch jb) ----------
        # The last chunk's backward on the last device consumes ITS
        # OWN same-tick loss cotangent; everything else the ring.
        g_in = jnp.where(
            is_last & (kb == v - 1), dy_j.astype(x_in.dtype), b_recv
        )
        # Stash read: the slot the matching forward wrote, i.e. the
        # forward offset of (gb, kb, rb).
        slot_b = (gb * v * s + kb * s + rb) % n_slots
        x_stash = jax.lax.dynamic_index_in_dim(
            stash, slot_b, 0, keepdims=False
        )
        seg_b = seg_all[jb] if has_seg else None
        params_b = pick(stage_params, kb)
        _, chunk_vjp = jax.vjp(
            lambda p, x: chunk_fwd(p, x, seg_b), params_b, x_stash
        )
        dp_j, dx_j = chunk_vjp(g_in)
        # Masked accumulate into the chunk row of the [v, ...] grads.
        g_stage = jax.tree.map(
            lambda acc, g: jax.lax.dynamic_update_index_in_dim(
                acc,
                jax.lax.dynamic_index_in_dim(
                    acc, kb, 0, keepdims=False
                )
                + jnp.where(b_on, g, 0.0),
                kb,
                0,
            ),
            g_stage,
            dp_j,
        )
        # Chunk 0 on device 0 backprops into the embedding lookup.
        g_embed = g_embed.at[tok_mb[jb]].add(
            jnp.where(
                (sidx == 0) & (kb == 0) & b_on, dx_j, 0.0
            ).astype(g_embed.dtype)
        )

        return (
            f_send, dx_j, stash, loss_sum,
            g_stage, g_embed, g_fnorm, g_head,
        ), None

    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)
    init = (
        zeros_mb,
        zeros_mb,
        jnp.zeros((n_slots, *mb_shape), x_mb.dtype),
        jnp.zeros((), jnp.float32),
        jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), stage_params
        ),
        jnp.zeros((vocab, d_model), jnp.float32),
        jnp.zeros(head_leaves["final_norm"].shape, jnp.float32),
        jnp.zeros(head_leaves["head"].shape, jnp.float32),
    )
    n_ticks = vm + (v + 1) * s - 2
    (
        _, _, _, loss_sum, g_stage, g_embed, g_fnorm, g_head
    ), _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))

    # Same epilogue reductions as 1F1B (see its comments).
    batch_axes = (AXIS_DATA, AXIS_FSDP)
    loss_sum = jax.lax.psum(loss_sum, (AXIS_PIPE, *batch_axes))
    g_fnorm = jax.lax.psum(g_fnorm, (AXIS_PIPE, *batch_axes))
    if vocab_scatter:
        g_embed = jax.lax.psum_scatter(
            g_embed, _VOCAB_REDUCE_AXES, scatter_dimension=0,
            tiled=True,
        )
        g_head = jax.lax.psum_scatter(
            g_head, _VOCAB_REDUCE_AXES, scatter_dimension=1,
            tiled=True,
        )
    else:
        g_embed = jax.lax.psum(g_embed, _VOCAB_REDUCE_AXES)
        g_head = jax.lax.psum(g_head, _VOCAB_REDUCE_AXES)
    g_stage = jax.tree.map(
        lambda g: jax.lax.psum(g, batch_axes), g_stage
    )
    # Re-add the pipe axis the in_spec stripped: [v, ...] -> [v, 1, ...].
    g_stage = jax.tree.map(lambda g: g[:, None], g_stage)
    return loss_sum, g_stage, g_embed, g_fnorm, g_head


def pipeline_interleaved_value_and_grad(
    params: dict,
    batch: dict | jax.Array,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
    backend: Optional[str] = None,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype=None,
) -> tuple[jax.Array, dict]:
    """(mean token loss, grads) through the interleaved schedule —
    drop-in counterpart of ``pipeline_1f1b_value_and_grad`` for params
    in the ``[v, S, ...]`` virtual layout."""
    from tpufw.train.trainer import shift_and_mask

    _check_1f1b(cfg, mesh)
    if not pipe.virtual_layout:
        raise ValueError(
            f"schedule='{pipe.schedule}' is not the interleaved "
            "schedule; use pipeline_1f1b / GPipe entry points"
        )
    if mesh.shape[AXIS_PIPE] != pipe.n_stages:
        raise ValueError(
            f"PipelineConfig.n_stages={pipe.n_stages} but mesh pipe "
            f"axis has size {mesh.shape[AXIS_PIPE]}"
        )
    if not isinstance(batch, dict):
        batch = {"tokens": batch}
    inputs, targets, seg_in, mask = shift_and_mask(batch)
    pipe.validate(cfg, inputs.shape[0])
    backend = backend or cfg.attention_backend
    b, t = inputs.shape
    m = pipe.n_microbatches
    dp = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
    if (b // m) % dp:
        raise ValueError(
            f"microbatch rows {b // m} not divisible over "
            f"data x fsdp = {dp} devices"
        )
    if mask is None:
        mask = jnp.ones_like(targets, jnp.float32)

    x = _embed_fwd(params["embed"], inputs, cfg.dtype)
    mbd = lambda a: a.reshape(m, b // m, *a.shape[1:])  # noqa: E731
    head_leaves = {
        "final_norm": params["final_norm"],
        "head": params["head"],
    }

    row = (AXIS_DATA, AXIS_FSDP)
    mb4 = P(None, row, None, None)
    mb3 = P(None, row, None)
    stage_specs = stage_partition_specs(
        params["stages"], virtual=True
    )
    hl_specs = {"final_norm": P(), "head": P()}
    scatter, embed_spec, head_spec = vocab_scatter_plan(
        params["head"].shape[-1], mesh
    )
    local = partial(
        _interleaved_local,
        cfg=cfg,
        backend=backend,
        n_microbatches=m,
        n_virtual=pipe.n_virtual,
        loss_chunk_size=loss_chunk_size,
        loss_chunk_dtype=loss_chunk_dtype,
        vocab_scatter=scatter,
    )
    args = [
        params["stages"], head_leaves, mbd(x), mbd(inputs),
        mbd(targets), mbd(mask.astype(jnp.float32)),
    ]
    in_specs = [stage_specs, hl_specs, mb4, mb3, mb3, mb3]
    if seg_in is not None:
        args.append(mbd(seg_in.astype(jnp.int32)))
        in_specs.append(mb3)
    loss_sum, g_stage, g_embed, g_fnorm, g_head = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), stage_specs, embed_spec, P(), head_spec),
        check_vma=False,
    )(*args)

    n_tok = jnp.maximum(mask.sum(), 1.0)
    inv = (1.0 / n_tok).astype(jnp.float32)
    grads = {
        "embed": (g_embed * inv).astype(params["embed"].dtype),
        "stages": jax.tree.map(
            lambda g, p: (g * inv).astype(p.dtype),
            g_stage,
            params["stages"],
        ),
        "final_norm": (g_fnorm * inv).astype(
            params["final_norm"].dtype
        ),
        "head": (g_head * inv).astype(params["head"].dtype),
    }
    return loss_sum / n_tok, grads
