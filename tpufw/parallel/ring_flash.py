"""Ring attention with the Pallas flash kernel per shard (VERDICT r1 item 5).

The einsum ring (tpufw.parallel.ring) holds one [B, H, L, L] logits block
per chunk step — fine as a reference, but it caps the per-device context at
whatever a materialized logits block allows, defeating the point of
sequence parallelism. Here each ring step runs the blockwise flash kernel
(tpufw.ops.flash) on the resident q shard against the visiting kv chunk, so
per-device memory is O(L·D) regardless of total context length:

  memory     einsum ring:  O(L²)  per device per step
             flash ring:   O(L)   (online softmax in VMEM)

Forward: chunks merge by their log-sum-exp — for normalized partial
outputs o₁, o₂ with lse₁, lse₂:  o = w₁o₁ + w₂o₂, wᵢ = exp(lseᵢ - lse₁₊₂).

Backward is the flash trick lifted to the ring: a custom VJP recomputes
per-chunk probabilities from (q, k_chunk, GLOBAL lse) — the same kernels
as single-device flash backward (tpufw.ops.flash._flash_bwd_impl), called
once per visiting chunk — while (k, v, dk_acc, dv_acc) rotate together
around the ring; after n rotations each chunk's gradient accumulator is
back on its owner with every device's contribution summed.

Causality at chunk granularity is a static 3-way case (the chunk-vs-chunk
position is data-dependent only through ``axis_index``): kv chunk entirely
before the q shard -> full attention; the diagonal chunk -> causal; after
-> no contribution. ``lax.switch`` selects between three compiled kernels.

Packed-batch ``segment_ids`` ride the ring with their kv chunk exactly as
in the einsum ring; the flash kernels mask cross-segment pairs in-block.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from tpufw.mesh.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQUENCE, AXIS_TENSOR
from tpufw.ops import flash as F
from tpufw.parallel.context import current_mesh

NEG_INF = F.NEG_INF


def _chunk_fwd(case, q, k, v, qseg, kseg, interpret, soft_cap=None):
    """One q-shard x kv-chunk flash forward. Returns (o [B,L,H,D] fp32
    normalized, lse [B,H,L] fp32). case: 0 full / 1 causal-diag / 2 empty."""
    b, l, h, d = q.shape

    def run(causal):
        def f(q, k, v, qseg, kseg):
            out, res = F._flash_fwd_impl(q, k, v, qseg, kseg, causal,
                                         interpret, soft_cap, None)
            lse = res[-1][:, :, 0, :l]  # un-pad [B,H,1,Tp] -> [B,H,L]
            return out.astype(jnp.float32), lse

        return f

    def empty(q, k, v, qseg, kseg):
        return (
            jnp.zeros((b, l, h, d), jnp.float32),
            jnp.full((b, h, l), NEG_INF, jnp.float32),
        )

    return jax.lax.switch(
        case, (run(False), run(True), empty), q, k, v, qseg, kseg
    )


def _chunk_bwd(
    case, q, k, v, qseg, kseg, out, lse_pad, g, interpret, soft_cap=None
):
    """Per-chunk gradients via the flash backward kernels with the GLOBAL
    lse. Returns (dq, dk, dv) in fp32."""

    def run(causal):
        def f(q, k, v, qseg, kseg, out, lse_pad, g):
            dq, dk, dv, _, _ = F._flash_bwd_impl(
                causal, interpret, soft_cap, None,
                (q, k, v, qseg, kseg, out, lse_pad), g,
            )
            return (
                dq.astype(jnp.float32),
                dk.astype(jnp.float32),
                dv.astype(jnp.float32),
            )

        return f

    def empty(q, k, v, qseg, kseg, out, lse_pad, g):
        return (
            jnp.zeros(q.shape, jnp.float32),
            jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32),
        )

    return jax.lax.switch(
        case, (run(False), run(True), empty),
        q, k, v, qseg, kseg, out, lse_pad, g,
    )


def _merge(out, lse, o_c, lse_c):
    """Merge normalized partials by log-sum-exp (docstring formula)."""
    lse_new = jnp.logaddexp(lse, lse_c)
    w1 = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(lse - lse_new))
    w2 = jnp.where(lse_c <= NEG_INF / 2, 0.0, jnp.exp(lse_c - lse_new))
    # [B,H,L] weights -> [B,L,H,1] to scale [B,L,H,D] outputs.
    t = lambda w: jnp.transpose(w, (0, 2, 1))[..., None]  # noqa: E731
    return t(w1) * out + t(w2) * o_c, lse_new


def _make_local(
    n: int, axis_name: str, interpret: bool, has_seg: bool,
    soft_cap=None,
):
    """Build the per-device custom-VJP ring-flash body for a ring of n."""
    perm = [(i, (i + 1) % n) for i in range(n)]

    def case_of(src, idx):
        # 0 full (chunk before shard), 1 diag (causal), 2 empty (after).
        return jnp.int32(src == idx) + 2 * jnp.int32(src > idx)

    def fwd(q, k, v, qseg, kseg):
        idx = jax.lax.axis_index(axis_name)
        b, l, h, d = q.shape
        out = jnp.zeros((b, l, h, d), jnp.float32)
        lse = jnp.full((b, h, l), NEG_INF, jnp.float32)
        k_cur, v_cur, kseg_cur = k, v, kseg
        for step in range(n):  # unrolled: n is the static mesh-axis size
            src = (idx - step) % n
            o_c, lse_c = _chunk_fwd(
                case_of(src, idx), q, k_cur, v_cur, qseg, kseg_cur,
                interpret, soft_cap,
            )
            out, lse = _merge(out, lse, o_c, lse_c)
            if step < n - 1:
                k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
                v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
                if has_seg:
                    kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        return out.astype(q.dtype), lse

    @jax.custom_vjp
    def local(q, k, v, qseg, kseg):
        return fwd(q, k, v, qseg, kseg)[0]

    def fwd_rule(q, k, v, qseg, kseg):
        out, lse = fwd(q, k, v, qseg, kseg)
        return out, (q, k, v, qseg, kseg, out, lse)

    def bwd_rule(res, g):
        q, k, v, qseg, kseg, out, lse = res
        idx = jax.lax.axis_index(axis_name)
        l = q.shape[1]
        # The flash bwd kernels take lse in the padded [B,H,1,Tp] layout.
        l_pad = -l % 128
        lse_pad = jnp.pad(lse, ((0, 0), (0, 0), (0, l_pad)))[:, :, None, :]
        dq = jnp.zeros(q.shape, jnp.float32)
        k_cur, v_cur, kseg_cur = k, v, kseg
        dk_acc = jnp.zeros(k.shape, jnp.float32)
        dv_acc = jnp.zeros(v.shape, jnp.float32)
        for step in range(n):
            src = (idx - step) % n
            dq_c, dk_c, dv_c = _chunk_bwd(
                case_of(src, idx), q, k_cur, v_cur, qseg, kseg_cur,
                out, lse_pad, g, interpret, soft_cap,
            )
            dq = dq + dq_c
            dk_acc = dk_acc + dk_c
            dv_acc = dv_acc + dv_c
            # Rotate accumulators WITH their chunk every step (n total):
            # after the loop each chunk's grads are home on its owner.
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
            if has_seg:
                kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        return (
            dq.astype(q.dtype),
            dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype),
            None,
            None,
        )

    local.defvjp(fwd_rule, bwd_rule)
    return local


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQUENCE,
    interpret: Optional[bool] = None,
    logits_soft_cap: Optional[float] = None,
) -> jax.Array:
    """Sequence-parallel flash attention. Global shapes q:[B,T,H,D],
    k/v:[B,T,K,D]; sharded over (batch=data+fsdp, seq=sequence,
    heads=tensor) like the einsum ring. Causal only (the LM path): the
    chunk-level case analysis assumes it.
    """
    if not causal:
        raise NotImplementedError(
            "ring_flash_attention is causal-only; use the einsum ring "
            "(impl='einsum') for non-causal sequence parallelism"
        )
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError(
            "ring_flash_attention needs a mesh: pass mesh= or register one "
            "via tpufw.parallel.context.use_mesh(...)"
        )
    if q.shape[1] != k.shape[1]:
        raise ValueError(
            f"ring attention is self-attention only: T={q.shape[1]} != "
            f"S={k.shape[1]}"
        )
    n = mesh.shape[axis_name]
    if interpret is None:
        interpret = mesh.devices.flatten()[0].platform == "cpu"
    has_seg = segment_ids is not None
    cap = None if logits_soft_cap is None else float(logits_soft_cap)
    local = _make_local(n, axis_name, interpret, has_seg, cap)

    spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE, AXIS_TENSOR, None)
    seg_spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE)
    if has_seg:
        seg = segment_ids.astype(jnp.int32)
        fn = shard_map(
            lambda q, k, v, qs, ks: local(q, k, v, qs, ks),
            mesh=mesh,
            in_specs=(spec, spec, spec, seg_spec, seg_spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v, seg, seg)
    fn = shard_map(
        lambda q, k, v: local(q, k, v, None, None),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
