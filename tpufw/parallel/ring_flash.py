"""Ring attention with the Pallas flash kernel per shard (VERDICT r1 item 5).

The einsum ring (tpufw.parallel.ring) holds one [B, H, L, L] logits block
per chunk step — fine as a reference, but it caps the per-device context at
whatever a materialized logits block allows, defeating the point of
sequence parallelism. Here each ring step runs the blockwise flash kernel
(tpufw.ops.flash) on the resident q shard against the visiting kv chunk, so
per-device memory is O(L·D) regardless of total context length:

  memory     einsum ring:  O(L²)  per device per step
             flash ring:   O(L)   (online softmax in VMEM)

Forward: chunks merge by their log-sum-exp — for normalized partial
outputs o₁, o₂ with lse₁, lse₂:  o = w₁o₁ + w₂o₂, wᵢ = exp(lseᵢ - lse₁₊₂).

Backward is the flash trick lifted to the ring: a custom VJP recomputes
per-chunk probabilities from (q, k_chunk, GLOBAL lse) — the same kernels
as single-device flash backward (tpufw.ops.flash._flash_bwd_impl), called
once per visiting chunk — while (k, v, dk_acc, dv_acc) rotate together
around the ring; after n rotations each chunk's gradient accumulator is
back on its owner with every device's contribution summed.

Causality at chunk granularity is a static 3-way case (the chunk-vs-chunk
position is data-dependent only through ``axis_index``): kv chunk entirely
before the q shard -> full attention; the diagonal chunk -> causal; after
-> no contribution. ``lax.switch`` selects between three compiled kernels.

Packed-batch ``segment_ids`` ride the ring with their kv chunk exactly as
in the einsum ring; the flash kernels mask cross-segment pairs in-block.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tpufw.parallel.compat import shard_map

from tpufw.mesh.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQUENCE, AXIS_TENSOR
from tpufw.ops import flash as F
from tpufw.parallel.context import current_mesh

NEG_INF = F.NEG_INF


def _chunk_fwd(
    case, q, k, v, qseg, kseg, interpret, soft_cap=None, window=None,
    offset=0,
):
    """One q-shard x kv-chunk flash forward. Returns (o [B,L,H,D] fp32
    normalized, lse [B,H,L] fp32). case: 0 full / 1 causal-diag / 2 empty.

    ``offset`` is the STATIC global distance of the q shard ahead of the
    visiting kv chunk (step*L on the ring) — with a ``window`` it makes
    the in-kernel (q_pos - k_pos) < window mask see global positions.
    Only the "full" branch uses it (the diagonal branch is only
    reachable at step 0, offset 0); for offset >= L every pair is
    already causal, so causal=False there stays correct."""
    b, l, h, d = q.shape

    def run(causal):
        def f(q, k, v, qseg, kseg):
            out, res = F._flash_fwd_impl(
                q, k, v, qseg, kseg, causal, interpret, soft_cap,
                window, offset=(0 if causal else offset),
            )
            lse = res[-1][:, :, 0, :l]  # un-pad [B,H,1,Tp] -> [B,H,L]
            return out.astype(jnp.float32), lse

        return f

    def empty(q, k, v, qseg, kseg):
        return (
            jnp.zeros((b, l, h, d), jnp.float32),
            jnp.full((b, h, l), NEG_INF, jnp.float32),
        )

    return jax.lax.switch(
        case, (run(False), run(True), empty), q, k, v, qseg, kseg
    )


def _chunk_bwd(
    case, q, k, v, qseg, kseg, out, lse_pad, g, interpret, soft_cap=None,
    window=None, offset=0,
):
    """Per-chunk gradients via the flash backward kernels with the GLOBAL
    lse. Returns (dq, dk, dv) in fp32. ``window``/``offset`` as in
    ``_chunk_fwd``."""

    def run(causal):
        def f(q, k, v, qseg, kseg, out, lse_pad, g):
            dq, dk, dv, _, _ = F._flash_bwd_impl(
                causal, interpret, soft_cap, window,
                (q, k, v, qseg, kseg, out, lse_pad), g,
                offset=(0 if causal else offset),
            )
            return (
                dq.astype(jnp.float32),
                dk.astype(jnp.float32),
                dv.astype(jnp.float32),
            )

        return f

    def empty(q, k, v, qseg, kseg, out, lse_pad, g):
        return (
            jnp.zeros(q.shape, jnp.float32),
            jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32),
        )

    return jax.lax.switch(
        case, (run(False), run(True), empty),
        q, k, v, qseg, kseg, out, lse_pad, g,
    )


def _merge(out, lse, o_c, lse_c):
    """Merge normalized partials by log-sum-exp (docstring formula)."""
    lse_new = jnp.logaddexp(lse, lse_c)
    w1 = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(lse - lse_new))
    w2 = jnp.where(lse_c <= NEG_INF / 2, 0.0, jnp.exp(lse_c - lse_new))
    # [B,H,L] weights -> [B,L,H,1] to scale [B,L,H,D] outputs.
    t = lambda w: jnp.transpose(w, (0, 2, 1))[..., None]  # noqa: E731
    return t(w1) * out + t(w2) * o_c, lse_new


def _n_live_steps(n: int, l: int, window) -> int:
    """How many ring steps can contribute under a sliding window.

    At step s > 0 the visiting chunk sits exactly s*L positions behind
    the q shard, so the closest pair is (s-1)*L + 1 apart; once that
    reaches the window the chunk — and every later (farther) one — is
    statically invisible. This is where windowed ring attention's
    savings come from: ceil-bounded rotations instead of n (e.g. a 4k
    window over 8 x 8k shards runs 2 of 8 steps)."""
    if window is None:
        return n
    s = 1
    while s < n and (s - 1) * l + 1 < window:
        s += 1
    return s


def _make_local(
    n: int, axis_name: str, interpret: bool, has_seg: bool,
    soft_cap=None, window=None,
):
    """Build the per-device custom-VJP ring-flash body for a ring of n."""
    perm = [(i, (i + 1) % n) for i in range(n)]

    def case_of(src, idx):
        # 0 full (chunk before shard), 1 diag (causal), 2 empty (after).
        return jnp.int32(src == idx) + 2 * jnp.int32(src > idx)

    def fwd(q, k, v, qseg, kseg):
        idx = jax.lax.axis_index(axis_name)
        b, l, h, d = q.shape
        steps = _n_live_steps(n, l, window)
        out = jnp.zeros((b, l, h, d), jnp.float32)
        lse = jnp.full((b, h, l), NEG_INF, jnp.float32)
        k_cur, v_cur, kseg_cur = k, v, kseg
        for step in range(steps):  # unrolled: static mesh-axis size
            src = (idx - step) % n
            o_c, lse_c = _chunk_fwd(
                case_of(src, idx), q, k_cur, v_cur, qseg, kseg_cur,
                interpret, soft_cap, window, offset=step * l,
            )
            out, lse = _merge(out, lse, o_c, lse_c)
            if step < steps - 1:
                k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
                v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
                if has_seg:
                    kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        return out.astype(q.dtype), lse

    @jax.custom_vjp
    def local(q, k, v, qseg, kseg):
        return fwd(q, k, v, qseg, kseg)[0]

    def fwd_rule(q, k, v, qseg, kseg):
        out, lse = fwd(q, k, v, qseg, kseg)
        return out, (q, k, v, qseg, kseg, out, lse)

    def bwd_rule(res, g):
        q, k, v, qseg, kseg, out, lse = res
        idx = jax.lax.axis_index(axis_name)
        l = q.shape[1]
        steps = _n_live_steps(n, l, window)
        # The flash bwd kernels take lse in the padded [B,H,1,Tp] layout.
        l_pad = -l % 128
        lse_pad = jnp.pad(lse, ((0, 0), (0, 0), (0, l_pad)))[:, :, None, :]
        dq = jnp.zeros(q.shape, jnp.float32)
        k_cur, v_cur, kseg_cur = k, v, kseg
        dk_acc = jnp.zeros(k.shape, jnp.float32)
        dv_acc = jnp.zeros(v.shape, jnp.float32)
        for step in range(steps):
            src = (idx - step) % n
            dq_c, dk_c, dv_c = _chunk_bwd(
                case_of(src, idx), q, k_cur, v_cur, qseg, kseg_cur,
                out, lse_pad, g, interpret, soft_cap, window,
                offset=step * l,
            )
            dq = dq + dq_c
            dk_acc = dk_acc + dk_c
            dv_acc = dv_acc + dv_c
            # Rotate accumulators WITH their chunk every live step; the
            # final hop home happens below in ONE collective.
            if step < steps - 1:
                k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
                v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
                dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
                dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
                if has_seg:
                    kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        # After steps-1 rotations a chunk owned by device o sits on
        # device (o + steps - 1) % n: one ppermute of distance
        # n - (steps - 1) sends every accumulator home (with a full
        # window this is the same single +1 hop the old loop ended on).
        home = (n - (steps - 1)) % n
        if home:
            perm_home = [(i, (i + home) % n) for i in range(n)]
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm_home)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm_home)
        return (
            dq.astype(q.dtype),
            dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype),
            None,
            None,
        )

    local.defvjp(fwd_rule, bwd_rule)
    return local


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQUENCE,
    interpret: Optional[bool] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel flash attention. Global shapes q:[B,T,H,D],
    k/v:[B,T,K,D]; sharded over (batch=data+fsdp, seq=sequence,
    heads=tensor) like the einsum ring. Causal only (the LM path): the
    chunk-level case analysis assumes it.

    ``sliding_window`` (Mistral/Gemma-local layers) runs in-kernel with
    GLOBAL positions — the per-step chunk distance is static on the
    unrolled ring, so the window needs no traced offsets — and cuts the
    ring short: chunks entirely beyond the window are never computed or
    rotated (``_n_live_steps``).
    """
    if not causal:
        raise NotImplementedError(
            "ring_flash_attention is causal-only; use the einsum ring "
            "(impl='einsum') for non-causal sequence parallelism"
        )
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError(
            "ring_flash_attention needs a mesh: pass mesh= or register one "
            "via tpufw.parallel.context.use_mesh(...)"
        )
    if q.shape[1] != k.shape[1]:
        raise ValueError(
            f"ring attention is self-attention only: T={q.shape[1]} != "
            f"S={k.shape[1]}"
        )
    n = mesh.shape[axis_name]
    if interpret is None:
        interpret = mesh.devices.flatten()[0].platform == "cpu"
    has_seg = segment_ids is not None
    cap = None if logits_soft_cap is None else float(logits_soft_cap)
    win = None if sliding_window is None else int(sliding_window)
    if win is not None and win < 1:
        raise ValueError(f"sliding_window must be >= 1, got {win}")
    local = _make_local(n, axis_name, interpret, has_seg, cap, win)

    spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE, AXIS_TENSOR, None)
    seg_spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE)
    if has_seg:
        seg = segment_ids.astype(jnp.int32)
        fn = shard_map(
            lambda q, k, v, qs, ks: local(q, k, v, qs, ks),
            mesh=mesh,
            in_specs=(spec, spec, spec, seg_spec, seg_spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v, seg, seg)
    fn = shard_map(
        lambda q, k, v: local(q, k, v, None, None),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
