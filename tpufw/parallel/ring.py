"""Ring attention: sequence/context parallelism over the ``sequence`` mesh axis.

Long-context design per SURVEY.md §5: activations are sharded along the
sequence dimension; K/V shards rotate around the ring via
``jax.lax.ppermute`` (XLA lowers it to ICI collective-permute) while each
device accumulates attention for its resident Q shard with online-softmax
merging — attention over a context n_seq times longer than one chip could
hold, with comms riding neighbor ICI links instead of all-gathers.

The global causal mask falls out of absolute positions: device d holds
positions [d*L, (d+1)*L); masks compare global q/k positions, so the
same SPMD code handles the full/partial/empty chunk cases.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tpufw.parallel.compat import shard_map

from tpufw.mesh.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQUENCE, AXIS_TENSOR
from tpufw.ops.attention import _repeat_kv, tanh_soft_cap
from tpufw.parallel.context import current_mesh

NEG_INF = -1e30


def _chunk_attn(
    q, k, v, q_start, k_start, causal, scale, rep, qseg=None, kseg=None,
    soft_cap=None, window=None,
):
    """Attention of local q against one kv chunk; returns (acc, m, l) stats.

    q: [B,T,H,D], k/v: [B,S,K,D] with H = K*rep (GQA repeat happens here,
    post-ppermute, so the ring never rotates repeated bytes).
    qseg [B,T] / kseg [B,S]: packed-batch segment ids; the key-side ids
    rotate around the ring with their kv chunk.
    m/l: [B,H,T,1] running max / normalizer in fp32.
    """
    k = _repeat_kv(k, rep)
    v = _repeat_kv(v, rep)
    logits = (
        jnp.einsum(
            "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    if soft_cap is not None:
        # Position-independent and elementwise: capping per chunk before
        # the online-softmax merge equals capping the full logits.
        logits = tanh_soft_cap(logits, soft_cap)
    mask = None
    if causal or window is not None:
        t, s = q.shape[1], k.shape[1]
        q_pos = q_start + jnp.arange(t)[:, None]
        k_pos = k_start + jnp.arange(s)[None, :]
        if causal:
            mask = (q_pos >= k_pos)[None, None]
        if window is not None:
            near = ((q_pos - k_pos) < window)[None, None]
            mask = near if mask is None else (mask & near)
    if qseg is not None:
        seg_mask = qseg[:, None, :, None] == kseg[:, None, None, :]
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B,H,T,1]
    p = jnp.exp(logits - m)
    # Guard fully-masked chunks: exp(NEG_INF - NEG_INF) would be 1.
    p = jnp.where(m <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhts,bshd->bhtd", p.astype(q.dtype), v).astype(
        jnp.float32
    )
    return acc, m, l


def _ring_attn_local(
    q, k, v, *seg, causal, axis_name, scale, rep, soft_cap, window
):
    """Body run per-device under shard_map. q: [B,L,H,D], k/v: [B,L,K,D].
    ``seg`` is () or (qseg [B,L], kseg [B,L]); kseg rides the ring with kv."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    b, _, h, d = q.shape
    qseg, kseg0 = seg if seg else (None, None)

    m0 = jnp.full((b, h, t_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    # kseg rotates with its kv chunk. ``seg`` is a static (Python-level)
    # choice, so the unsegmented trace carries no dummy array and issues no
    # extra ppermute.
    has_seg = qseg is not None

    def body(step, carry):
        if has_seg:
            k_cur, v_cur, kseg_cur, m, l, acc = carry
        else:
            k_cur, v_cur, m, l, acc = carry
            kseg_cur = None
        src_chunk = (idx - step) % n
        acc_c, m_c, l_c = _chunk_attn(
            q,
            k_cur,
            v_cur,
            q_start=idx * t_local,
            k_start=src_chunk * t_local,
            causal=causal,
            scale=scale,
            rep=rep,
            qseg=qseg,
            kseg=kseg_cur,
            soft_cap=soft_cap,
            window=window,
        )
        m_new = jnp.maximum(m, m_c)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(m_c <= NEG_INF / 2, 0.0, jnp.exp(m_c - m_new))
        l_new = l * alpha + l_c * beta
        acc_new = acc * alpha + acc_c * beta
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        if has_seg:
            kseg_nxt = jax.lax.ppermute(kseg_cur, axis_name, perm)
            return k_nxt, v_nxt, kseg_nxt, m_new, l_new, acc_new
        return k_nxt, v_nxt, m_new, l_new, acc_new

    init = (k, v, kseg0, m0, l0, acc0) if has_seg else (k, v, m0, l0, acc0)
    out_carry = jax.lax.fori_loop(0, n, body, init)
    m, l, acc = out_carry[-3], out_carry[-2], out_carry[-1]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe).astype(q.dtype)  # [B,H,T,D]
    return jnp.transpose(out, (0, 2, 1, 3))


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQUENCE,
    impl: Optional[str] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel attention. q:[B,T,H,D], k/v:[B,S,K,D] global shapes.

    Wraps its own ``shard_map`` over (batch=data+fsdp, seq=sequence,
    heads=tensor); requires a registered current mesh (tpufw.parallel.context)
    or an explicit ``mesh``. T must equal S (self-attention) and divide
    evenly by the sequence-axis size. ``segment_ids`` ([B, T] int) masks
    cross-segment attention for packed batches; the key-side copy rotates
    around the ring with its kv chunk.

    ``impl``: "flash" = Pallas flash kernel per shard (O(L) memory,
    tpufw.parallel.ring_flash — the long-context scaling path); "einsum" =
    materialized per-chunk logits (the reference implementation). Default
    (None) picks flash on TPU for the causal LM path and einsum elsewhere;
    the two are numerically interchangeable (tests/test_ring_flash.py).

    ``logits_soft_cap`` (Gemma) works on both impls (elementwise, so
    per-chunk capping commutes with the online-softmax merge).
    ``sliding_window`` (Mistral/Gemma-local) works on both impls too:
    the flash path passes the ring step's STATIC chunk distance as the
    kernel's position offset, so window masks see global positions, and
    chunks entirely beyond the window skip compute and rotation — a
    window spanning w shards runs ~w of n ring steps.
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError(
            "ring_attention needs a mesh: pass mesh= or register one via "
            "tpufw.parallel.context.use_mesh(...)"
        )
    if sliding_window is not None and sliding_window < 1:
        # Checked here so BOTH impls fail loudly: window=0 would mask
        # every logit (einsum would silently emit uniform-softmax means).
        raise ValueError(
            f"sliding_window must be >= 1, got {sliding_window}"
        )
    if impl is None:
        on_tpu = mesh.devices.flatten()[0].platform == "tpu"
        impl = "flash" if (causal and on_tpu) else "einsum"
    if impl == "flash":
        # sliding_window runs in-kernel: the per-step chunk distance is
        # static on the unrolled ring, so window masks see global
        # positions without traced offsets, and out-of-window chunks
        # skip compute AND rotation (tpufw.parallel.ring_flash).
        from tpufw.parallel.ring_flash import ring_flash_attention

        return ring_flash_attention(
            q, k, v,
            causal=causal,
            segment_ids=segment_ids,
            mesh=mesh,
            axis_name=axis_name,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )
    if impl != "einsum":
        raise ValueError(f"unknown ring impl {impl!r}")
    if q.shape[1] != k.shape[1]:
        raise ValueError(
            f"ring attention is self-attention only: T={q.shape[1]} != "
            f"S={k.shape[1]}"
        )
    rep = q.shape[2] // k.shape[2]
    spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE, AXIS_TENSOR, None)
    seg_spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE)
    scale = 1.0 / math.sqrt(q.shape[-1])
    local = functools.partial(
        _ring_attn_local,
        causal=causal,
        axis_name=axis_name,
        scale=scale,
        rep=rep,
        soft_cap=logits_soft_cap,
        window=sliding_window,
    )
    if segment_ids is None:
        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec, seg_spec),
        out_specs=spec,
        check_vma=False,
    )
    seg = segment_ids.astype(jnp.int32)
    return fn(q, k, v, seg, seg)
