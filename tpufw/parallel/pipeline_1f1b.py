"""1F1B pipeline schedule: O(stages) activation memory, manual VJP.

The GPipe schedule (``tpufw.parallel.pipeline``) differentiates the
whole microbatch stream with autodiff, so every in-flight tick's stage
input is a saved residual — peak activation memory grows with the
microbatch count M. This module implements the 1F1B (one-forward-
one-backward) discipline instead: each device interleaves one forward
sub-tick and one backward sub-tick per schedule tick, a microbatch's
backward starts as soon as its loss gradient exists, and a stage input
is stashed only for the ticks its own backward is in flight — a ring
buffer of 2S slots, INDEPENDENT of M. Backward recomputes the stage
forward from the stashed input (full remat, the same trade the bench's
winning ``remat_policy="nothing"`` makes), so steady-state compute is
1 fwd + 1 recompute+bwd per tick — identical total FLOPs to GPipe with
full remat.

Schedule algebra (S stages, M microbatches, ticks t = 0 .. M+2S-3):
  - stage s runs the FORWARD of microbatch ``t - s`` (when in [0, M));
  - stage s runs the BACKWARD of microbatch ``t - 2(S-1) + s``;
  - the last stage's forward of microbatch j lands at tick j + S - 1,
    and its backward of j is at the SAME tick: the per-microbatch loss
    gradient (embed -> stages -> final norm -> head -> CE all live
    INSIDE the shard_map region) feeds straight into the backward ring.
  - both handoffs are produced at tick t-1 and consumed at t: one
    forward ``ppermute`` (s -> s+1) and one cotangent ``ppermute``
    (s -> s-1) per tick. Both are ISSUED so they overlap compute: the
    forward send right after the stage forward (before the epilogue
    and backward math), and the cotangent send deferred — the raw dx
    rides the carry and is permuted at the TOP of the next tick, ahead
    of that tick's forward — so the compiler can hide each transfer
    behind roughly half a tick of block math instead of serializing it
    at the scan-body boundary.
  - a stash written at tick j + s is read at tick j + 2(S-1) - s:
    lifetime <= 2(S-1) ticks, so ``j mod 2S`` slots never collide.

Whole-model gradients come out of one ``lax.scan``: stage-stack grads
accumulate locally (sharded exactly like the stage params); embed /
final-norm / head grads accumulate as masked zeros on non-owning
stages and one cross-axis psum makes them exact. Gradient parity with
the GPipe+autodiff path is pinned by tests/test_pipeline_1f1b.py —
the two schedules must produce the SAME gradients (both are exact).

Memory accounting: "O(stages)" is the ACTIVATION claim. The embed and
head gradient accumulators are full fp32 [V, D]/[D, V] buffers per
device while the scan runs — the scatter-add into the embed grad needs
the full vocab axis, so the carry can't shard it. What CAN shard is
the epilogue: when the vocab divides the pipe x data x fsdp shard
count, the final cross-device reduction is a ``psum_scatter`` instead
of a ``psum``, so the grads LEAVE the region vocab-sharded — 1/(P*D*F)
of the buffer per device from the region boundary onward (same wire
bytes as the psum's reduce-scatter phase, minus its all-gather). The
optimizer update then runs on the sharded grads; XLA re-replicates
only at the param write. Non-divisible vocabs fall back to the plain
replicated psum, decided statically at trace time.

Scope: Llama-family blocks incl. Qwen qkv biases (the shared _block
carries them), composed with data/fsdp batch sharding and Megatron
tensor parallelism. Gemma pairs and MoE are rejected loudly (GPipe
supports them; extend here the same way).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from tpufw.parallel.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpufw.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
)
from tpufw.models.llama import LlamaConfig
from tpufw.ops import rms_norm
from tpufw.parallel.pipeline import (
    PipelineConfig,
    _block,
    _is_gemma,
    _is_mla,
    _is_moe,
    _mla_block,
    stage_partition_specs,
)

# ----------------------------------------------------------------------
# Megatron f/g operators — manual-VJP-safe tensor-parallel collectives
# ----------------------------------------------------------------------
#
# GPipe differentiates the whole shard_map region from OUTSIDE, where
# shard_map's transpose machinery gets psum cotangents right. This
# module calls jax.vjp INSIDE the region, where a plain lax.psum
# transposes to another psum (doubling the cotangent) and the
# rank-varying input cotangent is silently wrong (measured: all stage
# grads diverge under tensor>1). The fix is the classic Megatron
# algebra, stated as custom VJPs: the row-parallel combine ("g") is
# psum forward / identity backward, and the column-parallel entry
# ("f") is identity forward / psum backward. With activations
# replicated across ``tensor``, the local VJP then yields exactly the
# global gradients: sharded weight grads stay local shards, replicated
# leaves (norm scales) come out FULL on every rank (so they are NOT
# psummed over tensor in the accumulation below).


@jax.custom_vjp
def _g_combine(y: jax.Array) -> jax.Array:
    return jax.lax.psum(y, AXIS_TENSOR)


def _g_fwd(y):
    return jax.lax.psum(y, AXIS_TENSOR), None


def _g_bwd(_, ct):
    return (ct,)


_g_combine.defvjp(_g_fwd, _g_bwd)


@jax.custom_vjp
def _f_enter(x: jax.Array) -> jax.Array:
    return x


def _f_fwd(x):
    return x, None


def _f_bwd(_, ct):
    return (jax.lax.psum(ct, AXIS_TENSOR),)


_f_enter.defvjp(_f_fwd, _f_bwd)


def _stage_1f1b(stage_params, x, cfg, backend, seg, tp: bool):
    """The SAME Llama / DeepSeek-MLA block as the GPipe schedule
    (pipeline._block / pipeline._mla_block), with the tensor-parallel
    collectives routed through the f/g operators above so in-region
    ``jax.vjp`` transposes them exactly. tp=False inserts no
    collectives and is bit-identical to GPipe's."""
    tp_ops = (_f_enter, _g_combine) if tp else None
    blk = _mla_block if _is_mla(cfg) else _block

    def body(h, layer_p):
        return blk(layer_p, h, cfg, backend, seg, tp, tp_ops), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def _check_1f1b(cfg, mesh: Mesh) -> None:
    if _is_gemma(cfg) or _is_moe(cfg) or (_is_mla(cfg) and cfg.moe):
        raise NotImplementedError(
            "schedule='1f1b' implements Llama-family and dense "
            "DeepSeek-MLA blocks; use the GPipe schedule for "
            "Gemma/Mixtral"
        )
    for ax in (AXIS_SEQUENCE, AXIS_EXPERT):
        if mesh.shape[ax] != 1:
            raise NotImplementedError(
                f"1f1b composes with data/fsdp/tensor; mesh axis {ax} "
                f"has size {mesh.shape[ax]}"
            )


def _embed_fwd(embed: jax.Array, tokens: jax.Array, dtype) -> jax.Array:
    return embed.astype(dtype)[tokens]


#: Axes the embed/head grad reduction sums over (all ranks hold
#: masked partial sums; ``tensor`` is excluded — the f/g VJP algebra
#: already leaves those grads full on every tensor rank).
_VOCAB_REDUCE_AXES = (AXIS_PIPE, AXIS_DATA, AXIS_FSDP)


def vocab_scatter_plan(vocab: int, mesh: Mesh):
    """Static decision for the embed/head grad epilogue: returns
    ``(scatter, embed_spec, head_spec)``. ``scatter=True`` means the
    in-region reduction is a ``psum_scatter`` over the pipe x data x
    fsdp product and the grads leave the region sharded on their vocab
    axis (embed [V, D] on dim 0, head [D, V] on dim 1); ``False``
    falls back to the replicated psum (vocab not divisible, or a
    single shard where scatter is pointless)."""
    n = (
        mesh.shape[AXIS_PIPE]
        * mesh.shape[AXIS_DATA]
        * mesh.shape[AXIS_FSDP]
    )
    if n > 1 and vocab % n == 0:
        return (
            True,
            P(_VOCAB_REDUCE_AXES, None),
            P(None, _VOCAB_REDUCE_AXES),
        )
    return False, P(), P()


def _epilogue_loss(
    head_leaves: dict,
    hidden: jax.Array,
    targets: jax.Array,
    mask: jax.Array,
    cfg,
    loss_chunk_size: Optional[int],
    loss_chunk_dtype=None,
) -> jax.Array:
    """final RMSNorm -> LM head -> SUM token CE for one microbatch.
    Returns the unnormalized sum (token normalization happens once,
    globally, after the schedule)."""
    from tpufw.ops.loss import token_cross_entropy

    h = rms_norm(hidden, head_leaves["final_norm"], cfg.rms_eps)
    if loss_chunk_size:
        from tpufw.ops.loss import chunked_cross_entropy

        loss_mean, n = chunked_cross_entropy(
            h, head_leaves["head"], targets, mask,
            chunk_size=loss_chunk_size,
            compute_dtype=loss_chunk_dtype or jnp.bfloat16,
        )
        return loss_mean * n
    logits = h.astype(jnp.float32) @ head_leaves["head"].astype(
        jnp.float32
    )
    ce = token_cross_entropy(logits, targets)
    return (ce * mask).sum()


def _1f1b_local(
    stage_params,
    head_leaves,
    x_mb,
    tok_mb,
    tgt_mb,
    mask_mb,
    *seg_mb,
    cfg,
    backend,
    n_microbatches,
    loss_chunk_size,
    loss_chunk_dtype,
    vocab_scatter=False,
):
    """Per-device schedule body (inside shard_map).

    x_mb/tok_mb: [M, mb, T(, D)] embedded inputs + token ids;
    tgt_mb/mask_mb: [M, mb, T] shifted targets + loss mask; seg_mb is
    () or one [M, mb, T] segment-id array. Returns (loss_sum, stage
    grads, embed grad, final-norm grad, head grad) — all unnormalized
    sums over this device's rows; caller psums/normalizes.
    """
    s = axis_size(AXIS_PIPE)
    sidx = jax.lax.axis_index(AXIS_PIPE)
    tp = axis_size(AXIS_TENSOR) > 1
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    m = n_microbatches
    d_model = x_mb.shape[-1]
    mb_shape = x_mb.shape[1:]  # [mb, T, D]
    fwd_perm = [(i, (i + 1) % s) for i in range(s)]
    bwd_perm = [(i, (i - 1) % s) for i in range(s)]
    has_seg = bool(seg_mb)
    seg_all = seg_mb[0] if has_seg else None
    n_slots = 2 * s

    def stage_fwd(p, x, seg):
        return _stage_1f1b(p, x, cfg, backend, seg, tp)

    def mb_loss(hl, hidden, jf):
        return _epilogue_loss(
            hl,
            hidden,
            tgt_mb[jf],
            mask_mb[jf],
            cfg,
            loss_chunk_size,
            loss_chunk_dtype,
        )

    vocab = head_leaves["head"].shape[-1]

    def tick(carry, t):
        (
            f_recv, dx_prev, stash, loss_sum,
            g_stage, g_embed, g_fnorm, g_head,
        ) = carry
        jf = t - sidx                   # forward microbatch index
        jb = t - 2 * (s - 1) + sidx     # backward microbatch index
        f_on = (jf >= 0) & (jf < m)
        b_on = (jb >= 0) & (jb < m)
        jf_c = jnp.clip(jf, 0, m - 1)
        jb_c = jnp.clip(jb, 0, m - 1)

        # Cotangent handoff for THIS tick, issued first: the transfer
        # overlaps the forward sub-tick's block math below (the value
        # was computed last tick; only the wire time remains).
        b_recv = jax.lax.ppermute(dx_prev, AXIS_PIPE, bwd_perm)

        # ---- forward sub-tick -------------------------------------
        x_in = jnp.where(sidx == 0, x_mb[jf_c], f_recv)
        seg_f = seg_all[jf_c] if has_seg else None
        y = stage_fwd(stage_params, x_in, seg_f)
        # Forward handoff issued as soon as y exists — it overlaps the
        # epilogue + backward math of the rest of this tick.
        f_send = jax.lax.ppermute(y, AXIS_PIPE, fwd_perm)
        # Write-guard: inactive sub-ticks clip jf to 0 / m-1, whose
        # slots may hold a LIVE stash (e.g. mb m-1 awaits its backward
        # while drain ticks keep clipping to it) — keep the old value.
        slot_f = jf_c % n_slots
        old_slot = jax.lax.dynamic_index_in_dim(
            stash, slot_f, 0, keepdims=False
        )
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_on, x_in, old_slot), slot_f, 0
        )

        # Last stage: this microbatch's loss + cotangent, NOW. Gated
        # with lax.cond — the head fwd+bwd is comparable to a whole
        # stage forward at real vocab sizes, and only one of S stages
        # uses the result; inside shard_map the scalar predicate stays
        # real control flow, so the other S-1 stages skip it at
        # runtime.
        def head_loss(hl, hidden):
            return mb_loss(hl, hidden, jf_c)

        is_last = sidx == s - 1
        take_loss = is_last & f_on

        def run_epilogue(hl, hidden):
            return jax.value_and_grad(head_loss, argnums=(0, 1))(
                hl, hidden
            )

        def skip_epilogue(hl, hidden):
            return (
                jnp.zeros((), jnp.float32),
                (
                    jax.tree.map(jnp.zeros_like, hl),
                    jnp.zeros_like(hidden),
                ),
            )

        loss_j, (g_hl_j, dy_j) = jax.lax.cond(
            take_loss, run_epilogue, skip_epilogue, head_leaves, y
        )
        loss_sum = loss_sum + loss_j
        g_fnorm = g_fnorm + g_hl_j["final_norm"]
        g_head = g_head + g_hl_j["head"]

        # ---- backward sub-tick ------------------------------------
        # Cotangent in: the last stage's own loss grad for jb (== jf
        # there, same tick); everyone else consumes the ring.
        g_in = jnp.where(is_last, dy_j.astype(x_in.dtype), b_recv)
        x_stash = jax.lax.dynamic_index_in_dim(
            stash, jb_c % n_slots, 0, keepdims=False
        )
        seg_b = seg_all[jb_c] if has_seg else None
        _, stage_vjp = jax.vjp(
            lambda p, x: stage_fwd(p, x, seg_b), stage_params, x_stash
        )
        dp_j, dx_j = stage_vjp(g_in)
        g_stage = jax.tree.map(
            lambda acc, g: acc + jnp.where(b_on, g, 0.0), g_stage, dp_j
        )
        # Stage 0's dx backprops through the embedding lookup:
        # masked scatter-add straight into the carry (no [V, D]
        # intermediate per tick).
        g_embed = g_embed.at[tok_mb[jb_c]].add(
            jnp.where((sidx == 0) & b_on, dx_j, 0.0).astype(
                g_embed.dtype
            )
        )

        # f_send is in flight since the forward sub-tick; the raw dx
        # rides the carry and is permuted at the top of the NEXT tick
        # (same value the old tail-of-tick ppermute delivered, but the
        # send no longer serializes against this tick's compute).
        return (
            f_send, dx_j, stash, loss_sum,
            g_stage, g_embed, g_fnorm, g_head,
        ), None

    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)
    init = (
        zeros_mb,
        zeros_mb,
        jnp.zeros((n_slots, *mb_shape), x_mb.dtype),
        jnp.zeros((), jnp.float32),
        jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), stage_params
        ),
        jnp.zeros((vocab, d_model), jnp.float32),
        jnp.zeros(head_leaves["final_norm"].shape, jnp.float32),
        jnp.zeros(head_leaves["head"].shape, jnp.float32),
    )
    (
        _, _, _, loss_sum, g_stage, g_embed, g_fnorm, g_head
    ), _ = jax.lax.scan(tick, init, jnp.arange(m + 2 * s - 2))

    # Make every accumulator exact across the mesh:
    # - loss / replicated-param grads: sum over pipe (masked zeros on
    #   non-owning stages) and over the batch shards (data, fsdp).
    # - stage grads: sharded over pipe (+tensor per leaf), so psum over
    #   the batch shards only; replicated stage leaves (norms) also
    #   need the tensor sum. d_model axes: no sum (sharded).
    batch_axes = (AXIS_DATA, AXIS_FSDP)
    loss_sum = jax.lax.psum(loss_sum, (AXIS_PIPE, *batch_axes))
    g_fnorm = jax.lax.psum(g_fnorm, (AXIS_PIPE, *batch_axes))
    # Embed/head grads: reduce-scatter onto the vocab axis when the
    # plan allows (see ``vocab_scatter_plan``) so the [V, D]/[D, V]
    # fp32 buffers leave the region sharded; otherwise the replicated
    # psum. ``vocab_scatter`` is static — one branch traces.
    if vocab_scatter:
        g_embed = jax.lax.psum_scatter(
            g_embed, _VOCAB_REDUCE_AXES, scatter_dimension=0,
            tiled=True,
        )
        g_head = jax.lax.psum_scatter(
            g_head, _VOCAB_REDUCE_AXES, scatter_dimension=1,
            tiled=True,
        )
    else:
        g_embed = jax.lax.psum(g_embed, _VOCAB_REDUCE_AXES)
        g_head = jax.lax.psum(g_head, _VOCAB_REDUCE_AXES)
    # The f/g custom VJPs make replicated leaves' grads (norm scales)
    # FULL on every tensor rank already — only the batch-shard sum is
    # needed; sharded leaves' grads are their local shards as-is.
    g_stage = jax.tree.map(
        lambda g: jax.lax.psum(g, batch_axes), g_stage
    )
    # Re-add the leading local stage axis the in_spec stripped.
    g_stage = jax.tree.map(lambda g: g[None], g_stage)
    return loss_sum, g_stage, g_embed, g_fnorm, g_head


def pipeline_1f1b_value_and_grad(
    params: dict,
    batch: dict | jax.Array,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
    backend: Optional[str] = None,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype=None,
) -> tuple[jax.Array, dict]:
    """(mean token loss, grads) through the 1F1B schedule — the drop-in
    counterpart of ``jax.value_and_grad(pipeline_loss)`` with O(S)
    activation memory. ``batch`` is {tokens [+ segment_ids,
    loss_mask]} or a bare token array."""
    from tpufw.train.trainer import shift_and_mask

    _check_1f1b(cfg, mesh)
    if mesh.shape[AXIS_PIPE] != pipe.n_stages:
        raise ValueError(
            f"PipelineConfig.n_stages={pipe.n_stages} but mesh pipe "
            f"axis has size {mesh.shape[AXIS_PIPE]}"
        )
    if not isinstance(batch, dict):
        batch = {"tokens": batch}
    inputs, targets, seg_in, mask = shift_and_mask(batch)
    pipe.validate(cfg, inputs.shape[0])
    backend = backend or cfg.attention_backend
    b, t = inputs.shape
    m = pipe.n_microbatches
    dp = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
    if (b // m) % dp:
        raise ValueError(
            f"microbatch rows {b // m} not divisible over "
            f"data x fsdp = {dp} devices"
        )
    if mask is None:
        mask = jnp.ones_like(targets, jnp.float32)

    x = _embed_fwd(params["embed"], inputs, cfg.dtype)
    mbd = lambda a: a.reshape(m, b // m, *a.shape[1:])  # noqa: E731
    # The embed kernel stays OUTSIDE the region (fwd is the host-side
    # lookup above; its grad is the scatter-add of stage 0's input
    # cotangents, accumulated inside) so the epilogue VJP never
    # materializes a [V, D] zero cotangent per tick.
    head_leaves = {
        "final_norm": params["final_norm"],
        "head": params["head"],
    }

    row = (AXIS_DATA, AXIS_FSDP)
    mb4 = P(None, row, None, None)
    mb3 = P(None, row, None)
    stage_specs = stage_partition_specs(params["stages"])
    hl_specs = {"final_norm": P(), "head": P()}
    scatter, embed_spec, head_spec = vocab_scatter_plan(
        params["head"].shape[-1], mesh
    )
    local = partial(
        _1f1b_local,
        cfg=cfg,
        backend=backend,
        n_microbatches=m,
        loss_chunk_size=loss_chunk_size,
        loss_chunk_dtype=loss_chunk_dtype,
        vocab_scatter=scatter,
    )
    args = [
        params["stages"], head_leaves, mbd(x), mbd(inputs),
        mbd(targets), mbd(mask.astype(jnp.float32)),
    ]
    in_specs = [stage_specs, hl_specs, mb4, mb3, mb3, mb3]
    if seg_in is not None:
        args.append(mbd(seg_in.astype(jnp.int32)))
        in_specs.append(mb3)
    loss_sum, g_stage, g_embed, g_fnorm, g_head = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), stage_specs, embed_spec, P(), head_spec),
        check_vma=False,
    )(*args)

    n_tok = jnp.maximum(mask.sum(), 1.0)
    inv = (1.0 / n_tok).astype(jnp.float32)
    grads = {
        "embed": (g_embed * inv).astype(params["embed"].dtype),
        "stages": jax.tree.map(
            lambda g, p: (g * inv).astype(p.dtype),
            g_stage,
            params["stages"],
        ),
        "final_norm": (g_fnorm * inv).astype(
            params["final_norm"].dtype
        ),
        "head": (g_head * inv).astype(params["head"].dtype),
    }
    return loss_sum / n_tok, grads
