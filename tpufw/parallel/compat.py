"""shard_map and axis helpers across jax versions.

Newer jax exports ``jax.shard_map`` with a ``check_vma`` kwarg; 0.4.x
has ``jax.experimental.shard_map.shard_map`` with the same flag under
its old name ``check_rep``. Newer jax also adds ``jax.lax.axis_size``;
on 0.4.x the equivalent static lookup is ``psum(1, axis)``, which
constant-folds to a Python int at trace time. Every user in tpufw
imports from here so the version split lives in exactly one place.
"""

from __future__ import annotations

import functools

import jax

try:
    from jax import shard_map  # jax >= 0.5
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, /, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)


try:
    axis_size = jax.lax.axis_size  # jax >= 0.5
except AttributeError:  # jax 0.4.x

    def axis_size(axis_name):
        # psum of a Python scalar over a named axis is evaluated
        # statically, so this stays usable in range()/perm lists.
        return jax.lax.psum(1, axis_name)


__all__ = ["axis_size", "shard_map"]
