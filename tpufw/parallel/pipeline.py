"""Pipeline parallelism: GPipe schedule over the ``pipe`` mesh axis.

The reference implements no parallelism of any kind (SURVEY.md §2c); tpufw
treats the device mesh as the communication backend, and this module adds
the pipeline dimension: the layer stack is split into S stages, each stage
owned by one rank of the ``pipe`` mesh axis, and microbatches stream
through the stages with activations handed off by ``lax.ppermute`` —
point-to-point neighbor traffic, the cheapest collective on the mesh.

TPU-first shape of the implementation:
- the schedule is a ``lax.scan`` over M + S - 1 ticks inside one
  ``shard_map`` region — no per-tick Python, one compiled program, and
  the backward pass (autodiff through scan + ppermute) is the reverse
  schedule for free. Bubble fraction is (S-1)/(M+S-1): pick
  ``n_microbatches >> n_stages``.
- stage parameters are stacked on a leading [S] axis sharded over
  ``pipe`` — each device materializes only its own stage's layers.
- within a stage, layers run under ``lax.scan`` over a [layers_per_stage]
  axis (same one-block-compile property as the flax trunk).
- composes with data parallelism (microbatch rows sharded over
  (``data``, ``fsdp``)), tensor parallelism (Megatron head/ffn split
  inside each stage, two psums per block), and — for Mixtral — expert
  parallelism (expert stacks sharded over ``expert``, dispatch sliced
  to local experts, one psum combines); ``sequence`` must be 1.

The block math matches ``tpufw.models.llama`` (RMSNorm -> GQA attention
with RoPE -> SwiGLU) / ``tpufw.models.mixtral`` (routed MoE MLP via the
shared ``tpufw.ops.moe`` routing algebra), reusing the same functional
ops (``tpufw.ops.rms_norm`` / ``multi_head_attention`` /
``tpufw.models.llama.apply_rope``), so a pipeline stage is numerically
the same transformer block — pinned by the parity tests
(tests/test_pipeline.py, tests/test_pipeline_moe.py) against a
sequential evaluation of the identical parameters.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from tpufw.parallel.compat import axis_size, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpufw.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_TENSOR,
)
from tpufw.models.llama import LlamaConfig, apply_rope
from tpufw.ops import multi_head_attention, rms_norm
from tpufw.ops.moe import expert_capacity, route_topk_capacity


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Pipeline schedule hyperparameters on top of a LlamaConfig.

    ``schedule``: "gpipe" (autodiff through the microbatch stream;
    activation memory grows with n_microbatches; supports Llama, Gemma,
    Mixtral incl. expert parallelism), "1f1b" (manual-VJP
    one-forward-one-backward, O(n_stages) activation memory — see
    tpufw.parallel.pipeline_1f1b; Llama-family, data/fsdp/tensor),
    "interleaved" (1F1B over ``n_virtual`` non-contiguous model chunks
    per device — bubble shrinks by the virtual-stage factor, see
    tpufw.parallel.pipeline_interleaved), or "zb1" (ZB-H1-style
    zero-bubble 1F1B: backward split into input-grad and weight-grad
    phases, weight grads scheduled into former drain-bubble ticks —
    see tpufw.parallel.pipeline_zb1).

    ``n_virtual`` is the interleaved schedule's virtual-stage count v:
    each device owns v chunks of n_layers/(v*n_stages) layers, stacked
    ``[v, S, layers_per_chunk, ...]`` (the leading [v] axis replicated,
    [S] sharded over ``pipe``). Other schedules keep v == 1 and the
    canonical ``[S, layers_per_stage, ...]`` stacks."""

    n_stages: int
    n_microbatches: int
    schedule: str = "gpipe"
    n_virtual: int = 1

    @property
    def virtual_layout(self) -> bool:
        """True when stage stacks carry the leading [n_virtual] axis."""
        return self.schedule == "interleaved"

    def validate(self, model: LlamaConfig, batch_size: int) -> None:
        if self.schedule not in ("gpipe", "1f1b", "interleaved", "zb1"):
            raise ValueError(
                f"unknown pipeline schedule {self.schedule!r}; "
                "expected 'gpipe', '1f1b', 'interleaved', or 'zb1'"
            )
        _check_model_split(model, self.n_stages)
        if batch_size % self.n_microbatches:
            raise ValueError(
                f"batch {batch_size} not divisible by "
                f"{self.n_microbatches} microbatches"
            )
        if self.schedule == "interleaved":
            v, s = self.n_virtual, self.n_stages
            if v < 2:
                raise ValueError(
                    "schedule='interleaved' needs n_virtual >= 2 "
                    "(v == 1 is exactly the '1f1b' schedule)"
                )
            if model.n_layers % (v * s):
                raise ValueError(
                    f"n_layers={model.n_layers} not divisible by "
                    f"n_virtual*n_stages={v * s} model chunks"
                )
            if self.n_microbatches % s:
                raise ValueError(
                    f"interleaved schedule groups microbatches by "
                    f"stage count: n_microbatches="
                    f"{self.n_microbatches} % n_stages={s} != 0"
                )
        elif self.n_virtual != 1:
            raise ValueError(
                f"n_virtual={self.n_virtual} only applies to "
                "schedule='interleaved'"
            )

    def bubble_fraction(self) -> float:
        """Analytic bubble fraction in the classic accounting (idle
        time / schedule time with fwd+bwd counted per microbatch):
        GPipe/1F1B (S-1)/(M+S-1); interleaved divides the fill by the
        virtual-stage factor, (S-1)/(vM+S-1); ZB-H1 splits the
        backward into thirds (F = B = W) and refills the bubble with
        deferred W, (S-1)/(3M+S-1). zb1 <= interleaved for v <= 3."""
        s, m = self.n_stages, self.n_microbatches
        if self.schedule == "interleaved":
            return (s - 1) / (self.n_virtual * m + s - 1)
        if self.schedule == "zb1":
            return (s - 1) / (3 * m + s - 1)
        return (s - 1) / (m + s - 1)

    def n_ticks(self) -> int:
        """Scan ticks per train step — each one fwd and/or bwd slot on
        every device plus the ring handoffs. GPipe runs separate fwd
        and bwd sweeps of M+S-1; 1F1B fuses them into M+2(S-1)
        fwd/bwd tick-pairs; interleaved stretches by the chunk factor
        to vM+(v+1)S-2; ZB-H1's three phases drain in M+3(S-1). The
        host-side ``pipeline_tick`` span divides the step wall by this
        (docs/OBSERVABILITY.md)."""
        s, m = self.n_stages, self.n_microbatches
        if self.schedule == "gpipe":
            return 2 * (m + s - 1)
        if self.schedule == "interleaved":
            v = self.n_virtual
            return v * m + (v + 1) * s - 2
        if self.schedule == "zb1":
            return m + 3 * (s - 1)
        return m + 2 * (s - 1)


# ----------------------------------------------------------------------
# Parameters
# ----------------------------------------------------------------------


def _is_moe(cfg) -> bool:
    """MixtralConfig subclasses LlamaConfig: every pipeline entry point
    must branch on this or it would silently build DENSE llama stacks
    (no experts, no router) from an MoE config."""
    from tpufw.models.mixtral import MixtralConfig

    return isinstance(cfg, MixtralConfig)


def _is_gemma(cfg) -> bool:
    from tpufw.models.gemma import GemmaConfig

    return isinstance(cfg, GemmaConfig)


def _is_mla(cfg) -> bool:
    """DeepseekConfig: MLA attention (latent KV factorization), its own
    dataclass — NOT a LlamaConfig subclass, so every dispatch must
    branch here before touching n_kv_heads/head_dim (MLA has neither)."""
    from tpufw.models.deepseek import DeepseekConfig

    return isinstance(cfg, DeepseekConfig)


def _returns_aux(cfg) -> bool:
    """Configs whose forward returns (logits, router aux): Mixtral and
    MoE-FFN DeepSeek. Every aux-threading branch keys off this ONE
    predicate so a new MoE family can't half-plumb."""
    return _is_moe(cfg) or (_is_mla(cfg) and cfg.moe)


def _check_model_split(cfg, n_stages: int) -> None:
    """Model-side pipelineability checks, shared by
    ``PipelineConfig.validate`` (trainer path) and
    ``init_pipeline_params`` (direct callers) so the two can't drift:
    an unchecked config silently builds a truncated or wrong-family
    model."""
    if not (
        isinstance(cfg, LlamaConfig) or _is_gemma(cfg) or _is_mla(cfg)
    ):
        # A foreign config would silently build Llama-shaped stages —
        # wrong model, no error until (at best) a missing attribute
        # deep in init.
        raise NotImplementedError(
            f"pipeline schedules implement Llama-family, Gemma, and "
            f"DeepSeek-MLA blocks; got {type(cfg).__name__}"
        )
    if _is_mla(cfg) and cfg.moe and cfg.first_k_dense > 0:
        # Uniform MoE stacks pipeline fine (_mla_moe_block); mixing
        # dense and routed layers per first_k_dense does not fit the
        # homogeneous per-stage stacks — building it would silently
        # drop the dense/MoE structure.
        raise NotImplementedError(
            "pipelined MLA-MoE stages need UNIFORM layers "
            f"(first_k_dense == 0, got {cfg.first_k_dense}); mixed "
            "dense/MoE stacks use the flax trainer"
        )
    if not getattr(cfg, "causal", True):
        # Both schedules hardcode causal attention; silently training
        # a causal model under a bidirectional config would be the
        # quiet version of wrong.
        raise NotImplementedError(
            "pipeline schedules implement causal attention only; "
            "bidirectional (causal=False) embedding fine-tuning uses "
            "the plain Trainer (tpufw.train.contrastive)"
        )
    if _is_moe(cfg) and getattr(cfg, "attention_qkv_bias", False):
        # The MoE stage stacks don't carry bias leaves; building this
        # config would silently drop the biases.
        raise NotImplementedError(
            "pipelined MoE blocks do not implement attention_qkv_bias"
        )
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by "
            f"{n_stages} stages"
        )
    if _is_gemma(cfg) and (cfg.n_layers // n_stages) % 2:
        raise ValueError(
            f"Gemma pipelines scan local/global PAIRS: layers per "
            f"stage ({cfg.n_layers}/{n_stages}) must be even"
        )


def to_virtual_stages(stages: dict, n_virtual: int, n_stages: int):
    """Regroup stage stacks into the interleaved ``[v, S, lpc, ...]``
    layout. Accepts the canonical ``[S, lps, ...]`` stacks (or any
    ``[a, b, ...]`` leading pair with a*b == n_layers-per-leaf): the
    leading two axes flatten to layer order, then regroup so chunk
    c = k*S + d lands at ``[k, d]`` — device d (pipe rank) owns the
    round-robin chunks d, S+d, 2S+d, ... A pure reshape: on replicated
    arrays it is free; on pipe-sharded arrays XLA inserts the
    re-layout collective once (param conversion, not a per-step op)."""

    def conv(a):
        n_layers = a.shape[0] * a.shape[1]
        lpc = n_layers // (n_virtual * n_stages)
        return a.reshape(n_virtual, n_stages, lpc, *a.shape[2:])

    return jax.tree.map(conv, stages)


def to_canonical_stages(stages: dict, n_stages: int):
    """Inverse of :func:`to_virtual_stages`: ``[v, S, lpc, ...]`` back
    to contiguous ``[n_stages, lps, ...]`` stacks (layer order is the
    flattened [v, S, lpc] index order by construction)."""
    return jax.tree.map(
        lambda a: a.reshape(n_stages, -1, *a.shape[3:]), stages
    )


def init_pipeline_params(
    key: jax.Array, cfg: LlamaConfig, pipe: PipelineConfig
) -> dict:
    """Explicit param pytree; stage weights stacked on a leading [S] axis.

    Initializers match the flax trunk (normal embed, lecun-style fan-in
    scaling elsewhere); stored in ``cfg.param_dtype``. The interleaved
    schedule builds the same layer sequence, regrouped into its
    ``[n_virtual, S, layers_per_chunk, ...]`` stacks.
    """
    flat = pipe
    if pipe.virtual_layout:
        # Same layer sequence as a v*S-stage flat pipeline with the
        # same key — the regroup below is a pure reshape, so flat and
        # virtual inits are bit-identical per layer.
        flat = dataclasses.replace(
            pipe,
            n_stages=pipe.n_stages * pipe.n_virtual,
            schedule="1f1b",
            n_virtual=1,
        )
    params = _init_flat_pipeline_params(key, cfg, flat)
    if pipe.virtual_layout:
        params["stages"] = to_virtual_stages(
            params["stages"], pipe.n_virtual, pipe.n_stages
        )
    return params


def _init_flat_pipeline_params(
    key: jax.Array, cfg: LlamaConfig, pipe: PipelineConfig
) -> dict:
    """Canonical [S, lps, ...] init body (every schedule but the
    virtual-layout one; the interleaved wrapper above regroups it)."""
    s = pipe.n_stages
    _check_model_split(cfg, s)
    lps = cfg.n_layers // s
    d, h, f = cfg.d_model, cfg.n_heads, cfg.d_ff
    # MLA configs have no n_kv_heads/head_dim (factorized projections).
    kh = getattr(cfg, "n_kv_heads", None)
    dh = getattr(cfg, "head_dim", None)
    keys = jax.random.split(key, 9)

    def w(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, jnp.float32)
            / math.sqrt(fan_in)
        ).astype(cfg.param_dtype)

    if _is_mla(cfg):
        # MLA factorized stacks — the functional mirror of
        # tpufw.models.deepseek.MLAttention's expanded/training form
        # (deepseek.py:329): shared latent down-projections (wkv_a,
        # plus wq_a for the compressed-q path) with their RMSNorms,
        # head-expanding up-projections (wq/wq_b, wkv_b), and the
        # dense SwiGLU MLP.
        kvr, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
        stages = {
            "attn_norm": jnp.ones((s, lps, d), jnp.float32),
            "kv_a_norm": jnp.ones((s, lps, kvr), jnp.float32),
            "wkv_a": w(keys[2], (s, lps, d, kvr + dr), d),
            "wkv_b": w(
                keys[3],
                (s, lps, kvr, h, cfg.qk_nope_head_dim + cfg.v_head_dim),
                kvr,
            ),
            "wo": w(
                keys[4], (s, lps, h, cfg.v_head_dim, d),
                h * cfg.v_head_dim,
            ),
            "mlp_norm": jnp.ones((s, lps, d), jnp.float32),
        }
        if cfg.moe:
            # Routed stacks instead of the dense MLP ([E] axis after
            # the layer axis, like Mixtral); the always-on shared
            # experts are one fused SwiGLU of n_shared * moe_d_ff.
            # Built INSTEAD of the dense leaves — materializing dense
            # [S, lps, d, d_ff] stacks just to delete them would be a
            # multi-GB transient at real shapes.
            e, mf = cfg.n_routed_experts, cfg.moe_d_ff
            mkeys = jax.random.split(keys[5], 7)
            stages.update(
                router=w(mkeys[0], (s, lps, d, e), d),
                w_gate=w(mkeys[1], (s, lps, e, d, mf), d),
                w_up=w(mkeys[2], (s, lps, e, d, mf), d),
                w_down=w(mkeys[3], (s, lps, e, mf, d), mf),
            )
            if cfg.n_shared_experts:
                sf = cfg.n_shared_experts * mf
                stages.update(
                    w_shared_gate=w(mkeys[4], (s, lps, d, sf), d),
                    w_shared_up=w(mkeys[5], (s, lps, d, sf), d),
                    w_shared_down=w(mkeys[6], (s, lps, sf, d), sf),
                )
        else:
            stages.update(
                w_gate=w(keys[5], (s, lps, d, f), d),
                w_up=w(keys[6], (s, lps, d, f), d),
                w_down=w(keys[7], (s, lps, f, d), f),
            )
        if cfg.q_lora_rank is None:
            stages["wq"] = w(keys[1], (s, lps, d, h, cfg.qk_head_dim), d)
        else:
            qr = cfg.q_lora_rank
            qkeys = jax.random.split(keys[1], 2)
            stages["wq_a"] = w(qkeys[0], (s, lps, d, qr), d)
            stages["q_a_norm"] = jnp.ones((s, lps, qr), jnp.float32)
            stages["wq_b"] = w(
                qkeys[1], (s, lps, qr, h, cfg.qk_head_dim), qr
            )
        return {
            "embed": jax.random.normal(
                keys[0], (cfg.vocab_size, d), jnp.float32
            ).astype(cfg.param_dtype),
            "stages": stages,
            "final_norm": jnp.ones((d,), jnp.float32),
            "head": w(keys[8], (d, cfg.vocab_size), d),
        }

    if _is_gemma(cfg):
        # Pair layout (local sliding-window block + global block), the
        # functional mirror of tpufw.models.gemma.GemmaPair: stage
        # stacks are [S, pairs_per_stage, ...]; sandwich norms store the
        # (1 + w) offset (zeros init); embeddings are tied (no head)
        # and stored at 1/sqrt(d) for the sqrt(d) lookup scaling.
        pairs = lps // 2

        def block(k):
            ks = jax.random.split(k, 7)
            return {
                "pre_attn_norm": jnp.zeros((s, pairs, d), jnp.float32),
                "post_attn_norm": jnp.zeros((s, pairs, d), jnp.float32),
                "pre_mlp_norm": jnp.zeros((s, pairs, d), jnp.float32),
                "post_mlp_norm": jnp.zeros((s, pairs, d), jnp.float32),
                "wq": w(ks[0], (s, pairs, d, h, dh), d),
                "wk": w(ks[1], (s, pairs, d, kh, dh), d),
                "wv": w(ks[2], (s, pairs, d, kh, dh), d),
                "wo": w(ks[3], (s, pairs, h, dh, d), h * dh),
                "w_gate": w(ks[4], (s, pairs, d, f), d),
                "w_up": w(ks[5], (s, pairs, d, f), d),
                "w_down": w(ks[6], (s, pairs, f, d), f),
            }

        return {
            "embed": (
                jax.random.normal(
                    keys[0], (cfg.vocab_size, d), jnp.float32
                )
                / math.sqrt(d)
            ).astype(cfg.param_dtype),
            "stages": {
                "local": block(keys[1]),
                "global": block(keys[2]),
            },
            "final_norm": jnp.zeros((d,), jnp.float32),
        }

    if _is_moe(cfg):
        # Expert stacks carry an [E] axis after the layer axis —
        # [S, lps, E, in, out] — which stage_partition_specs maps onto
        # the ``expert`` mesh axis (pp x ep). The router stays
        # replicated: its logits must cover ALL experts on every rank
        # so the capacity/slot assignment agrees globally.
        e = cfg.n_experts
        mkeys = jax.random.split(keys[8], 3)
        return {
            "embed": jax.random.normal(
                keys[0], (cfg.vocab_size, d), jnp.float32
            ).astype(cfg.param_dtype),
            "stages": {
                "attn_norm": jnp.ones((s, lps, d), jnp.float32),
                "wq": w(keys[1], (s, lps, d, h, dh), d),
                "wk": w(keys[2], (s, lps, d, kh, dh), d),
                "wv": w(keys[3], (s, lps, d, kh, dh), d),
                "wo": w(keys[4], (s, lps, h, dh, d), h * dh),
                "moe_norm": jnp.ones((s, lps, d), jnp.float32),
                "router": w(keys[5], (s, lps, d, e), d),
                "w_gate": w(keys[6], (s, lps, e, d, f), d),
                "w_up": w(keys[7], (s, lps, e, d, f), d),
                "w_down": w(mkeys[0], (s, lps, e, f, d), f),
            },
            "final_norm": jnp.ones((d,), jnp.float32),
            "head": w(mkeys[1], (d, cfg.vocab_size), d),
        }

    stages = {
        "attn_norm": jnp.ones((s, lps, d), jnp.float32),
        "wq": w(keys[1], (s, lps, d, h, dh), d),
        "wk": w(keys[2], (s, lps, d, kh, dh), d),
        "wv": w(keys[3], (s, lps, d, kh, dh), d),
        "wo": w(keys[4], (s, lps, h, dh, d), h * dh),
        "mlp_norm": jnp.ones((s, lps, d), jnp.float32),
        "w_gate": w(keys[5], (s, lps, d, f), d),
        "w_up": w(keys[6], (s, lps, d, f), d),
        "w_down": w(keys[7], (s, lps, f, d), f),
    }
    if getattr(cfg, "attention_qkv_bias", False):
        # Qwen-2 family: zero-init biases on q/k/v only (o and the MLP
        # stay bias-free), mirroring the flax Attention's projection
        # use_bias — tpufw/models/llama.py Attention.__call__.
        stages["bq"] = jnp.zeros((s, lps, h, dh), jnp.float32)
        stages["bk"] = jnp.zeros((s, lps, kh, dh), jnp.float32)
        stages["bv"] = jnp.zeros((s, lps, kh, dh), jnp.float32)
    return {
        "embed": jax.random.normal(
            keys[0], (cfg.vocab_size, d), jnp.float32
        ).astype(cfg.param_dtype),
        "stages": stages,
        "final_norm": jnp.ones((d,), jnp.float32),
        "head": w(keys[8], (d, cfg.vocab_size), d),
    }


#: Which axis of each stage-stack leaf shards over ``tensor``
#: (Megatron-style): q/k/v split output heads, o splits input heads,
#: gate/up split d_ff columns, down splits d_ff rows — so each block
#: needs exactly two psums (post-attention, post-MLP). Axes are counted
#: FROM THE END so one table covers the Llama ([S, lps, ...]), Gemma
#: ([S, pairs, ...]), and Mixtral expert ([S, lps, E, in, out]) stack
#: ranks: the contraction dims sit at fixed offsets from the tail in
#: all three layouts.
_TENSOR_LEAF_AXIS = {
    "wq": -2, "wk": -2, "wv": -2,  # [..., d, H, dh] -> head axis
    "wo": -3,                      # [..., H, dh, d] -> head axis
    "bq": -2, "bk": -2, "bv": -2,  # [..., H, dh] -> head axis (Qwen)
    "w_gate": -1, "w_up": -1,      # [..., d, f] -> ffn columns
    "w_down": -2,                  # [..., f, d] -> ffn rows
    # MLA head-expanding kernels split their head axis too; the latent
    # down-projections (wq_a, wkv_a) and latent norms stay REPLICATED —
    # the latents are shared across heads, and splitting them would put
    # an RMSNorm on a partial axis.
    "wq_b": -2,                    # [..., qr, H, qk] -> head axis
    "wkv_b": -2,                   # [..., kvr, H, dn+dv] -> head axis
    # DeepSeek shared experts: one fused SwiGLU, Megatron-split like
    # the dense MLP.
    "w_shared_gate": -1, "w_shared_up": -1,
    "w_shared_down": -2,
}

#: Mixtral expert stacks are rank 5 ([S, lps, E, in, out]); their [E]
#: axis shards over ``expert`` (pp x ep). Dense w_* leaves are rank 4
#: and never match.
_EXPERT_LEAVES = ("w_gate", "w_up", "w_down")


def stage_partition_specs(stages: dict, virtual: bool = False) -> Any:
    """Per-leaf PartitionSpecs for a stage-stack pytree: leading [S]
    axis over ``pipe``, the Megatron tensor split per
    ``_TENSOR_LEAF_AXIS``, and the expert split for rank-5 MoE stacks.
    Used both as ``shard_map`` in_specs and (via
    ``pipeline_param_shardings``) as the physical param layout, so the
    two can't disagree.

    ``virtual=True`` covers the interleaved ``[v, S, lpc, ...]`` layout:
    the pipe axis moves to position 1 (v chunks per device stay local,
    so axis 0 is unsharded). The tensor offsets still work — they count
    from the tail. Expert stacks never reach here (the interleaved
    schedule is dense-only), and the rank-5 expert test is skipped
    because a rank-5 *dense* leaf under the virtual layout would
    misfire on it."""

    def spec(path, leaf):
        name = next(
            (
                k.key
                for k in reversed(path)
                if isinstance(getattr(k, "key", None), str)
            ),
            "",
        )
        if virtual:
            axes: list = [None, AXIS_PIPE, *([None] * (leaf.ndim - 2))]
        else:
            axes = [AXIS_PIPE, *([None] * (leaf.ndim - 1))]
        t = _TENSOR_LEAF_AXIS.get(name)
        if t is not None:
            axes[leaf.ndim + t] = AXIS_TENSOR
        if not virtual and name in _EXPERT_LEAVES and leaf.ndim == 5:
            axes[2] = AXIS_EXPERT
        return P(*axes)

    return jax.tree_util.tree_map_with_path(spec, stages)


def pipeline_param_shardings(
    mesh: Mesh, params: dict, virtual: bool = False
) -> dict:
    """NamedShardings: stage stacks split over ``pipe`` (+ ``tensor``
    on head/ffn axes), rest replicated. ``virtual=True`` for the
    interleaved ``[v, S, ...]`` stacks (pipe on axis 1)."""
    rep = NamedSharding(mesh, P())
    out = {
        "embed": rep,
        "stages": jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            stage_partition_specs(params["stages"], virtual=virtual),
        ),
        "final_norm": rep,
    }
    if "head" in params:
        out["head"] = rep
    return out


# ----------------------------------------------------------------------
# Block / stage math (numerically the tpufw.models.llama block)
# ----------------------------------------------------------------------


def _tp_psum(y: jax.Array, tp: bool) -> jax.Array:
    """Combine row-parallel partial sums over ``tensor``. ``tp`` is a
    trace-time bool: False in the sequential oracle (no mesh axes
    bound) and on tensor=1 meshes (psum would be identity)."""
    return jax.lax.psum(y, AXIS_TENSOR) if tp else y


def _attn_sublayer(
    p: dict, x: jax.Array, cfg: LlamaConfig, backend: str, seg=None,
    tp: bool = False, tp_ops=None,
) -> jax.Array:
    """Pre-norm GQA attention with RoPE + residual add — the half of
    the decoder block shared verbatim by the dense (``_block``) and
    MoE (``_mixtral_block``) layouts. With ``tp`` the head axes of p
    are LOCAL shards; the output projection partial-sum is psummed.

    ``tp_ops`` overrides the two tensor-parallel collectives as an
    (enter, combine) pair — the 1F1B schedule substitutes Megatron f/g
    custom VJPs (pipeline_1f1b) because in-region ``jax.vjp`` cannot
    transpose a plain psum; GPipe's autodiff-from-outside uses the
    defaults (identity enter, plain psum combine)."""
    enter, combine = tp_ops or (
        (lambda h: h), (lambda y: _tp_psum(y, tp))
    )
    dt = cfg.dtype
    positions = jnp.broadcast_to(
        jnp.arange(x.shape[1]), x.shape[:2]
    )
    h = enter(rms_norm(x, p["attn_norm"], cfg.rms_eps))
    q = jnp.einsum("btd,dhk->bthk", h, p["wq"].astype(dt))
    k = jnp.einsum("btd,dhk->bthk", h, p["wk"].astype(dt))
    v = jnp.einsum("btd,dhk->bthk", h, p["wv"].astype(dt))
    if "bq" in p:  # Qwen qkv biases: added pre-RoPE, like the flax path
        q = q + p["bq"].astype(dt)
        k = k + p["bk"].astype(dt)
        v = v + p["bv"].astype(dt)
    rs = getattr(cfg, "rope_scaling", None)
    q = apply_rope(q, positions, cfg.rope_theta, rs)
    k = apply_rope(k, positions, cfg.rope_theta, rs)
    att = multi_head_attention(
        q, k, v, causal=True, segment_ids=seg,
        # Mistral-style uniform window (None for plain Llama).
        sliding_window=getattr(cfg, "sliding_window", None),
        backend=backend,
    )
    return x + combine(
        jnp.einsum("bthk,hkd->btd", att, p["wo"].astype(dt))
    )


def _block(
    p: dict, x: jax.Array, cfg: LlamaConfig, backend: str, seg=None,
    tp: bool = False, tp_ops=None,
):
    """One decoder block; p leaves have no leading layer axis. With
    ``tp`` the head/ffn axes of p are LOCAL shards (Megatron split per
    ``_TENSOR_LEAF_AXIS``); the two partial-sum einsums are psummed
    (or routed through ``tp_ops`` — see ``_attn_sublayer``)."""
    enter, combine = tp_ops or (
        (lambda h: h), (lambda y: _tp_psum(y, tp))
    )
    dt = cfg.dtype
    x = _attn_sublayer(p, x, cfg, backend, seg, tp, tp_ops)
    h = enter(rms_norm(x, p["mlp_norm"], cfg.rms_eps))
    g = jnp.einsum("btd,df->btf", h, p["w_gate"].astype(dt))
    u = jnp.einsum("btd,df->btf", h, p["w_up"].astype(dt))
    x = x + combine(
        jnp.einsum(
            "btf,fd->btd", jax.nn.silu(g) * u, p["w_down"].astype(dt)
        )
    )
    return x


def _mla_attn_sublayer(
    p: dict, x: jax.Array, cfg, backend: str, seg=None,
    tp: bool = False, tp_ops=None,
):
    """MLA attention + residual, numerically the
    tpufw.models.deepseek.MLAttention expanded/training form — shared
    by the dense (``_mla_block``) and MoE (``_mla_moe_block``) layouts.
    Under ``tp`` the head axes of wq/wq_b/wkv_b/wo are LOCAL shards;
    the latent projections (wq_a, wkv_a) run replicated on every rank —
    their outputs are identical across ``tensor``, so the decoupled
    rope key and both latent RMSNorms agree globally, and the only
    collective is the output projection's combine."""
    from tpufw.models.deepseek import apply_rope_interleaved

    enter, combine = tp_ops or (
        (lambda h: h), (lambda y: _tp_psum(y, tp))
    )
    dt = cfg.dtype
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    dv, kvr = cfg.v_head_dim, cfg.kv_lora_rank
    positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])

    # Megatron-f (``enter``) placement: at each COLUMN-PARALLEL input —
    # the operand of a head-sharded einsum — and NOT at the shared h.
    # The latent kernels (wq_a/wkv_a) are replicated, so their inputs
    # need no f; their OUTPUTS (cq, c_kv, k_pe) feed head-local math
    # whose per-rank cotangents are partial sums, and the f's backward
    # psum completes them exactly there. An f at h instead would leave
    # the latent params' grads unreduced (the 1F1B parity test caught
    # this) and double-count the latent path's h-contribution.
    h = rms_norm(x, p["attn_norm"], cfg.rms_eps)
    if "wq" in p:
        q = jnp.einsum("btd,dhk->bthk", enter(h), p["wq"].astype(dt))
    else:  # compressed-q path (V2-236B): q_a -> norm -> q_b
        cq = jnp.einsum("btd,dr->btr", h, p["wq_a"].astype(dt))
        cq = rms_norm(cq, p["q_a_norm"], cfg.rms_eps)
        q = jnp.einsum(
            "btr,rhk->bthk", enter(cq), p["wq_b"].astype(dt)
        )
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    q_pe = apply_rope_interleaved(
        q_pe, positions, cfg.rope_theta, cfg.rope_scaling
    )

    # Shared KV latent + decoupled-rope key (one "head").
    ckv_kr = jnp.einsum("btd,dr->btr", h, p["wkv_a"].astype(dt))
    c_kv = rms_norm(ckv_kr[..., :kvr], p["kv_a_norm"], cfg.rms_eps)
    k_pe = apply_rope_interleaved(
        ckv_kr[..., kvr:][:, :, None, :],
        positions, cfg.rope_theta, cfg.rope_scaling,
    )  # [B, T, 1, dr]
    k_pe = enter(k_pe)  # broadcast over LOCAL heads below
    kv = jnp.einsum(
        "btr,rhd->bthd", enter(c_kv).astype(dt), p["wkv_b"].astype(dt)
    )
    k_nope, v = kv[..., :dn], kv[..., dn:]
    k = jnp.concatenate(
        [k_nope, jnp.broadcast_to(k_pe, (*k_nope.shape[:3], dr))], axis=-1
    )
    q = jnp.concatenate([q_nope, q_pe], axis=-1)
    if backend in ("flash", "ring"):
        # v zero-padded to qk_head_dim, output sliced back — exact
        # (padded value columns contribute zeros), same discipline as
        # the flax MLAttention backend dispatch.
        v_in = jnp.pad(
            v, ((0, 0), (0, 0), (0, 0), (0, cfg.qk_head_dim - dv))
        )
    else:
        v_in = v
    att = multi_head_attention(
        q, k, v_in, causal=True, segment_ids=seg, backend=backend
    )
    if backend in ("flash", "ring"):
        att = att[..., :dv]
    return x + combine(
        jnp.einsum("bthd,hdD->btD", att, p["wo"].astype(dt))
    )


def _mla_block(
    p: dict, x: jax.Array, cfg, backend: str, seg=None,
    tp: bool = False, tp_ops=None,
):
    """One dense-FFN DeepSeek-MLA decoder block: the shared MLA
    attention sublayer + the standard SwiGLU MLP."""
    enter, combine = tp_ops or (
        (lambda h: h), (lambda y: _tp_psum(y, tp))
    )
    dt = cfg.dtype
    x = _mla_attn_sublayer(p, x, cfg, backend, seg, tp, tp_ops)
    hm = enter(rms_norm(x, p["mlp_norm"], cfg.rms_eps))
    g = jnp.einsum("btd,df->btf", hm, p["w_gate"].astype(dt))
    u = jnp.einsum("btd,df->btf", hm, p["w_up"].astype(dt))
    return x + combine(
        jnp.einsum(
            "btf,fd->btd", jax.nn.silu(g) * u, p["w_down"].astype(dt)
        )
    )


def _mla_moe_block(
    p: dict, x: jax.Array, cfg, backend: str, seg=None,
    tp: bool = False, ep: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One MoE-FFN DeepSeek-MLA decoder block (uniform stacks,
    first_k_dense == 0): the shared MLA attention sublayer + the
    DeepSeek MoE FFN — routed experts through the SAME ``_moe_mlp``
    dispatch algebra as pipelined Mixtral (V2 gate conventions: raw
    softmax mass, optional group-limited selection,
    routed_scaling_factor) plus the always-on shared-expert SwiGLU.
    Returns (x, router aux loss)."""
    x = _mla_attn_sublayer(p, x, cfg, backend, seg, tp)
    dt = cfg.dtype
    h = rms_norm(x, p["mlp_norm"], cfg.rms_eps)
    y, aux = _moe_mlp(
        p, h, cfg, None if seg is None else seg > 0, tp, ep
    )
    y = y * cfg.routed_scaling_factor
    if "w_shared_gate" in p:
        g = jnp.einsum("btd,df->btf", h, p["w_shared_gate"].astype(dt))
        u = jnp.einsum("btd,df->btf", h, p["w_shared_up"].astype(dt))
        y = y + _tp_psum(
            jnp.einsum(
                "btf,fd->btd",
                jax.nn.silu(g) * u,
                p["w_shared_down"].astype(dt),
            ),
            tp,
        )
    return x + y, aux


def _moe_mlp(
    p: dict, h: jax.Array, cfg, valid, tp: bool, ep: bool
) -> tuple[jax.Array, jax.Array]:
    """Functional top-k MoE MLP over this device's LOCAL experts.

    Routing (``tpufw.ops.moe.route_topk_capacity`` — the SAME algebra
    as the flax MoEMLP, so the two paths can't drift) runs over ALL
    experts on every rank: the router kernel is replicated and the
    slot/capacity assignment must agree globally. Under ``ep`` each
    rank then slices the dispatch/combine tensors down to its own [E /
    ep] expert stack — no all-to-all is needed because the batch rides
    ``data``/``fsdp``, never ``expert``, so activations are already
    replicated across the expert axis and one psum combines the expert
    partial sums (+ the ``tp`` d_ff partial sums in the same
    collective).

    The routing group is this device's microbatch shard (G = local
    rows x T), i.e. capacity is per (microbatch, data-shard) group —
    the standard pipelined-MoE discipline; the flax path's group is
    the global batch.
    """
    b, t, d = h.shape
    e, k = cfg.n_experts, cfg.experts_per_token
    g = b * t
    capacity = expert_capacity(g, k, e, cfg.capacity_factor)

    logits = jnp.einsum(
        "btd,de->bte",
        h.astype(jnp.float32),
        p["router"].astype(jnp.float32),
    ).reshape(g, e)
    dispatch, combine, aux, z = route_topk_capacity(
        logits, k, capacity,
        valid=None if valid is None else valid.reshape(g),
        dtype=cfg.dtype,
        # Mixtral renormalizes top-k mass; DeepSeek keeps the raw
        # softmax mass and may group-limit selection — both read off
        # the config so the flax and pipelined paths can't drift.
        norm_topk=getattr(cfg, "norm_topk_prob", True),
        group_limit=(
            (cfg.n_group, cfg.topk_group)
            if getattr(cfg, "n_group", 0)
            else None
        ),
    )

    if ep:
        e_local = p["w_gate"].shape[0]
        off = jax.lax.axis_index(AXIS_EXPERT) * e_local
        dispatch = jax.lax.dynamic_slice_in_dim(dispatch, off, e_local, 1)
        combine = jax.lax.dynamic_slice_in_dim(combine, off, e_local, 1)

    dt = cfg.dtype
    xf = h.reshape(g, d).astype(dt)
    xe = jnp.einsum("gec,gd->ecd", dispatch, xf)  # [E_local, C, d]
    gate = jnp.einsum("ecd,edf->ecf", xe, p["w_gate"].astype(dt))
    up = jnp.einsum("ecd,edf->ecf", xe, p["w_up"].astype(dt))
    down = jnp.einsum(
        "ecf,efd->ecd", jax.nn.silu(gate) * up, p["w_down"].astype(dt)
    )
    y = jnp.einsum("gec,ecd->gd", combine, down)
    axes = (AXIS_EXPERT,) * ep + (AXIS_TENSOR,) * tp
    if axes:
        y = jax.lax.psum(y, axes)
    aux_loss = cfg.router_aux_weight * aux + cfg.router_z_weight * z
    return y.reshape(b, t, d), aux_loss


def _mixtral_block(
    p: dict, x: jax.Array, cfg, backend: str, seg=None,
    tp: bool = False, ep: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One Mixtral decoder block (attention + routed MoE MLP); returns
    (x, router aux loss). ``valid`` for routing mirrors the flax
    MixtralBlock: padding rows of packed batches (segment id 0) are
    excluded from routing and capacity."""
    x = _attn_sublayer(p, x, cfg, backend, seg, tp)
    h = rms_norm(x, p["moe_norm"], cfg.rms_eps)
    y, aux = _moe_mlp(
        p, h, cfg, None if seg is None else seg > 0, tp, ep
    )
    return x + y, aux


def _gemma_block(p, x, cfg, backend, seg, window, tp: bool = False):
    """One Gemma-2 block (sandwich (1+w) norms, GeGLU, caps, qpas
    scaling) — the functional mirror of tpufw.models.gemma.GemmaBlock.
    Under ``tp`` the partial sums are combined BEFORE the post-norms
    (RMSNorm is nonlinear; psum must see the full activation)."""
    dt = cfg.dtype
    positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])

    def norm(which, h):
        return rms_norm(h, p[which] + 1.0, cfg.rms_eps)

    h = norm("pre_attn_norm", x)
    q = jnp.einsum("btd,dhk->bthk", h, p["wq"].astype(dt))
    k = jnp.einsum("btd,dhk->bthk", h, p["wk"].astype(dt))
    v = jnp.einsum("btd,dhk->bthk", h, p["wv"].astype(dt))
    rs = getattr(cfg, "rope_scaling", None)
    q = apply_rope(q, positions, cfg.rope_theta, rs)
    k = apply_rope(k, positions, cfg.rope_theta, rs)
    qpas = cfg.query_pre_attn_scalar
    if qpas is not None and float(qpas) != float(cfg.head_dim):
        q = q * (math.sqrt(cfg.head_dim) / math.sqrt(float(qpas)))
    att = multi_head_attention(
        q, k, v, causal=True, segment_ids=seg,
        logits_soft_cap=cfg.attn_logit_soft_cap,
        sliding_window=window,
        backend=backend,
    )
    x = x + norm(
        "post_attn_norm",
        _tp_psum(
            jnp.einsum("bthk,hkd->btd", att, p["wo"].astype(dt)), tp
        ),
    )
    h = norm("pre_mlp_norm", x)
    g = jnp.einsum("btd,df->btf", h, p["w_gate"].astype(dt))
    u = jnp.einsum("btd,df->btf", h, p["w_up"].astype(dt))
    m = _tp_psum(
        jnp.einsum(
            "btf,fd->btd",
            jax.nn.gelu(g, approximate=True) * u,
            p["w_down"].astype(dt),
        ),
        tp,
    )
    return x + norm("post_mlp_norm", m)


def _stage(
    stage_params: dict, x: jax.Array, cfg, backend: str, seg=None,
    tp: bool = False, ep: bool = False,
):
    """Run this stage's [layers_per_stage] blocks via lax.scan; returns
    (out, aux) where aux is the summed router loss of this stage's MoE
    layers (0.0 for dense families). For Gemma the scanned unit is a
    local+global PAIR (the alternation is a static per-block property,
    so it cannot ride a plain layer scan)."""
    if _is_gemma(cfg):
        out, _ = jax.lax.scan(
            _gemma_pair_body(cfg, backend, seg, tp), x, stage_params
        )
        return out, jnp.zeros((), jnp.float32)

    if _returns_aux(cfg):
        moe_blk = _mla_moe_block if _is_mla(cfg) else _mixtral_block

        def moe_body(carry, layer_p):
            h, aux = carry
            h, a = moe_blk(layer_p, h, cfg, backend, seg, tp, ep)
            return (h, aux + a.astype(jnp.float32)), None

        (out, aux), _ = jax.lax.scan(
            moe_body, (x, jnp.zeros((), jnp.float32)), stage_params
        )
        return out, aux

    blk = _mla_block if _is_mla(cfg) else _block

    def body(h, layer_p):
        return blk(layer_p, h, cfg, backend, seg, tp), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out, jnp.zeros((), jnp.float32)


# ----------------------------------------------------------------------
# GPipe schedule
# ----------------------------------------------------------------------


def _gpipe_local(stage_params, x_mb, *seg_mb, cfg, backend):
    """Per-device body (inside shard_map): stream M microbatches through
    the pipe ring. x_mb: [M, mb_local, T, D]; seg_mb is () or one
    [M, mb_local, T] int32 array of segment ids. Returns (outs, aux):
    outs in x_mb's shape (valid data produced on the last stage, zeros
    elsewhere, psum-combined); aux the global-mean router loss scalar
    (0.0 for dense families), replicated on every device."""
    s = axis_size(AXIS_PIPE)
    sidx = jax.lax.axis_index(AXIS_PIPE)
    # Static (trace-time) tensor/expert-parallel degrees: the stage
    # weights' head/ffn/expert axes arrive pre-sharded per
    # _TENSOR_LEAF_AXIS / _EXPERT_LEAVES.
    tp = axis_size(AXIS_TENSOR) > 1
    ep = axis_size(AXIS_EXPERT) > 1
    # Local leading stage dim is 1 after sharding: drop it.
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    m = x_mb.shape[0]
    perm = [(i, (i + 1) % s) for i in range(s)]
    has_seg = bool(seg_mb)
    seg_all = seg_mb[0] if has_seg else None

    def tick(carry, t):
        recv, outs, aux_acc = carry
        x_in = jnp.where(sidx == 0, x_mb[jnp.clip(t, 0, m - 1)], recv)
        if has_seg:
            # Stage sidx processes microbatch t - sidx at tick t (the
            # same invariant the output collection uses). seg_all is
            # replicated across the pipe axis (its spec doesn't mention
            # pipe), so the ids are indexed locally — no need to
            # ppermute them around the ring with the activations.
            seg_in = seg_all[jnp.clip(t - sidx, 0, m - 1)]
        else:
            seg_in = None
        out, aux = _stage(stage_params, x_in, cfg, backend, seg_in, tp, ep)
        nxt = jax.lax.ppermute(out, AXIS_PIPE, perm)
        # Last stage finishes microbatch t-(s-1) at tick t.
        oidx = jnp.clip(t - (s - 1), 0, m - 1)
        valid = (t >= s - 1) & (sidx == s - 1)
        cur = jax.lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, out, cur), oidx, 0
        )
        # Bubble ticks run the stage on clip-duplicated (garbage)
        # microbatches; only ticks where stage sidx holds a REAL
        # microbatch (t - sidx in [0, m)) contribute router loss.
        real = (t >= sidx) & (t < sidx + m)
        aux_acc = aux_acc + jnp.where(real, aux, 0.0)
        return (nxt, outs, aux_acc), None

    zeros = jnp.zeros_like(x_mb[0])
    outs0 = jnp.zeros_like(x_mb)
    # aux rides through the body as shape (1,), never (): jax 0.4.x
    # shard_map autodiff gives residuals the {0: all_axes} out-spec,
    # which is unsatisfiable for a rank-0 residual and raises
    # _SpecError from the transpose. Callers take [0] outside.
    (_, outs, aux_sum), _ = jax.lax.scan(
        tick, (zeros, outs0, jnp.zeros((1,), jnp.float32)),
        jnp.arange(m + s - 1),
    )
    # Non-last stages hold zeros; the psum replicates the real result
    # across the pipe axis (required: `pipe` is unmentioned in out_specs).
    outs = jax.lax.psum(outs, AXIS_PIPE)
    # aux: sum over stages (pipe) = sum over all layers; mean over the
    # m x (data x fsdp shards) routing groups. tensor/expert ranks
    # compute identical copies (router is replicated), so they are NOT
    # psum axes — the result is already replicated across them.
    dp = axis_size(AXIS_DATA) * axis_size(AXIS_FSDP)
    aux = jax.lax.psum(
        aux_sum, (AXIS_PIPE, AXIS_DATA, AXIS_FSDP)
    ) / float(m * dp)
    return outs, aux


def pipeline_forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
    backend: Optional[str] = None,
    segment_ids: Optional[jax.Array] = None,
    return_hidden: bool = False,
) -> jax.Array:
    """Full LM forward with the block stack pipelined: logits [B, T, V]
    (or, with ``return_hidden``, the post-final-norm hidden states
    [B, T, D] for the chunked-vocab CE path, which applies the head
    per sequence chunk and never materializes full logits). For MoE
    configs the return value is a TUPLE (logits_or_hidden, aux): the
    mean router loss (already /n_layers, matching the flax Mixtral
    convention) that the training objective must add.

    Embedding and the head run outside the pipeline region (they are a
    small fraction of compute and live replicated / batch-sharded);
    everything between — the whole layer stack — runs on the pipe ring.
    ``segment_ids`` [B, T] masks cross-document attention for packed
    batches; ids ride the ring with their microbatch's activations.
    """
    is_moe = _returns_aux(cfg)
    if mesh.shape["sequence"] != 1:
        raise NotImplementedError(
            "pipeline composes with data/fsdp/tensor/expert only for "
            f"now; mesh axis sequence has size {mesh.shape['sequence']}"
        )
    ep = mesh.shape[AXIS_EXPERT]
    if ep > 1:
        if not is_moe:
            raise NotImplementedError(
                f"mesh expert axis has size {ep} but {type(cfg).__name__}"
                " has no experts to shard over it"
            )
        if cfg.n_experts % ep:
            raise ValueError(
                f"mesh expert={ep} must divide n_experts="
                f"{cfg.n_experts} for pipelined expert parallelism"
            )
    tp = mesh.shape[AXIS_TENSOR]
    if tp > 1:
        # Megatron split: heads over q/k/v/o, ffn width over
        # gate/up/down. Uneven splits would silently mis-shard the
        # stacked weights. MLA has no kv heads (one shared latent,
        # replicated kernels); MLA-MoE shards moe_d_ff (routed stacks)
        # and the shared-expert width, never the dense d_ff (those
        # leaves don't exist in its stacks).
        checks = [("n_heads", cfg.n_heads)]
        if _is_mla(cfg) and cfg.moe:
            # moe_d_ff % tp also covers the shared-expert width
            # (n_shared * moe_d_ff) — no separate check needed.
            checks.append(("moe_d_ff", cfg.moe_d_ff))
        else:
            checks.append(("d_ff", cfg.d_ff))
        if not _is_mla(cfg):
            checks.append(("n_kv_heads", cfg.n_kv_heads))
        for fname, v in checks:
            if v % tp:
                raise ValueError(
                    f"mesh tensor={tp} must divide {fname}={v} "
                    f"for pipelined tensor parallelism"
                )
    if mesh.shape[AXIS_PIPE] != pipe.n_stages:
        # Without this, sharding a [S, ...] stack over a differently-sized
        # pipe axis silently drops (or duplicates) stages' layers.
        raise ValueError(
            f"PipelineConfig.n_stages={pipe.n_stages} but mesh pipe axis "
            f"has size {mesh.shape[AXIS_PIPE]}"
        )
    pipe.validate(cfg, tokens.shape[0])
    backend = backend or cfg.attention_backend
    b, t = tokens.shape
    m = pipe.n_microbatches
    dp = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
    if (b // m) % dp:
        raise ValueError(
            f"microbatch rows {b // m} (batch {b} / {m} microbatches) "
            f"not divisible over data x fsdp = {dp} devices"
        )

    x = _embed(params, tokens, cfg)  # [B, T, D]
    x = x.reshape(m, b // m, t, cfg.d_model)

    mb_spec = P(None, (AXIS_DATA, AXIS_FSDP), None, None)
    stage_specs = stage_partition_specs(params["stages"])
    local = partial(_gpipe_local, cfg=cfg, backend=backend)
    if segment_ids is None:
        hidden, aux = shard_map(
            local,
            mesh=mesh,
            in_specs=(stage_specs, mb_spec),
            out_specs=(mb_spec, P()),
            check_vma=False,
        )(params["stages"], x)
    else:
        seg = segment_ids.astype(jnp.int32).reshape(m, b // m, t)
        seg_spec = P(None, (AXIS_DATA, AXIS_FSDP), None)
        hidden, aux = shard_map(
            local,
            mesh=mesh,
            in_specs=(stage_specs, mb_spec, seg_spec),
            out_specs=(mb_spec, P()),
            check_vma=False,
        )(params["stages"], x, seg)
    hidden = hidden.reshape(b, t, cfg.d_model)

    out = (
        _final_norm(params, hidden, cfg)
        if return_hidden
        else _logits_epilogue(params, hidden, cfg)
    )
    if is_moe:
        return out, aux[0] / cfg.n_layers
    return out


def _head_kernel(params: dict) -> jax.Array:
    """[D, V] LM head: dedicated, or the transposed tied embedding."""
    return (
        params["head"] if "head" in params else params["embed"].T
    )


def _embed(params: dict, tokens: jax.Array, cfg) -> jax.Array:
    """Token embedding lookup incl. Gemma's sqrt(d) scaling — ONE copy
    for the pipelined and sequential forwards."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    if _is_gemma(cfg):
        x = x * jnp.asarray(
            math.sqrt(cfg.d_model), cfg.dtype
        ).astype(x.dtype)
    return x


def _final_norm(params: dict, hidden: jax.Array, cfg) -> jax.Array:
    """Final RMSNorm incl. Gemma's (1+w) offset — ONE copy for the
    logits epilogue and the return_hidden (chunked-CE) path."""
    fnorm = params["final_norm"]
    if _is_gemma(cfg):
        fnorm = fnorm + 1.0
    return rms_norm(hidden, fnorm, cfg.rms_eps)


def _logits_epilogue(params: dict, hidden: jax.Array, cfg) -> jax.Array:
    """final norm -> head -> optional soft-cap: ONE copy shared by the
    pipelined and sequential (parity-oracle) forwards."""
    h = _final_norm(params, hidden, cfg)
    logits = h.astype(jnp.float32) @ _head_kernel(params).astype(
        jnp.float32
    )
    cap = getattr(cfg, "final_logit_soft_cap", None)
    if cap is not None:
        from tpufw.ops.attention import tanh_soft_cap

        logits = tanh_soft_cap(logits, cap)
    return logits


def _gemma_pair_body(cfg, backend, seg, tp: bool = False):
    """The scanned local+global pair: ONE copy for the staged schedule
    and the sequential oracle."""

    def body(h, pair_p):
        h = _gemma_block(
            pair_p["local"], h, cfg, backend, seg, cfg.sliding_window, tp
        )
        h = _gemma_block(
            pair_p["global"], h, cfg, backend, seg, None, tp
        )
        return h, None

    return body


def reference_forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    backend: str = "xla",
    segment_ids: Optional[jax.Array] = None,
    group_rows: Optional[int] = None,
) -> jax.Array:
    """Sequential evaluation of the SAME params (no pipe axis) — the
    parity oracle for the schedule.

    For MoE configs, routing capacity is a per-group property: the
    schedule routes each (microbatch x data-shard) group of
    ``group_rows`` rows independently, so the oracle must group the
    same way to be bit-comparable (vmap over row groups). Returns
    (logits, aux) for MoE — aux meaned over groups, summed over
    layers, /n_layers — matching ``pipeline_forward``'s accounting.
    """
    b, t = tokens.shape
    x = _embed(params, tokens, cfg)
    flat = jax.tree.map(
        lambda a: a.reshape(-1, *a.shape[2:]), params["stages"]
    )
    seg = (
        None if segment_ids is None else segment_ids.astype(jnp.int32)
    )

    if _returns_aux(cfg):
        gr = group_rows or b
        if b % gr:
            raise ValueError(f"batch {b} not divisible by group_rows {gr}")
        moe_blk = _mla_moe_block if _is_mla(cfg) else _mixtral_block

        def run_group(xg, sg):
            def body(carry, layer_p):
                h, aux = carry
                h, a = moe_blk(layer_p, h, cfg, backend, sg)
                return (h, aux + a.astype(jnp.float32)), None

            (h, aux), _ = jax.lax.scan(
                body, (xg, jnp.zeros((), jnp.float32)), flat
            )
            return h, aux

        xg = x.reshape(b // gr, gr, t, cfg.d_model)
        if seg is None:
            hidden, aux = jax.vmap(lambda xx: run_group(xx, None))(xg)
        else:
            hidden, aux = jax.vmap(run_group)(
                xg, seg.reshape(b // gr, gr, t)
            )
        hidden = hidden.reshape(b, t, cfg.d_model)
        return (
            _logits_epilogue(params, hidden, cfg),
            jnp.mean(aux) / cfg.n_layers,
        )

    if _is_gemma(cfg):
        body = _gemma_pair_body(cfg, backend, seg)
    else:
        blk = _mla_block if _is_mla(cfg) else _block

        def body(h, layer_p):
            return blk(layer_p, h, cfg, backend, seg), None

    x, _ = jax.lax.scan(body, x, flat)
    return _logits_epilogue(params, x, cfg)


def pipeline_loss(
    params: dict,
    batch: dict | jax.Array,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype=None,
) -> jax.Array:
    """LM objective through the pipelined forward — the SAME shift +
    packed-batch masking as the flax trainer (shift_and_mask), so the
    two training paths can't diverge on what they optimize. ``batch``
    is {tokens [+ segment_ids, loss_mask]} (a bare token array is
    wrapped for back-compat)."""
    return pipeline_eval(
        params, batch, cfg, pipe, mesh, loss_chunk_size, loss_chunk_dtype
    )["loss"]


def pipeline_eval(
    params: dict,
    batch: dict | jax.Array,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype=None,
) -> dict:
    """Forward-only objective through the pipelined model:
    {loss, n_tokens} — the held-out-eval analog of ``pipeline_loss``
    (same shift/mask, no gradient), so PipelineTrainer.evaluate reports
    numbers directly comparable to the flax Trainer's. With
    ``loss_chunk_size`` the head runs inside the chunked-vocab CE
    (tpufw.ops.loss) and [B, T, V] logits never materialize."""
    from tpufw.train.trainer import cross_entropy_loss, shift_and_mask

    if not isinstance(batch, dict):
        batch = {"tokens": batch}
    inputs, targets, seg_in, mask = shift_and_mask(batch)
    aux = 0.0  # MoE router loss joins the objective, as in the flax path
    if loss_chunk_size:
        from tpufw.ops.loss import chunked_cross_entropy

        hidden = pipeline_forward(
            params, inputs, cfg, pipe, mesh, segment_ids=seg_in,
            return_hidden=True,
        )
        if _returns_aux(cfg):
            hidden, aux = hidden
        loss, n = chunked_cross_entropy(
            hidden, _head_kernel(params), targets, mask,
            chunk_size=loss_chunk_size,
            compute_dtype=loss_chunk_dtype or jnp.bfloat16,
            logits_soft_cap=getattr(cfg, "final_logit_soft_cap", None),
        )
        return {"loss": loss + aux, "n_tokens": n}
    logits = pipeline_forward(
        params, inputs, cfg, pipe, mesh, segment_ids=seg_in
    )
    if _returns_aux(cfg):
        logits, aux = logits
    loss, n = cross_entropy_loss(logits, targets, mask)
    return {"loss": loss + aux, "n_tokens": n}


def pipeline_train_step(
    params: dict,
    opt_state: Any,
    tokens: jax.Array,
    tx,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
) -> tuple[dict, Any, jax.Array]:
    """One SGD/AdamW step over the pipelined model (jit this)."""
    import optax

    loss, grads = jax.value_and_grad(pipeline_loss)(
        params, tokens, cfg, pipe, mesh
    )
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss
