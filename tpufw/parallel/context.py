"""Process-wide current-mesh registry.

Model code runs under ``jax.jit`` tracing and can't take a Mesh argument
through flax module signatures without plumbing it everywhere; the Trainer
(or user) registers the active mesh here and mesh-aware ops (ring attention)
pick it up. Explicit ``mesh=`` arguments always override.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from jax.sharding import Mesh

_current: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]) -> None:
    global _current
    _current = mesh


def current_mesh() -> Optional[Mesh]:
    return _current


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    global _current
    prev = _current
    _current = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _current = prev
