"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second long-context strategy next to ring attention (SURVEY.md §5
names both: "ring attention / all-to-all"). Where the ring rotates K/V
chunks around neighbor ICI links and merges online-softmax statistics,
Ulysses does two ``lax.all_to_all`` transposes: sequence-sharded
projections [B, T/P, H, D] become head-sharded [B, T, H/P, D], each
device runs ordinary FULL-sequence attention over its head group (any
local backend — the Pallas flash kernel included — unchanged), and one
reverse all-to-all restores sequence sharding.

Trade-offs vs the ring (why tpufw ships both):
- Ulysses comm volume is O(T·H·D/P) per all-to-all, independent of the
  number of steps — two collectives total, no per-chunk latency chain;
  the ring pays P ppermute rounds but each is neighbor-only traffic.
- Ulysses parallelism is capped by head count (P must divide the local
  head count); the ring scales to any P that divides T.
- Ulysses reuses the exact single-device attention kernel (simpler
  numerics: no cross-chunk softmax merging).

GQA: if the kv-head count doesn't divide by P, kv heads are repeated up
to the query head count before the swap (costs bandwidth; exact same
math — _repeat_kv is what single-device GQA attention does anyway).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from tpufw.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpufw.mesh.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQUENCE, AXIS_TENSOR
from tpufw.ops.attention import _repeat_kv, multi_head_attention
from tpufw.parallel.context import current_mesh


def _ulysses_local(
    q, k, v, *seg, axis_name, causal, backend, soft_cap, window
):
    """Per-device body. q: [B, T/P, Hl, D], k/v: [B, T/P, Kl, D] local
    shapes (Hl = heads already divided by any tensor sharding outside).
    ``seg`` is () or (qseg [B, T/P],)."""
    n = jax.lax.psum(1, axis_name)
    h, kh = q.shape[2], k.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses needs sequence-axis size {n} to divide the local "
            f"query head count {h}"
        )
    if kh % n:
        # GQA with too few kv heads for the swap: repeat up to H first.
        k = _repeat_kv(k, h // kh)
        v = _repeat_kv(v, h // kh)

    def swap(x):  # [B, T/P, H, D] -> [B, T, H/P, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    q_g, k_g, v_g = swap(q), swap(k), swap(v)
    seg_full = None
    if seg:
        # Every device needs the FULL-length segment ids for its heads.
        seg_full = jax.lax.all_gather(
            seg[0], axis_name, axis=1, tiled=True
        )

    out = multi_head_attention(
        q_g, k_g, v_g,
        causal=causal,
        segment_ids=seg_full,
        logits_soft_cap=soft_cap,
        sliding_window=window,
        backend=backend,
    )  # [B, T, H/P, D]
    # Reverse swap: back to [B, T/P, H, D].
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQUENCE,
    backend: Optional[str] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel attention via all-to-all. Global shapes
    q: [B,T,H,D], k/v: [B,S,K,D]; self-attention only (T == S), T must
    divide by the sequence-axis size, and H (after any tensor sharding)
    must divide by it too.

    ``backend`` is the LOCAL attention implementation each device runs on
    its head group ("xla" or "flash"); default picks flash on TPU for the
    causal path, xla elsewhere — mirroring ring_attention's choice.
    ``logits_soft_cap``/``sliding_window`` pass straight through to the
    local kernel: each device sees the FULL sequence for its heads, so
    Gemma-style capping and local attention need no extra handling here.
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError(
            "ulysses_attention needs a mesh: pass mesh= or register one "
            "via tpufw.parallel.context.use_mesh(...)"
        )
    if q.shape[1] != k.shape[1]:
        raise ValueError(
            f"ulysses attention is self-attention only: T={q.shape[1]} "
            f"!= S={k.shape[1]}"
        )
    if backend is None:
        on_tpu = mesh.devices.flatten()[0].platform == "tpu"
        backend = "flash" if (causal and on_tpu) else "xla"
    if backend not in ("xla", "flash"):
        raise ValueError(
            f"ulysses local backend must be 'xla' or 'flash', "
            f"got {backend!r}"
        )

    spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE, AXIS_TENSOR, None)
    seg_spec = P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE)
    local = functools.partial(
        _ulysses_local,
        axis_name=axis_name,
        causal=causal,
        backend=backend,
        soft_cap=logits_soft_cap,
        window=sliding_window,
    )
    if segment_ids is None:
        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, segment_ids.astype(jnp.int32))
