"""ZB-H1-style zero-bubble 1F1B: backward split into B and W phases.

1F1B's drain bubble exists because a stage's backward is one monolith:
stage s can't start microbatch j's backward until the cotangent
arrives, and while it waits it has NOTHING else to do. The zero-bubble
observation (PAPERS.md, "zero bubble" line of work; this is the H1
variant) is that only the INPUT-gradient half (B) of the backward is
on the critical path — the WEIGHT-gradient half (W) has no consumer
until the optimizer step, so it can be deferred into the ticks that
used to be bubble. Each schedule tick here runs three sub-ticks:

  F: forward of microbatch  jf = t - s            (stash input)
  B: input-grad of          jb = t - 2(S-1) + s   (dx -> ring, NOW)
  W: weight-grad of         jw = t - 3(S-1) + 2s  (local accumulate)

W for microbatch j on stage s runs S-1-s ticks AFTER its B — stage
S-1 runs them back-to-back (delay 0), stage 0 defers the longest —
which is exactly the deferral that fills stage 0's drain bubble with
useful weight-grad work. Weight-grad accumulation is purely local
(same masked-accumulator + epilogue reductions as 1F1B), so the
schedule adds ZERO communication: the same two ppermutes per tick,
issued with the same compute-overlap placement as ``pipeline_1f1b``.

Bookkeeping (S stages, M microbatches, ticks t = 0 .. M+3(S-1)-1):
  - activation stash: written at t = j+s, read by B at j+2(S-1)-s and
    again by W at j+3(S-1)-2s — lifetime <= 3(S-1), ring of 3S slots.
  - cotangent stash: B stores the OUTPUT cotangent it consumed so W
    can transpose the same stage against it; read S-1-s ticks later,
    ring of S slots (stage S-1 writes and reads the same slot within
    one tick; sub-tick order B-then-W makes that well-defined).
  - the last stage's F and B of a microbatch share a tick (in-region
    loss epilogue feeds B directly), as in 1F1B.
  - analytic bubble: per-device busy sub-slots 3M in the
    (S-1)/(3M+S-1) accounting pinned by tests — at most the
    interleaved schedule's (S-1)/(vM+S-1) for any v <= 3.

The honest trade on this full-remat substrate: B re-runs the stage
forward to get its VJP (the same remat 1F1B does), and W re-runs it
AGAIN — ``jax.vjp`` residuals can't ride the scan carry across ticks,
so splitting the transpose costs one extra forward recompute per
microbatch per stage (~25% more stage FLOPs at bwd ~ 2x fwd). zb1
buys its bubble shape with compute; interleaved buys it with
handoffs. PERF.md has the selection guidance.

Gradient exactness: identical discipline to ``pipeline_1f1b`` (the
split transpose computes the same two VJP factors, just on different
ticks); parity with GPipe+autodiff is pinned by
tests/test_pipeline_interleaved.py at the same tolerance. Scope:
``_check_1f1b`` envelope (Llama-family dense incl. Qwen biases,
data/fsdp x tensor), canonical ``[S, lps, ...]`` stage layout.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from tpufw.parallel.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpufw.mesh import AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_TENSOR
from tpufw.models.llama import LlamaConfig
from tpufw.parallel.pipeline import (
    PipelineConfig,
    stage_partition_specs,
)
from tpufw.parallel.pipeline_1f1b import (
    _VOCAB_REDUCE_AXES,
    _check_1f1b,
    _embed_fwd,
    _epilogue_loss,
    _stage_1f1b,
    vocab_scatter_plan,
)


def _zb1_local(
    stage_params,
    head_leaves,
    x_mb,
    tok_mb,
    tgt_mb,
    mask_mb,
    *seg_mb,
    cfg,
    backend,
    n_microbatches,
    loss_chunk_size,
    loss_chunk_dtype,
    vocab_scatter=False,
):
    """Per-device schedule body (inside shard_map); see module
    docstring for the three-phase tick algebra."""
    s = axis_size(AXIS_PIPE)
    sidx = jax.lax.axis_index(AXIS_PIPE)
    tp = axis_size(AXIS_TENSOR) > 1
    stage_params = jax.tree.map(lambda a: a[0], stage_params)
    m = n_microbatches
    d_model = x_mb.shape[-1]
    mb_shape = x_mb.shape[1:]
    fwd_perm = [(i, (i + 1) % s) for i in range(s)]
    bwd_perm = [(i, (i - 1) % s) for i in range(s)]
    has_seg = bool(seg_mb)
    seg_all = seg_mb[0] if has_seg else None
    n_slots = 3 * s  # activation ring (two readers, see docstring)

    def stage_fwd(p, x, seg):
        return _stage_1f1b(p, x, cfg, backend, seg, tp)

    vocab = head_leaves["head"].shape[-1]

    def tick(carry, t):
        (
            f_recv, dx_prev, stash, cot, loss_sum,
            g_stage, g_embed, g_fnorm, g_head,
        ) = carry
        jf = t - sidx                    # F microbatch
        jb = t - 2 * (s - 1) + sidx      # B microbatch
        jw = t - 3 * (s - 1) + 2 * sidx  # W microbatch
        f_on = (jf >= 0) & (jf < m)
        b_on = (jb >= 0) & (jb < m)
        w_on = (jw >= 0) & (jw < m)
        jf_c = jnp.clip(jf, 0, m - 1)
        jb_c = jnp.clip(jb, 0, m - 1)
        jw_c = jnp.clip(jw, 0, m - 1)

        # Cotangent handoff issued first — overlaps the F sub-tick.
        b_recv = jax.lax.ppermute(dx_prev, AXIS_PIPE, bwd_perm)

        # ---- F sub-tick -------------------------------------------
        x_in = jnp.where(sidx == 0, x_mb[jf_c], f_recv)
        seg_f = seg_all[jf_c] if has_seg else None
        y = stage_fwd(stage_params, x_in, seg_f)
        f_send = jax.lax.ppermute(y, AXIS_PIPE, fwd_perm)
        slot_f = jf_c % n_slots
        old_slot = jax.lax.dynamic_index_in_dim(
            stash, slot_f, 0, keepdims=False
        )
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_on, x_in, old_slot), slot_f, 0
        )

        def head_loss(hl, hidden):
            return _epilogue_loss(
                hl, hidden, tgt_mb[jf_c], mask_mb[jf_c], cfg,
                loss_chunk_size, loss_chunk_dtype,
            )

        is_last = sidx == s - 1
        take_loss = is_last & f_on

        def run_epilogue(hl, hidden):
            return jax.value_and_grad(head_loss, argnums=(0, 1))(
                hl, hidden
            )

        def skip_epilogue(hl, hidden):
            return (
                jnp.zeros((), jnp.float32),
                (
                    jax.tree.map(jnp.zeros_like, hl),
                    jnp.zeros_like(hidden),
                ),
            )

        loss_j, (g_hl_j, dy_j) = jax.lax.cond(
            take_loss, run_epilogue, skip_epilogue, head_leaves, y
        )
        loss_sum = loss_sum + loss_j
        g_fnorm = g_fnorm + g_hl_j["final_norm"]
        g_head = g_head + g_hl_j["head"]

        # ---- B sub-tick: input gradient only ----------------------
        g_in = jnp.where(is_last, dy_j.astype(x_in.dtype), b_recv)
        x_b = jax.lax.dynamic_index_in_dim(
            stash, jb_c % n_slots, 0, keepdims=False
        )
        seg_b = seg_all[jb_c] if has_seg else None
        _, vjp_x = jax.vjp(
            lambda xx: stage_fwd(stage_params, xx, seg_b), x_b
        )
        (dx_j,) = vjp_x(g_in)
        # Park the consumed output cotangent for this stage's W phase
        # (write-guarded: drain ticks clip jb onto a LIVE slot).
        slot_cb = jb_c % s
        old_cot = jax.lax.dynamic_index_in_dim(
            cot, slot_cb, 0, keepdims=False
        )
        cot = jax.lax.dynamic_update_index_in_dim(
            cot, jnp.where(b_on, g_in, old_cot), slot_cb, 0
        )
        g_embed = g_embed.at[tok_mb[jb_c]].add(
            jnp.where((sidx == 0) & b_on, dx_j, 0.0).astype(
                g_embed.dtype
            )
        )

        # ---- W sub-tick: weight gradient, deferred ----------------
        # Runs S-1-s ticks after the matching B — the deferral that
        # fills the drain bubble. Second forward recompute (see
        # docstring for why the VJP can't be split across ticks).
        x_w = jax.lax.dynamic_index_in_dim(
            stash, jw_c % n_slots, 0, keepdims=False
        )
        g_w = jax.lax.dynamic_index_in_dim(
            cot, jw_c % s, 0, keepdims=False
        )
        seg_w = seg_all[jw_c] if has_seg else None
        _, vjp_p = jax.vjp(
            lambda pp: stage_fwd(pp, x_w, seg_w), stage_params
        )
        (dp_j,) = vjp_p(g_w)
        g_stage = jax.tree.map(
            lambda acc, g: acc + jnp.where(w_on, g, 0.0),
            g_stage, dp_j,
        )

        return (
            f_send, dx_j, stash, cot, loss_sum,
            g_stage, g_embed, g_fnorm, g_head,
        ), None

    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)
    init = (
        zeros_mb,
        zeros_mb,
        jnp.zeros((n_slots, *mb_shape), x_mb.dtype),
        jnp.zeros((s, *mb_shape), x_mb.dtype),
        jnp.zeros((), jnp.float32),
        jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), stage_params
        ),
        jnp.zeros((vocab, d_model), jnp.float32),
        jnp.zeros(head_leaves["final_norm"].shape, jnp.float32),
        jnp.zeros(head_leaves["head"].shape, jnp.float32),
    )
    (
        _, _, _, _, loss_sum, g_stage, g_embed, g_fnorm, g_head
    ), _ = jax.lax.scan(tick, init, jnp.arange(m + 3 * (s - 1)))

    batch_axes = (AXIS_DATA, AXIS_FSDP)
    loss_sum = jax.lax.psum(loss_sum, (AXIS_PIPE, *batch_axes))
    g_fnorm = jax.lax.psum(g_fnorm, (AXIS_PIPE, *batch_axes))
    if vocab_scatter:
        g_embed = jax.lax.psum_scatter(
            g_embed, _VOCAB_REDUCE_AXES, scatter_dimension=0,
            tiled=True,
        )
        g_head = jax.lax.psum_scatter(
            g_head, _VOCAB_REDUCE_AXES, scatter_dimension=1,
            tiled=True,
        )
    else:
        g_embed = jax.lax.psum(g_embed, _VOCAB_REDUCE_AXES)
        g_head = jax.lax.psum(g_head, _VOCAB_REDUCE_AXES)
    g_stage = jax.tree.map(
        lambda g: jax.lax.psum(g, batch_axes), g_stage
    )
    g_stage = jax.tree.map(lambda g: g[None], g_stage)
    return loss_sum, g_stage, g_embed, g_fnorm, g_head


def pipeline_zb1_value_and_grad(
    params: dict,
    batch: dict | jax.Array,
    cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh: Mesh,
    backend: Optional[str] = None,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype=None,
) -> tuple[jax.Array, dict]:
    """(mean token loss, grads) through the zero-bubble H1 schedule —
    drop-in counterpart of ``pipeline_1f1b_value_and_grad`` (same
    canonical ``[S, ...]`` stage layout)."""
    from tpufw.train.trainer import shift_and_mask

    _check_1f1b(cfg, mesh)
    if mesh.shape[AXIS_PIPE] != pipe.n_stages:
        raise ValueError(
            f"PipelineConfig.n_stages={pipe.n_stages} but mesh pipe "
            f"axis has size {mesh.shape[AXIS_PIPE]}"
        )
    if not isinstance(batch, dict):
        batch = {"tokens": batch}
    inputs, targets, seg_in, mask = shift_and_mask(batch)
    pipe.validate(cfg, inputs.shape[0])
    backend = backend or cfg.attention_backend
    b, t = inputs.shape
    m = pipe.n_microbatches
    dp = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
    if (b // m) % dp:
        raise ValueError(
            f"microbatch rows {b // m} not divisible over "
            f"data x fsdp = {dp} devices"
        )
    if mask is None:
        mask = jnp.ones_like(targets, jnp.float32)

    x = _embed_fwd(params["embed"], inputs, cfg.dtype)
    mbd = lambda a: a.reshape(m, b // m, *a.shape[1:])  # noqa: E731
    head_leaves = {
        "final_norm": params["final_norm"],
        "head": params["head"],
    }

    row = (AXIS_DATA, AXIS_FSDP)
    mb4 = P(None, row, None, None)
    mb3 = P(None, row, None)
    stage_specs = stage_partition_specs(params["stages"])
    hl_specs = {"final_norm": P(), "head": P()}
    scatter, embed_spec, head_spec = vocab_scatter_plan(
        params["head"].shape[-1], mesh
    )
    local = partial(
        _zb1_local,
        cfg=cfg,
        backend=backend,
        n_microbatches=m,
        loss_chunk_size=loss_chunk_size,
        loss_chunk_dtype=loss_chunk_dtype,
        vocab_scatter=scatter,
    )
    args = [
        params["stages"], head_leaves, mbd(x), mbd(inputs),
        mbd(targets), mbd(mask.astype(jnp.float32)),
    ]
    in_specs = [stage_specs, hl_specs, mb4, mb3, mb3, mb3]
    if seg_in is not None:
        args.append(mbd(seg_in.astype(jnp.int32)))
        in_specs.append(mb3)
    loss_sum, g_stage, g_embed, g_fnorm, g_head = shard_map(
        local,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), stage_specs, embed_spec, P(), head_spec),
        check_vma=False,
    )(*args)

    n_tok = jnp.maximum(mask.sum(), 1.0)
    inv = (1.0 / n_tok).astype(jnp.float32)
    grads = {
        "embed": (g_embed * inv).astype(params["embed"].dtype),
        "stages": jax.tree.map(
            lambda g, p: (g * inv).astype(p.dtype),
            g_stage,
            params["stages"],
        ),
        "final_norm": (g_fnorm * inv).astype(
            params["final_norm"].dtype
        ),
        "head": (g_head * inv).astype(params["head"].dtype),
    }
    return loss_sum / n_tok, grads
