from tpufw.parallel.context import current_mesh, set_current_mesh, use_mesh  # noqa: F401
from tpufw.parallel.ring import ring_attention  # noqa: F401
from tpufw.parallel.ring_flash import ring_flash_attention  # noqa: F401
from tpufw.parallel.ulysses import ulysses_attention  # noqa: F401
