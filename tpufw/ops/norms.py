"""Normalization ops. RMSNorm is the Llama/Mixtral norm; computed in fp32."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm with fp32 accumulation, output cast back to x.dtype.

    XLA fuses this into neighbors on TPU; a Pallas fusion only pays off when
    combined with quantization, so the plain version is the default.
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)
