"""Pallas TPU flash attention (forward + backward), FlashAttention-2 style.

Replaces the O(T*S) materialized-logits attention with blockwise online
softmax in VMEM: per (batch, head, q-block) the kernel streams K/V blocks
from VMEM-resident [S, D] slabs, keeping running max/sum statistics. This is
the memory lever that lets the single-chip bench run larger batches (the
xla backend's [B, H, T, S] fp32 logits were the OOM driver) and the building
block the ring (sequence-parallel) backend reuses per shard.

Layout notes (see /opt/skills/guides/pallas_guide.md):
- blocks are (bq, D) / (bkv, D) with D=head_dim (128 for Llama) — lane dim
  aligned; bq/bkv are 128 multiples; inputs are padded to block multiples
  and masked via static-shape iota comparisons.
- GQA never materializes repeated K/V: the kv BlockSpec index_map divides
  the head index (h // rep) so all rep query heads stream the same slab.
- softmax statistics accumulate in fp32; matmuls request
  preferred_element_type=f32 so the MXU accumulates in fp32 from bf16 inputs.
- packed batches: int32 segment ids ([B, T] query-side, [B, S] key-side)
  stream alongside q/k and add a same-segment term to the mask, so the
  packed-corpus data path (tpufw.train.native_data emits segment_ids) keeps
  the flash kernel instead of falling back to materialized logits. Padded
  positions carry segment 0 on both sides; cross-segment and pad→real
  attention are both cut by the equality test.

Backward recomputes P from (q, k, lse) — the flash trick — in two kernels:
dq (grid over q blocks) and dk/dv (grid over kv blocks, per *query* head,
summed over the GQA group outside the kernel).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpufw.ops.attention import tanh_soft_cap

NEG_INF = -1e30

# Mosaic tiling: the last two dims of every block must be (divisible by 8,
# divisible by 128) or equal to the array dims. 2-D [B, T] segment-id
# arrays can't satisfy that (a (1, bq) block has sublane size 1), so they
# ship lanes/sublanes-broadcast — query ids as [B, T, LANES] blocks
# (bq, 128), kv ids as [B, SUBLANES, S] blocks (8, bkv) — the layout the
# official TPU flash kernel uses. Caught on real hardware in round 2: the
# CPU interpreter never enforces tiling, so tests alone missed it.
_LANES = 128
_SUBLANES = 8


def _qseg_lanes(qseg_p: jax.Array) -> jax.Array:
    b, t_p = qseg_p.shape
    return jnp.broadcast_to(qseg_p[:, :, None], (b, t_p, _LANES))


def _kseg_sublanes(kseg_p: jax.Array) -> jax.Array:
    b, s_p = kseg_p.shape
    return jnp.broadcast_to(kseg_p[:, None, :], (b, _SUBLANES, s_p))


def _seg_mask(qseg_block: jax.Array, kseg_row: jax.Array) -> jax.Array:
    """[bq, LANES] lanes-broadcast q ids x [1, bkv] kv ids -> [bq, bkv]."""
    bkv = kseg_row.shape[-1]
    return jnp.tile(qseg_block, (1, bkv // _LANES)) == kseg_row


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _causal_mask(i_block, j_block, bq, bkv, offset):
    """[bq, bkv] bool mask: query global pos (+offset) >= key global pos."""
    q_pos = i_block * bq + jax.lax.broadcasted_iota(
        jnp.int32, (bq, bkv), 0
    ) + offset
    k_pos = j_block * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    return q_pos >= k_pos


def _first_kv_block(i_block, bq, bkv, offset, window):
    """First kv block a sliding-window query block can see (0 without a
    window): the block holding position q_pos_min - window + 1. Blocks
    before it are fully masked — skipping them is where local attention's
    FLOP/bandwidth savings actually come from (the mask alone only zeroes
    already-done work)."""
    if window is None:
        return 0
    lo = i_block * bq + offset - window + 1
    return jnp.maximum(jax.lax.div(lo, bkv), 0)


def _window_mask(i_block, j_block, bq, bkv, offset, window):
    """[bq, bkv] bool mask: key within ``window`` positions of the query
    (sliding-window / local attention, Gemma-style)."""
    q_pos = i_block * bq + jax.lax.broadcasted_iota(
        jnp.int32, (bq, bkv), 0
    ) + offset
    k_pos = j_block * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    return (q_pos - k_pos) < window


def _fwd_kernel(
    *refs, bq, bkv, s_actual, causal, offset, scale, has_seg, soft_cap,
    window,
):
    if has_seg:
        q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref = refs
        qseg = qseg_ref[0]  # [bq, LANES]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        kseg_ref = qseg = None
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, D]
    n_kv = k_ref.shape[2] // bkv

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, 0, pl.ds(j * bkv, bkv), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * bkv, bkv), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bkv]
        if soft_cap is not None:
            # Applied before masking: cap(NEG_INF) would squash the mask.
            logits = tanh_soft_cap(logits, soft_cap)
        k_pos = j * bkv + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bkv), 1
        )
        mask = k_pos < s_actual
        if causal:
            mask = mask & _causal_mask(i, j, bq, bkv, offset)
        if window is not None:
            mask = mask & _window_mask(i, j, bq, bkv, offset, window)
        if has_seg:
            kseg = kseg_ref[0, :1, pl.ds(j * bkv, bkv)]  # [1, bkv]
            mask = mask & _seg_mask(qseg, kseg)
        logits = jnp.where(mask, logits, NEG_INF)
        m_cur = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc

    d = q_ref.shape[-1]
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # Only stream kv blocks that intersect the causal triangle.
        n_needed = jax.lax.div(
            (i + 1) * bq + offset + bkv - 1, bkv
        )
        n_iter = jnp.minimum(n_needed, n_kv)
    else:
        n_iter = n_kv
    j0 = _first_kv_block(i, bq, bkv, offset, window)
    m, l, acc = jax.lax.fori_loop(j0, n_iter, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0, 0] = (m + jnp.log(l_safe))[:, 0]


def _dq_kernel(
    *refs, bq, bkv, s_actual, causal, offset, scale, has_seg, soft_cap,
    window,
):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dq_ref) = refs
        qseg = qseg_ref[0]  # [bq, LANES]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref) = refs
        kseg_ref = qseg = None
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, 0][:, None]  # [bq, 1]
    delta = delta_ref[0, 0, 0][:, None]
    n_kv = k_ref.shape[2] // bkv

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * bkv, bkv), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * bkv, bkv), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        k_pos = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
        mask = k_pos < s_actual
        if causal:
            mask = mask & _causal_mask(i, j, bq, bkv, offset)
        if window is not None:
            mask = mask & _window_mask(i, j, bq, bkv, offset, window)
        if has_seg:
            kseg = kseg_ref[0, :1, pl.ds(j * bkv, bkv)]
            mask = mask & _seg_mask(qseg, kseg)
        if soft_cap is not None:
            capped = tanh_soft_cap(logits, soft_cap)
        else:
            capped = logits
        p = jnp.where(mask, jnp.exp(capped - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        if soft_cap is not None:
            # d(cap*tanh(x/cap))/dx = 1 - tanh^2 = 1 - (capped/cap)^2.
            ds = ds * (1.0 - (capped / soft_cap) ** 2)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    if causal:
        n_needed = jax.lax.div((i + 1) * bq + offset + bkv - 1, bkv)
        n_iter = jnp.minimum(n_needed, n_kv)
    else:
        n_iter = n_kv
    j0 = _first_kv_block(i, bq, bkv, offset, window)
    dq = jax.lax.fori_loop(
        j0, n_iter, body, jnp.zeros((bq, d), jnp.float32)
    )
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    *refs, bq, bkv, t_actual, causal, offset, scale, has_seg, soft_cap,
    window,
):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dk_ref, dv_ref) = refs
        kseg = kseg_ref[0, :1, :]  # [1, bkv]
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
        qseg_ref = kseg = None
    j = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)  # [bkv, D]
    v = v_ref[0, 0].astype(jnp.float32)
    n_q = q_ref.shape[2] // bq

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * bq, bq), :].astype(jnp.float32) * scale
        do = do_ref[0, 0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, 0, 0, pl.ds(i * bq, bq)][:, None]
        delta = delta_ref[0, 0, 0, pl.ds(i * bq, bq)][:, None]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
        mask = q_pos < t_actual
        if causal:
            mask = mask & _causal_mask(i, j, bq, bkv, offset)
        if window is not None:
            mask = mask & _window_mask(i, j, bq, bkv, offset, window)
        if has_seg:
            qseg = qseg_ref[0, pl.ds(i * bq, bq), :]  # [bq, LANES]
            mask = mask & _seg_mask(qseg, kseg)
        if soft_cap is not None:
            capped = tanh_soft_cap(logits, soft_cap)
        else:
            capped = logits
        p = jnp.where(mask, jnp.exp(capped - lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        if soft_cap is not None:
            ds = ds * (1.0 - (capped / soft_cap) ** 2)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    if causal:
        # q blocks strictly before this kv block never attend to it.
        first = jax.lax.div(j * bkv - offset, bq)
        i0 = jnp.maximum(first, 0)
    else:
        i0 = 0
    if window is not None:
        # q blocks entirely beyond the window never see this kv block:
        # the largest visible q_pos is (j+1)*bkv - 1 + window - 1.
        last_q = j * bkv + bkv - 1 + window - 1 - offset
        i_hi = jnp.minimum(jax.lax.div(last_q, bq) + 1, n_q)
        i_hi = jnp.maximum(i_hi, i0)  # never negative-length loops
    else:
        i_hi = n_q
    d = k_ref.shape[-1]
    dk0 = jnp.zeros((bkv, d), jnp.float32)
    dv0 = jnp.zeros((bkv, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(i0, i_hi, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _heads_layout(q, k, v):
    """[B,T,H,D] -> [B,H,T,D] for all three."""
    return (
        jnp.transpose(q, (0, 2, 1, 3)),
        jnp.transpose(k, (0, 2, 1, 3)),
        jnp.transpose(v, (0, 2, 1, 3)),
    )


def _env_block(name: str) -> int | None:
    from tpufw.workloads.env import env_opt_int

    return env_opt_int(name)


def _check_block(b: int, n_pad: int, axis: str, source: str) -> int:
    """Validate an explicit block-size override: the grid and the
    in-kernel kv loop both assume EXACT tiling of the padded length, and
    the lanes-broadcast segment masks assume 128 multiples."""
    if b % 128 or b <= 0:
        raise ValueError(
            f"flash {axis} block {b} (from {source}) must be a positive "
            "multiple of 128 (Mosaic lane tiling; segment masks "
            "broadcast in 128-lane tiles)"
        )
    if n_pad % b:
        raise ValueError(
            f"flash {axis} block {b} (from {source}) must divide the "
            f"padded sequence length {n_pad}; pick a 128-multiple "
            f"divisor of {n_pad} (e.g. {math.gcd(b, n_pad)})"
        )
    return b


def _block_sizes(t_pad, s_pad, override=None):
    """Block sizes for the (q, kv) grid. Default: the largest sizes
    (<=512) that DIVIDE the padded lengths — the grid and the in-kernel
    kv loop both assume exact tiling (inputs are padded to 128
    multiples, so 128 always divides).

    ``override`` is an explicit (bq, bkv) pair (either element None =
    heuristic); with no override the TPUFW_FLASH_BQ / TPUFW_FLASH_BKV
    env vars apply — the autotuner's lever (tpufw.tune), also usable
    standalone. Overrides are validated against the padded lengths with
    a clear error rather than silently mistiling."""

    def pick(n):
        for b in (512, 256, 128):
            if n % b == 0:
                return b
        return n  # n < 128 can't happen post-padding; defensive.

    bq, bkv = (override or (None, None))
    src_q, src_kv = "block_sizes kwarg", "block_sizes kwarg"
    if bq is None and (e := _env_block("flash_bq")) is not None:
        bq, src_q = e, "TPUFW_FLASH_BQ"
    if bkv is None and (e := _env_block("flash_bkv")) is not None:
        bkv, src_kv = e, "TPUFW_FLASH_BKV"
    bq = pick(t_pad) if bq is None else _check_block(bq, t_pad, "q", src_q)
    bkv = (
        pick(s_pad) if bkv is None else _check_block(bkv, s_pad, "kv", src_kv)
    )
    return bq, bkv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9)
)
def _flash(
    q, k, v, qseg, kseg, causal, interpret, soft_cap, window, block_sizes
):
    out, _ = _flash_fwd_impl(
        q, k, v, qseg, kseg, causal, interpret, soft_cap, window,
        block_sizes=block_sizes,
    )
    return out


def _flash_fwd_impl(
    q, k, v, qseg, kseg, causal, interpret, soft_cap, window=None,
    offset=None, block_sizes=None,
):
    """``offset``: query i sits at absolute position offset+i relative
    to the keys. Default s - t (decode alignment); ring attention passes
    the static chunk distance step*L so window masks see GLOBAL
    positions (tpufw.parallel.ring_flash)."""
    b, t, h, d = q.shape
    _, s, kh, _ = k.shape
    rep = h // kh
    scale = 1.0 / math.sqrt(d)
    if offset is None:
        offset = s - t
    has_seg = qseg is not None

    qh, kh_, vh = _heads_layout(q, k, v)
    t_pad_mult = 128
    qh = _pad_to(qh, 2, t_pad_mult)
    kh_ = _pad_to(kh_, 2, t_pad_mult)
    vh = _pad_to(vh, 2, t_pad_mult)
    t_p, s_p = qh.shape[2], kh_.shape[2]
    bq, bkv = _block_sizes(t_p, s_p, block_sizes)

    grid = (b, h, t_p // bq)
    kernel = functools.partial(
        _fwd_kernel,
        bq=bq,
        bkv=bkv,
        s_actual=s,
        causal=causal,
        offset=offset,
        scale=scale,
        has_seg=has_seg,
        soft_cap=soft_cap,
        window=window,
    )
    in_specs = [
        pl.BlockSpec(
            (1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)
        ),
        pl.BlockSpec(
            (1, 1, s_p, d), lambda b_, h_, i: (b_, h_ // rep, 0, 0)
        ),
        pl.BlockSpec(
            (1, 1, s_p, d), lambda b_, h_, i: (b_, h_ // rep, 0, 0)
        ),
    ]
    inputs = [qh, kh_, vh]
    if has_seg:
        # Pad with segment 0 == the padding segment on both sides.
        qseg_p = _pad_to(qseg.astype(jnp.int32), 1, t_pad_mult)
        kseg_p = _pad_to(kseg.astype(jnp.int32), 1, t_pad_mult)
        in_specs += [
            pl.BlockSpec(
                (1, bq, _LANES), lambda b_, h_, i: (b_, i, 0)
            ),
            pl.BlockSpec(
                (1, _SUBLANES, s_p), lambda b_, h_, i: (b_, 0, 0)
            ),
        ]
        inputs += [_qseg_lanes(qseg_p), _kseg_sublanes(kseg_p)]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, 1, bq), lambda b_, h_, i: (b_, h_, 0, i)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, t_p), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    out_bthd = jnp.transpose(out[:, :, :t, :], (0, 2, 1, 3))
    return out_bthd, (q, k, v, qseg, kseg, out_bthd, lse)


def _flash_bwd_impl(
    causal, interpret, soft_cap, window, res, g, offset=None,
    block_sizes=None,
):
    q, k, v, qseg, kseg, out, lse = res
    b, t, h, d = q.shape
    _, s, kh, _ = k.shape
    rep = h // kh
    scale = 1.0 / math.sqrt(d)
    if offset is None:
        offset = s - t
    has_seg = qseg is not None

    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, T, H]
    delta = jnp.transpose(delta, (0, 2, 1))[:, :, None, :]  # [B,H,1,T]

    qh, kh_, vh = _heads_layout(q, k, v)
    doh = jnp.transpose(g, (0, 2, 1, 3))
    qh = _pad_to(qh, 2, 128)
    kh_ = _pad_to(kh_, 2, 128)
    vh = _pad_to(vh, 2, 128)
    doh = _pad_to(doh, 2, 128)
    delta_p = _pad_to(delta, 3, 128)
    lse_p = lse  # stored padded in the residual
    t_p, s_p = qh.shape[2], kh_.shape[2]
    bq, bkv = _block_sizes(t_p, s_p, block_sizes)
    if has_seg:
        qseg_l = _qseg_lanes(_pad_to(qseg.astype(jnp.int32), 1, 128))
        kseg_s = _kseg_sublanes(_pad_to(kseg.astype(jnp.int32), 1, 128))

    # dq: grid over q blocks.
    dq_in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
        pl.BlockSpec(
            (1, 1, s_p, d), lambda b_, h_, i: (b_, h_ // rep, 0, 0)
        ),
        pl.BlockSpec(
            (1, 1, s_p, d), lambda b_, h_, i: (b_, h_ // rep, 0, 0)
        ),
        pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
        pl.BlockSpec(
            (1, 1, 1, bq), lambda b_, h_, i: (b_, h_, 0, i)
        ),
        pl.BlockSpec(
            (1, 1, 1, bq), lambda b_, h_, i: (b_, h_, 0, i)
        ),
    ]
    dq_inputs = [qh, kh_, vh, doh, lse_p, delta_p]
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec(
                (1, bq, _LANES), lambda b_, h_, i: (b_, i, 0)
            ),
            pl.BlockSpec(
                (1, _SUBLANES, s_p), lambda b_, h_, i: (b_, 0, 0)
            ),
        ]
        dq_inputs += [qseg_l, kseg_s]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            bq=bq,
            bkv=bkv,
            s_actual=s,
            causal=causal,
            offset=offset,
            scale=scale,
            has_seg=has_seg,
            soft_cap=soft_cap,
            window=window,
        ),
        grid=(b, h, t_p // bq),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, t_p, d), q.dtype),
        interpret=interpret,
    )(*dq_inputs)

    # dk/dv: grid over kv blocks, per *query* head; GQA-summed after.
    dkv_in_specs = [
        pl.BlockSpec((1, 1, t_p, d), lambda b_, h_, j: (b_, h_, 0, 0)),
        pl.BlockSpec(
            (1, 1, bkv, d), lambda b_, h_, j: (b_, h_ // rep, j, 0)
        ),
        pl.BlockSpec(
            (1, 1, bkv, d), lambda b_, h_, j: (b_, h_ // rep, j, 0)
        ),
        pl.BlockSpec((1, 1, t_p, d), lambda b_, h_, j: (b_, h_, 0, 0)),
        pl.BlockSpec((1, 1, 1, t_p), lambda b_, h_, j: (b_, h_, 0, 0)),
        pl.BlockSpec((1, 1, 1, t_p), lambda b_, h_, j: (b_, h_, 0, 0)),
    ]
    dkv_inputs = [qh, kh_, vh, doh, lse_p, delta_p]
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec(
                (1, t_p, _LANES), lambda b_, h_, j: (b_, 0, 0)
            ),
            pl.BlockSpec(
                (1, _SUBLANES, bkv), lambda b_, h_, j: (b_, 0, j)
            ),
        ]
        dkv_inputs += [qseg_l, kseg_s]
    dk_full, dv_full = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            bq=bq,
            bkv=bkv,
            t_actual=t,
            causal=causal,
            offset=offset,
            scale=scale,
            has_seg=has_seg,
            soft_cap=soft_cap,
            window=window,
        ),
        grid=(b, h, s_p // bkv),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bkv, d), lambda b_, h_, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda b_, h_, j: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_p, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s_p, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_inputs)

    dq = jnp.transpose(dq[:, :, :t, :], (0, 2, 1, 3))
    dk = dk_full[:, :, :s, :].reshape(b, kh, rep, s, d).sum(2)
    dv = dv_full[:, :, :s, :].reshape(b, kh, rep, s, d).sum(2)
    dk = jnp.transpose(dk, (0, 2, 1, 3)).astype(k.dtype)
    dv = jnp.transpose(dv, (0, 2, 1, 3)).astype(v.dtype)
    return dq, dk, dv, None, None


def _flash_fwd_rule(
    q, k, v, qseg, kseg, causal, interpret, soft_cap, window, block_sizes
):
    out, res = _flash_fwd_impl(
        q, k, v, qseg, kseg, causal, interpret, soft_cap, window,
        block_sizes=block_sizes,
    )
    return out, res


def _flash_bwd_rule(
    causal, interpret, soft_cap, window, block_sizes, res, g
):
    return _flash_bwd_impl(
        causal, interpret, soft_cap, window, res, g,
        block_sizes=block_sizes,
    )


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids=None,
    kv_segment_ids=None,
    logits_soft_cap: float | None = None,
    sliding_window: int | None = None,
    interpret: bool | None = None,
    block_sizes: tuple[int | None, int | None] | None = None,
) -> jax.Array:
    """Flash attention. q:[B,T,H,D], k/v:[B,S,K,D] -> [B,T,H,D].

    ``segment_ids`` ([B, T] int) masks cross-segment attention for packed
    batches; ``kv_segment_ids`` ([B, S]) defaults to ``segment_ids`` (which
    then requires T == S, the self-attention training path).
    ``logits_soft_cap`` applies Gemma-style ``cap * tanh(logits/cap)`` to
    the scaled logits inside the kernel (fwd and both bwd kernels),
    matching ``xla_attention``'s semantics.

    ``interpret=None`` auto-selects the Pallas interpreter on CPU backends
    (tests, dryruns); any accelerator backend gets the real Mosaic lowering.

    ``block_sizes`` is an explicit (bq, bkv) grid-block override for the
    fwd and both bwd pallas kernels (either element None keeps that
    axis's heuristic); unset, the TPUFW_FLASH_BQ / TPUFW_FLASH_BKV env
    vars apply. Values must be 128 multiples dividing the padded
    lengths — validated with a clear error. Default behavior (no kwarg,
    no env) is unchanged.
    """
    h, kh = q.shape[2], k.shape[2]
    if h % kh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kh}")
    qseg = segment_ids
    kseg = kv_segment_ids if kv_segment_ids is not None else segment_ids
    if (qseg is None) != (kseg is None):
        raise ValueError(
            "segment_ids and kv_segment_ids must be given together"
        )
    if qseg is not None and kv_segment_ids is None and (
        q.shape[1] != k.shape[1]
    ):
        raise ValueError(
            f"segment_ids without kv_segment_ids requires T==S "
            f"(self-attention); got T={q.shape[1]}, S={k.shape[1]}"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    cap = None if logits_soft_cap is None else float(logits_soft_cap)
    win = None if sliding_window is None else int(sliding_window)
    blocks = None if block_sizes is None else tuple(block_sizes)
    return _flash(q, k, v, qseg, kseg, causal, interpret, cap, win, blocks)
