"""Attention ops with switchable backends.

The reference has no compute ops at all (its workload is ``nvidia-smi``,
reference ``README.md:314``); attention exists here because BASELINE configs
3-5 are Llama/Mixtral training. Backends:

- ``"xla"``    — einsum softmax attention; XLA fuses it well and it runs
                 anywhere (CPU tests, dryruns). The correctness reference.
- ``"flash"``  — Pallas TPU flash-attention kernel (tpufw.ops.flash),
                 blockwise online-softmax in VMEM; long-seq memory O(T).
- ``"ring"``   — sequence-parallel ring attention over the ``sequence`` mesh
                 axis (tpufw.parallel.ring), for contexts longer than one
                 chip's HBM share.

All backends take [B, T, H, D] q and [B, S, K, D] k/v with K (kv heads)
dividing H (GQA: each kv head serves H//K query heads).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def tanh_soft_cap(x: jax.Array, cap: float) -> jax.Array:
    """Gemma-style logit soft-capping: cap * tanh(x / cap). The ONE
    implementation — the xla backend, the Pallas flash kernels, the
    chunked-CE loss, and the Gemma head all call this, so the numerics
    cannot drift between them."""
    return cap * jnp.tanh(x / cap)


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, K, D] -> [B, S, K*n_rep, D] by repeating each kv head."""
    if n_rep == 1:
        return x
    b, s, k, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, k, n_rep, d))
    return x.reshape(b, s, k * n_rep, d)


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    q_positions: Optional[jax.Array] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Reference softmax attention. q:[B,T,H,D], k/v:[B,S,K,D] -> [B,T,H,D].

    ``segment_ids`` ([B, T] int) masks cross-segment attention for packed
    sequences; ``kv_segment_ids`` ([B, S]) gives the key side its own ids
    when q and kv lengths differ (KV-cache decode — cached pad slots carry
    segment 0 and are never attended). ``q_positions`` ([B, T] int) are the
    queries' absolute positions in the S-long key axis for causal masking;
    default assumes queries are the final T positions. Softmax is computed
    in float32 regardless of input dtype — bf16 logits lose too much
    precision at long T. ``sliding_window`` masks keys more than that
    many positions behind the query (local attention).
    """
    b, t, h, d = q.shape
    _, s, kh, _ = k.shape
    if h % kh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kh}")
    k = _repeat_kv(k, h // kh)
    v = _repeat_kv(v, h // kh)

    scale = 1.0 / math.sqrt(d)
    # fp32 accumulation on the MXU: bf16 logits would already have lost the
    # precision the fp32 softmax is supposed to protect.
    logits = (
        jnp.einsum(
            "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    if logits_soft_cap is not None:
        logits = tanh_soft_cap(logits, logits_soft_cap)

    mask = None
    kpos = jnp.arange(s)[None, None, None, :]  # [1,1,1,S]
    if causal or sliding_window is not None:
        if q_positions is None:
            # Align query i with absolute position s-t+i.
            qpos = (jnp.arange(t) + (s - t))[None, None, :, None]
        else:
            qpos = q_positions[:, None, :, None]  # [B,1,T,1]
        if causal:
            mask = qpos >= kpos
        if sliding_window is not None:
            # Local attention (Gemma-style): only the last
            # ``sliding_window`` positions are visible.
            near = (qpos - kpos) < sliding_window
            mask = near if mask is None else (mask & near)
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None else segment_ids
        seg_mask = (
            segment_ids[:, None, :, None] == kv_seg[:, None, None, :]
        )
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def multi_head_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    q_positions: Optional[jax.Array] = None,
    logits_soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    backend: str = "xla",
) -> jax.Array:
    """Backend dispatcher — the single attention entry point for all models."""
    if backend == "xla":
        return xla_attention(
            q,
            k,
            v,
            causal=causal,
            segment_ids=segment_ids,
            kv_segment_ids=kv_segment_ids,
            q_positions=q_positions,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )
    if kv_segment_ids is not None or q_positions is not None:
        raise NotImplementedError(
            f"KV-cache decode (kv_segment_ids/q_positions) requires "
            f"backend='xla', got {backend!r}"
        )
    if backend == "flash":
        from tpufw.ops.flash import flash_attention

        return flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )
    if backend == "ring":
        from tpufw.parallel.ring import ring_attention

        return ring_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )
    if backend == "ulysses":
        from tpufw.parallel.ulysses import ulysses_attention

        return ulysses_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            logits_soft_cap=logits_soft_cap,
            sliding_window=sliding_window,
        )
    raise ValueError(f"unknown attention backend {backend!r}")
