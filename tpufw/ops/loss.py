"""Chunked-vocab cross-entropy: LM loss without the [B, T, V] fp32 tensor.

On a 16 GiB-HBM chip the fp32 logits for batch 8 x 2048 x 32k vocab are
2 GiB *before* the backward's matching gradient buffer — the single biggest
activation in the whole train step, and it caps the global batch (measured:
batch > 4 OOMs the bench config with full logits). The fix is the standard
TPU one: never materialize the full logits. The sequence axis is cut into
chunks inside a ``lax.scan`` whose (rematted) body computes one chunk's
logits on the MXU — bf16 inputs, fp32 accumulation via
``preferred_element_type`` — reduces it to per-token CE statistics, and
discards it; the backward pass recomputes each chunk's logits instead of
keeping them alive. Peak logits memory drops from O(T) to O(T / n_chunks)
at the cost of one extra head-matmul in the backward (a few % of model
FLOPs, bought back many times over by the larger batch it enables).

The reference has no ML layer at all (workload is ``nvidia-smi``, reference
``README.md:314``); this op serves BASELINE configs 3-5's training path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def token_cross_entropy(
    logits: jax.Array, targets: jax.Array, z_loss_weight: float = 1e-4
) -> jax.Array:
    """Per-token CE with z-loss, in fp32. [..., V] logits, [...] targets ->
    [...] ce. The ONE implementation of the LM objective's token term —
    both the full-logits loss (tpufw.train.trainer.cross_entropy_loss) and
    the chunked path below use it, so they cannot diverge.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    label = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce = logz - label
    if z_loss_weight:
        ce = ce + z_loss_weight * jnp.square(logz)
    return ce


def _chunk_stats(
    h, kernel, targets, z_loss_weight, compute_dtype, logits_soft_cap,
    logits_scale: float = 1.0,
):
    """CE statistics for one sequence chunk. h: [B, C, D], kernel: [D, V],
    targets: [B, C] -> per-token ce [B, C] (z-loss included).
    ``logits_scale`` applies after the cap (temperature, see
    chunked_token_logprob)."""
    logits = jnp.einsum(
        "bcd,dv->bcv",
        h.astype(compute_dtype),
        kernel.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    if logits_soft_cap is not None:
        from tpufw.ops.attention import tanh_soft_cap

        # Gemma final-logit soft-cap: elementwise, so it distributes over
        # chunks — parity with the model's full-logits forward.
        logits = tanh_soft_cap(logits, logits_soft_cap)
    if logits_scale != 1.0:
        logits = logits * logits_scale
    return token_cross_entropy(logits, targets, z_loss_weight)


def _chunk_seq(chunk_size: int, hidden, targets, mask):
    """Shared sequence-axis chunking: pad T up to a chunk multiple and
    reshape each array to [n_chunks, B, chunk, ...] for ``lax.scan`` —
    ONE implementation of the layout both chunked reductions scan over,
    so the padding semantics cannot diverge."""
    b, t, d = hidden.shape
    n_chunks = -(-t // chunk_size)
    pad = n_chunks * chunk_size - t
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    hs = hidden.reshape(b, n_chunks, chunk_size, d).swapaxes(0, 1)
    ts = targets.reshape(b, n_chunks, chunk_size).swapaxes(0, 1)
    ms = mask.reshape(b, n_chunks, chunk_size).swapaxes(0, 1)
    return hs, ts, ms


def chunked_cross_entropy(
    hidden: jax.Array,
    kernel: jax.Array,
    targets: jax.Array,
    mask: Optional[jax.Array] = None,
    z_loss_weight: float = 1e-4,
    chunk_size: int = 256,
    compute_dtype=jnp.bfloat16,
    logits_soft_cap: Optional[float] = None,
) -> tuple[jax.Array, jax.Array]:
    """Token CE from pre-head hidden states, chunked over the sequence axis.

    Args:
      hidden: [B, T, D] final hidden states (post final-norm).
      kernel: [D, V] LM-head kernel (for tied embeddings pass ``embed.T``).
      targets: [B, T] int token ids.
      mask: optional [B, T] float weights (0 drops a position).
      z_loss_weight: softmax-normalizer regularizer, matches
        ``cross_entropy_loss``.
      chunk_size: sequence positions per scan step; peak logits memory is
        ``B * chunk_size * V`` fp32.
      compute_dtype: head-matmul input dtype. bf16 is the MXU fast path
        (accumulation is always fp32); use fp32 for bit-exact parity with
        the unchunked loss.

    Returns:
      (mean loss over unmasked tokens, number of unmasked tokens).
    """
    b, t, _ = hidden.shape
    if mask is None:
        mask = jnp.ones((b, t), jnp.float32)
    hs, ts, ms = _chunk_seq(
        chunk_size, hidden, targets, mask.astype(jnp.float32)
    )

    @jax.checkpoint
    def body(carry, xs):
        h_c, t_c, m_c = xs
        ce = _chunk_stats(
            h_c, kernel, t_c, z_loss_weight, compute_dtype,
            logits_soft_cap,
        )
        ce_sum, n_sum = carry
        return (ce_sum + (ce * m_c).sum(), n_sum + m_c.sum()), None

    (ce_sum, n), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (hs, ts, ms),
    )
    n_safe = jnp.maximum(n, 1.0)
    return ce_sum / n_safe, n


def chunked_sequence_logprob(
    hidden: jax.Array,
    kernel: jax.Array,
    targets: jax.Array,
    mask: jax.Array,
    chunk_size: int = 256,
    compute_dtype=jnp.bfloat16,
    logits_soft_cap: Optional[float] = None,
) -> jax.Array:
    """Per-ROW sum of target-token log-probabilities, chunked like
    ``chunked_cross_entropy`` (same scan, same memory bound), but
    reduced per sequence instead of over the whole batch and WITHOUT
    z-loss — preference objectives (tpufw.train.dpo) need the pure
    ``sum_t log pi(y_t | x_<t)`` of each response, not a regularized
    batch mean.

    Args:
      hidden: [B, T, D] final hidden states (post final-norm).
      kernel: [D, V] LM-head kernel.
      targets: [B, T] int token ids (already shifted).
      mask: [B, T] float weights; positions with 0 don't contribute.

    Returns:
      [B] fp32 masked log-prob sums.
    """
    b = hidden.shape[0]
    hs, ts, ms = _chunk_seq(
        chunk_size, hidden, targets, mask.astype(jnp.float32)
    )

    @jax.checkpoint
    def body(carry, xs):
        h_c, t_c, m_c = xs
        # ce with z_loss_weight=0 is exactly -log p(target).
        nll = _chunk_stats(
            h_c, kernel, t_c, 0.0, compute_dtype, logits_soft_cap
        )
        return carry - (nll * m_c).sum(axis=-1), None

    sums, _ = lax.scan(body, jnp.zeros((b,), jnp.float32), (hs, ts, ms))
    return sums


def chunked_token_logprob(
    hidden: jax.Array,
    kernel: jax.Array,
    targets: jax.Array,
    chunk_size: int = 256,
    compute_dtype=jnp.bfloat16,
    logits_soft_cap: Optional[float] = None,
    logits_scale: float = 1.0,
) -> jax.Array:
    """PER-TOKEN target log-probabilities [B, T], chunked like
    ``chunked_cross_entropy`` (no z-loss). Policy-gradient objectives
    (tpufw.train.grpo) need every token's log-prob for importance
    ratios — a [B, T] fp32 output is tiny next to the [B, C, V] chunk
    logits this scan never keeps alive.

    ``logits_scale`` (= 1/sampling_temperature) is applied AFTER the
    soft cap, matching the decode path's order exactly: the model caps
    its own final logits, then ``sample_token`` divides by temperature
    (tpufw.infer.sampling) — so these log-probs are the behavior
    policy's.
    """
    b, t, _ = hidden.shape
    ones = jnp.ones((b, t), jnp.float32)
    hs, ts, _ = _chunk_seq(chunk_size, hidden, targets, ones)

    @jax.checkpoint
    def body(_, xs):
        h_c, t_c = xs
        nll = _chunk_stats(
            h_c, kernel, t_c, 0.0, compute_dtype, logits_soft_cap,
            logits_scale,
        )
        return None, -nll  # [B, C] per-chunk logp

    _, chunks = lax.scan(body, None, (hs, ts))
    # [n_chunks, B, C] -> [B, n_chunks * C], drop the chunk padding.
    return chunks.swapaxes(0, 1).reshape(b, -1)[:, :t]
