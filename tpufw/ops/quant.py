"""Weight-only int8 quantization for serving.

Autoregressive decode is HBM-bandwidth-bound: every step streams every
weight once to produce one token per sequence. Storing projection
weights as int8 + a per-output-channel fp scale halves the bytes moved
(vs bf16), which is the first-order decode-throughput lever on TPU; the
matmul itself still runs in the activation dtype (the int8->bf16 cast
and the scale multiply fuse into the surrounding ops under XLA).

Scope: the projection kernels per block (attention q/k/v/o, MLA's
q_a/q_b/kv_a, MLP gate/up/down), the dedicated LM head, and the raw
expert stacks of Mixtral (``moe`` scope) and DeepSeek (``routed``
scope) — routers and MLA's small kv_b latent up-projection stay fp. Embeddings stay full precision (a gather, and for tied
heads the two uses want incompatible scale granularities).
Per-OUTPUT-channel symmetric scales keep the quantization error
independent per output unit, and scaling AFTER the contraction is
algebraically exact for that granularity.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

#: projection module name -> number of CONTRACTED (input) dims of its
#: kernel; remaining trailing dims are output channels. Extra LEADING
#: dims (nn.scan layer stacks, Gemma pair stacks) are batch dims.
_PROJ_IN_DIMS = {
    "q": 1, "k": 1, "v": 1, "o": 2,
    # MLA (deepseek): compressed-q pair and the packed KV-latent
    # down-projection; the latent up-projection (kv_b_kernel, a raw
    # array) stays fp — it is tiny and the absorbed decode contracts
    # its halves separately.
    "q_a": 1, "q_b": 1, "kv_a": 1,
    "gate": 1, "up": 1, "down": 1,
    # The dedicated LM head ([D, V]) is the largest single matmul a
    # decode step streams; tied (Gemma) embeddings stay fp — the gather
    # and the attend contraction want incompatible scale granularities.
    "lm_head": 1,
}
#: unstacked kernel rank per module (leading dims beyond this = stacks).
_PROJ_RANK = {
    "q": 3, "k": 3, "v": 3, "o": 3,
    "q_a": 2, "q_b": 3, "kv_a": 2,
    "gate": 2, "up": 2, "down": 2,
    "lm_head": 2,
}
#: Mixtral expert stacks: RAW [E, in, out] arrays (not {kernel} modules)
#: named w_* inside the moe scope; input dim is always axis -2, scale is
#: per (expert, out-channel). The router stays fp (tiny).
_EXPERT_KEYS = {"w_gate", "w_up", "w_down"}


def quantize_kernel(w: jax.Array, in_axes: tuple) -> dict:
    """[*stack, *in, *out] fp kernel -> {"q_kernel" int8, "scale" fp32}
    with per-output-channel symmetric scales (reduced over ``in_axes``;
    scale shape = the remaining dims)."""
    amax = jnp.max(jnp.abs(w), axis=in_axes, keepdims=False)
    scale = (amax / 127.0 + 1e-12).astype(jnp.float32)
    # Broadcast scale back across the reduced axes for the division.
    bshape = list(w.shape)
    for ax in in_axes:
        bshape[ax] = 1
    q = jnp.clip(
        jnp.round(w / scale.reshape(bshape)), -127, 127
    ).astype(jnp.int8)
    return {"q_kernel": q, "scale": scale}


def quantize_params(params: Any) -> Any:
    """Walk a decoder param tree and replace every projection kernel
    with its int8 form ({"q_kernel", "scale"} in place of {"kernel"}).
    Handles plain, nn.scan-stacked, and Gemma pair-stacked layouts.
    Raises if the tree carries LoRA adapters (merge first)."""
    from flax.linen import meta

    from tpufw.models.lora import has_lora

    # Trees straight out of ``model.init`` carry flax AxisMetadata boxes
    # (LogicallyPartitioned) around each leaf; unbox (identity on raw
    # trees) so the walk below sees arrays. The quantized tree is raw —
    # the quant modules re-declare their own logical partitioning.
    params = meta.unbox(params)

    if has_lora(params):
        raise ValueError(
            "quantize_params on a LoRA tree: run merge_lora first "
            "(adapters must fold into the kernels they modify)"
        )
    hit = []

    def walk(node, parent=""):
        if not isinstance(node, dict):
            return node
        out = {}
        for key, val in node.items():
            if (
                key in _PROJ_IN_DIMS
                and isinstance(val, dict)
                and "kernel" in val
                and set(val) <= {"kernel", "bias"}
            ):
                w = val["kernel"]
                n_in = _PROJ_IN_DIMS[key]
                n_stack = w.ndim - _PROJ_RANK[key]
                in_axes = tuple(range(n_stack, n_stack + n_in))
                out[key] = quantize_kernel(w, in_axes)
                if "bias" in val:
                    # Qwen qkv bias: tiny, stays fp (the kernel carries
                    # the bandwidth; QuantDenseGeneral adds it back).
                    out[key]["bias"] = val["bias"]
                hit.append(key)
            elif (
                key in _EXPERT_KEYS
                and parent in ("moe", "routed")
                and not isinstance(val, dict)
                and getattr(val, "ndim", 0) >= 3
            ):
                # [*stack, E, in, out] expert stack (nn.scan adds a
                # leading layer dim) -> int8 + per-(…, E, out) scales
                # (tpufw.models.mixtral.QuantExpertKernel's shapes).
                # Gated on the 'moe' parent scope: the functional
                # pipeline params carry same-named DENSE stacks that
                # must stay untouched.
                out[key] = quantize_kernel(val, (val.ndim - 2,))
                hit.append(key)
            else:
                out[key] = walk(val, parent=key)
        return out

    quantized = walk(params)
    if not hit:
        raise ValueError(
            "quantize_params: no projection kernels found (expected "
            f"modules named {sorted(_PROJ_IN_DIMS)})"
        )
    return quantized


def quantize_kv(kv: jax.Array, n_feat: int = 1) -> tuple:
    """Per-token symmetric int8 quantization for KV-cache appends.

    ``kv`` is [..., *feat]: the trailing ``n_feat`` dims are the feature
    block quantized together (llama K/V: (heads, head_dim) -> n_feat=2;
    MLA latents: (rank,) -> n_feat=1); every leading dim keeps its own
    scale. Returns (q int8, scale fp32) with ``scale`` shaped like the
    leading dims — the paged pool stores scales page-structured
    ([n_pages, page_size]), one scale per token slot per page, so
    appends are pure scatters (no running-amax requantization of
    already-resident tokens)."""
    axes = tuple(range(kv.ndim - n_feat, kv.ndim))
    amax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=axes)
    scale = (amax / 127.0 + 1e-12).astype(jnp.float32)
    bshape = scale.shape + (1,) * n_feat
    q = jnp.clip(
        jnp.round(kv.astype(jnp.float32) / scale.reshape(bshape)),
        -127, 127,
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of ``quantize_kv``: int8 codes x broadcast fp32 scales,
    accumulated in fp32 and cast to the activation ``dtype`` at the end
    (the cast and multiply fuse into the attention reads under XLA —
    HBM only ever streams the int8 bytes plus one fp32 per token)."""
    n_feat = q.ndim - scale.ndim
    bshape = scale.shape + (1,) * n_feat
    return (q.astype(jnp.float32) * scale.reshape(bshape)).astype(dtype)


def quant_contract(
    x: jax.Array, q_kernel: jax.Array, scale: jax.Array, n_in: int
) -> jax.Array:
    """x ⋅ dequant(kernel): contract x's trailing ``n_in`` dims with the
    kernel's input dims, then apply the per-output-channel scale. The
    int8->activation-dtype cast happens here, fused by XLA — HBM only
    ever streams the int8 bytes."""
    w = q_kernel.astype(x.dtype)
    y = jnp.tensordot(
        x, w,
        axes=(tuple(range(x.ndim - n_in, x.ndim)), tuple(range(n_in))),
    )
    return y * scale.astype(x.dtype)
