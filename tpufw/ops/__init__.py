from tpufw.ops.attention import multi_head_attention, xla_attention  # noqa: F401
from tpufw.ops.loss import chunked_cross_entropy  # noqa: F401
from tpufw.ops.norms import rms_norm  # noqa: F401
